"""Drift observatory + online plan adaptation — ROADMAP item 5 closed.

The autotuner (tune.autotune) resolves codec/depth/bucket/topology ONCE
at trainer construction from banked artifacts; the obs metrics plane
measures real per-stage times every step.  Until now those two halves
never talked at runtime: a job that landed on a mesh whose effective
link rate disagrees with the roofline — SparCML's codec break-even
moving with the wire (arXiv:1802.08021), EQuARX's regime-dependent
quantized-collective wins (arXiv:2506.17615) — kept running the stale
plan forever.  This module closes the loop, in four pieces:

  live calibration   ``live_calibrate`` runs the existing microbenches
                     (a timed explicit-ring all-reduce, per-codec
                     encode/decode stages) ON THE REAL MESH at trainer
                     startup and overlays the measured rates at the
                     `live` provenance tier (tune.calibration.apply_live
                     — above every banked artifact, dryrun-flagged on a
                     CPU mesh, source strings prefixed ``live:``).
  attribution        ``Attribution`` joins each step's MEASURED wall
                     time against the active plan's MODELED stage times
                     (ring_cost roofline: stream / overhead /
                     collective) into per-stage residuals, streamed as
                     ``tune.drift.*`` metrics (MetricsSink + EventStream
                     counters) and as spans on the Perfetto
                     "attribution" lane (obs.timeline).  The attribution
                     assumption is explicit: the warmup-median step time
                     minus the modeled collective is the compute
                     baseline, so sustained excess is attributed to the
                     collective stage — exactly the stage the candidate
                     plans differ in.
  detection          ``DriftDetector``: two-sided CUSUM over the EWMA'd
                     relative residual with hysteresis (post-trip
                     cooldown) — a spike is absorbed, a SUSTAINED shift
                     trips.  Pure host-side Python over banked metrics;
                     nothing here is visible to jax tracing (R2/R4).
  adaptation         ``AdaptiveTrainer``: the bounded candidate set
                     (tune.tune_topk — the argmin winner + best
                     runner-ups from distinct wire-format groups) is
                     built AND traced up front; on a detected shift the
                     candidates are re-priced under the measured
                     effective link rate and the argmin is installed AT
                     A STEP BOUNDARY.  A switch causes ZERO new traces
                     (counted via DPTrainer.step_traces/gather_traces,
                     frozen as graftlint J13 — the J10 counted-trace
                     discipline applied to training); every switch is an
                     ``adapt.switch`` event carrying (from_plan,
                     to_plan, step, residual evidence), banked by
                     tools/adapt_bench.py and regression-gated by
                     obs-gate ``adapt.*`` keys.

Switch semantics: candidates sharing the active plan's codec (and hence
flat layout) switch by PASSING THE STATE THROUGH UNTOUCHED — bitwise
identity on the gradient path by construction.  A codec switch re-pads
the masters/moments onto the target layout (fused_update.repad_flat,
value-exact — the checkpoint-restore discipline) and re-zeros the EF
residual (the same self-healing rule restore applies); codec switches
are admissible because every registered codec already rides the
convergence smoke battery (tests/test_codec.py).  docs/TUNING.md
carries the full contract.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .autotune import TunedPlan, needs_autotune, score_candidate, tune_topk
from .calibration import Calibration, CodecRates, apply_live, \
    load_calibration
# the CUSUM step function itself lives with the other control-plane
# rules (verify.opstream.SchedEmitter) so graftsched explores the exact
# arithmetic the detector runs; delegation pinned in tests/test_sched.py
from ..verify.opstream import SCHED_RULES as _SCHED_RULES

__all__ = [
    "live_calibrate", "measure_ring_gbps", "Attribution", "DriftDetector",
    "AdaptiveController", "AdaptiveTrainer", "SwitchDecision",
]

_EPS_GBPS = 1e-4        # floor for the effective-rate estimate


# ---------------------------------------------------------------------------
# live calibration (the `live` tier — run at trainer startup)
# ---------------------------------------------------------------------------

def _best_of(fn: Callable[[], None], repeats: int) -> float:
    """Best-of-N wall time of ``fn`` (which must block) — the standard
    microbench discipline: the minimum is the least-perturbed sample."""
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def measure_ring_gbps(mesh: Any, axis_name: str = "dp", *,
                      payload_elems: int = 1 << 16,
                      repeats: int = 2) -> Tuple[float, float]:
    """(per-direction GB/s, seconds) of one uncompressed explicit-ring
    all-reduce of an [payload_elems] f32 payload on the LIVE mesh — the
    startup upgrade of the single-chip-loopback inter-rate proxy: the
    same ring program the trainers run, timed where the job actually
    landed.  Rate = the per-device wire bytes the ring's own accounting
    declares (ops.ring.wire_bytes_per_device) over the best-of wall
    time."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..ops import ring as ring_ops

    n = int(mesh.shape[axis_name])
    L = payload_elems + (-payload_elems) % max(n, 1)
    fn = jax.jit(jax.shard_map(
        lambda x: ring_ops.ring_all_reduce(x, axis_name),
        mesh=mesh, in_specs=P(axis_name), out_specs=P(axis_name),
        check_vma=False))
    x = jnp.ones((L,), jnp.float32)
    jax.block_until_ready(fn(x))        # compile outside the timed window
    t = _best_of(lambda: jax.block_until_ready(fn(x)), repeats)
    wire = ring_ops.wire_bytes_per_device(L, n, None)
    return (wire / t / 1e9 if t > 0 else 0.0), t


def _measure_codec_rates(payload_elems: int, repeats: int,
                         dryrun: bool) -> Dict[str, Dict[str, CodecRates]]:
    """Per-registered-codec encode/decode stage rates measured live —
    the codec half of the startup microbench sweep.  Raw f32 bytes over
    the best-of stage wall time; both payload classes get the same row
    (one mesh, one placement — the class split is a banked-artifact
    refinement this startup probe does not pretend to have)."""
    import jax
    import jax.numpy as jnp
    from ..compress import available_codecs, get_codec

    out: Dict[str, Dict[str, CodecRates]] = {}
    for name in available_codecs():
        codec = get_codec(name)
        L = payload_elems + (-payload_elems) % codec.pad_elems
        x = jnp.ones((L,), jnp.float32)
        enc_fn = jax.jit(codec.encode)
        payload = jax.block_until_ready(enc_fn(x))
        dec_fn = jax.jit(lambda p: codec.decode(p, L, jnp.float32))
        jax.block_until_ready(dec_fn(payload))
        t_enc = _best_of(lambda: jax.block_until_ready(enc_fn(x)), repeats)
        t_dec = _best_of(lambda: jax.block_until_ready(dec_fn(payload)),
                         repeats)
        if t_enc <= 0 or t_dec <= 0:
            continue                    # never fabricate a rate
        raw = L * 4
        rates = CodecRates(raw / t_enc / 1e9, raw / t_dec / 1e9,
                           "live startup microbench", dryrun)
        out[name] = {"vmem": rates, "streaming": rates}
    return out


def live_calibrate(mesh: Any, axis_name: str = "dp", *,
                   base: Optional[Calibration] = None,
                   payload_elems: int = 1 << 16,
                   repeats: int = 2,
                   measure_codecs: bool = True) -> Calibration:
    """First-step self-calibration: run the startup microbenches on the
    real mesh and overlay the measured rates onto the banked calibration
    at the `live` tier.  Provenance is honest by construction
    (calibration.apply_live): sources read ``live: ...``, ``*_live``
    flags are set, and a CPU mesh marks every live rate dryrun-class —
    better than any banked proxy for THIS machine, but still not a TPU
    measurement."""
    import jax
    base = base if base is not None else load_calibration()
    plat = jax.devices()[0].platform
    dryrun = plat != "tpu"
    gbps, t = measure_ring_gbps(mesh, axis_name,
                                payload_elems=payload_elems,
                                repeats=repeats)
    codec_rates = (_measure_codec_rates(payload_elems, repeats, dryrun)
                   if measure_codecs else None)
    n = int(mesh.shape[axis_name])
    return apply_live(
        base, inter_gbps=gbps if gbps > 0 else None,
        codec_rates=codec_rates, dryrun=dryrun,
        source=f"ring all-reduce microbench on the {plat} mesh "
               f"(n={n}, {payload_elems} elems, best of {repeats})")


# ---------------------------------------------------------------------------
# attribution: modeled vs measured, per stage
# ---------------------------------------------------------------------------

class Attribution:
    """Joins measured step wall times against the active plan's modeled
    stage times into per-stage residuals.

    Model (docs/TUNING.md "The attribution contract"): the ring_cost
    roofline prices the COLLECTIVE (stream + overhead); compute is not
    modeled.  The first ``warmup_steps`` observations establish the
    measured baseline (median), and ``compute_s`` is defined as
    baseline - modeled collective (floored at 0).  Thereafter each
    step's excess over the baseline is attributed to the collective
    stage — the stage the candidate plans differ in, and the one a
    regime shift on the wire moves.  Every observation yields a record
    with the raw join (measured, modeled, excess, relative residual,
    EWMA'd residual) so the ``tune.drift.*`` stream carries facts, not
    conclusions."""

    def __init__(self, modeled: Dict[str, float], *,
                 warmup_steps: int = 3, ewma_alpha: float = 0.25) -> None:
        from ..obs.metrics import Ewma
        assert warmup_steps >= 1, warmup_steps
        self.modeled = dict(modeled)        # stream_s/overhead_s/collective_s
        self.warmup_steps = int(warmup_steps)
        self._warm: List[float] = []
        self.baseline_step_s: Optional[float] = None
        self.compute_s: Optional[float] = None
        self._alpha = ewma_alpha
        self.resid_rel = Ewma(ewma_alpha)
        self.excess_s = Ewma(ewma_alpha)
        self.n_observed = 0

    def rebase(self, modeled: Optional[Dict[str, float]] = None) -> None:
        """Forget the baseline (after a plan switch: the new plan has a
        new modeled collective AND a new steady step time) and re-enter
        warmup."""
        from ..obs.metrics import Ewma
        if modeled is not None:
            self.modeled = dict(modeled)
        self._warm = []
        self.baseline_step_s = None
        self.compute_s = None
        self.resid_rel = Ewma(self._alpha)
        self.excess_s = Ewma(self._alpha)

    @property
    def warmed_up(self) -> bool:
        return self.baseline_step_s is not None

    def observe(self, step_s: float) -> Optional[Dict[str, float]]:
        """One measured step.  Returns the residual record, or None
        while the baseline is still warming up."""
        import statistics
        self.n_observed += 1
        step_s = float(step_s)
        if self.baseline_step_s is None:
            self._warm.append(step_s)
            if len(self._warm) < self.warmup_steps:
                return None
            self.baseline_step_s = float(statistics.median(self._warm))
            self.compute_s = max(
                0.0, self.baseline_step_s - self.modeled["collective_s"])
            return None
        excess = step_s - self.baseline_step_s
        rel = excess / max(self.baseline_step_s, 1e-12)
        return {
            "step_s": step_s,
            "baseline_step_s": self.baseline_step_s,
            "compute_s": self.compute_s or 0.0,
            "modeled_collective_s": self.modeled["collective_s"],
            "modeled_stream_s": self.modeled.get("stream_s", 0.0),
            "modeled_overhead_s": self.modeled.get("overhead_s", 0.0),
            "collective_excess_s": excess,
            "measured_collective_s":
                max(0.0, self.modeled["collective_s"] + excess),
            "resid_rel": rel,
            "resid_rel_ewma": self.resid_rel.update(rel),
            "excess_s_ewma": self.excess_s.update(excess),
        }


# ---------------------------------------------------------------------------
# detection: CUSUM with hysteresis
# ---------------------------------------------------------------------------

class DriftDetector:
    """Two-sided CUSUM over the per-step relative residual: a sustained
    shift accumulates past ``threshold`` and trips; a one-step spike of
    magnitude below ``threshold + drift_rel`` cannot.  ``drift_rel`` is
    the CUSUM slack — residual magnitude below it DRAINS the statistic,
    so the detector self-resets through calm stretches.  Hysteresis:
    after a trip the detector disarms for ``cooldown_steps`` (the
    switch's own re-baselining happens in that window), preventing
    flapping between two plans that score within noise of each other."""

    def __init__(self, *, drift_rel: float = 0.75,
                 threshold: float = 3.0,
                 cooldown_steps: int = 8) -> None:
        assert drift_rel > 0 and threshold > 0
        self.drift_rel = float(drift_rel)
        self.threshold = float(threshold)
        self.cooldown_steps = int(cooldown_steps)
        self.pos = 0.0      # sustained SLOWER-than-baseline drift
        self.neg = 0.0      # sustained FASTER-than-baseline drift
        self.cooldown = 0
        self.trips = 0

    def reset(self, *, cooldown: bool = True) -> None:
        self.pos = self.neg = 0.0
        if cooldown:
            self.cooldown = self.cooldown_steps

    def update(self, resid_rel: float) -> Optional[Tuple[str, float]]:
        """One residual observation -> None, or ("slow"|"fast", stat) on
        a sustained-shift trip."""
        self.pos, self.neg, self.cooldown, trip = \
            _SCHED_RULES.cusum_step(
                self.pos, self.neg, self.cooldown, float(resid_rel),
                self.drift_rel, self.threshold, self.cooldown_steps)
        if trip is not None:
            self.trips += 1
        return trip


@dataclasses.dataclass(frozen=True)
class SwitchDecision:
    """A pending step-boundary plan switch plus its evidence record —
    exactly what the ``adapt.switch`` event (and ADAPT_BENCH) banks."""
    target: int
    evidence: Dict[str, Any]


# ---------------------------------------------------------------------------
# the controller: attribution + detection + candidate re-pricing
# ---------------------------------------------------------------------------

class AdaptiveController:
    """Host-side glue: feeds measured step times through Attribution,
    the residual through DriftDetector, and on a trip re-prices the
    candidate set under the measured EFFECTIVE link rate to pick the
    switch target.  Emits the ``tune.drift.*`` counter stream (ambient
    MetricsSink + EventStream) and the Perfetto attribution-lane spans.

    Effective-rate estimate: with the baseline's compute fixed, a
    sustained excess ``e`` means the collective now takes
    (modeled + e) seconds, so the wire behaves as if the link ran at
    W_eff = W * modeled / (modeled + e) — the exact monotone knob the
    scoring model's codec argmin responds to (tune.autotune docstring).
    """

    def __init__(self, plans: List[TunedPlan], calibration: Calibration,
                 *, payload_elems: int, n: int, slice_elems: int = 8192,
                 warmup_steps: int = 3, ewma_alpha: float = 0.25,
                 drift_rel: float = 0.75, cusum_threshold: float = 3.0,
                 cooldown_steps: int = 8,
                 events: Optional[Any] = None) -> None:
        assert plans, "empty candidate set"
        self.plans = list(plans)
        self.calibration = calibration
        self.payload_elems = int(payload_elems)
        self.n = int(n)
        self.slice_elems = int(slice_elems)
        self.active = 0
        self.events = events
        self.attribution = Attribution(
            self._modeled(0), warmup_steps=warmup_steps,
            ewma_alpha=ewma_alpha)
        self.detector = DriftDetector(
            drift_rel=drift_rel, threshold=cusum_threshold,
            cooldown_steps=cooldown_steps)
        self._pending: Optional[SwitchDecision] = None
        self.last_record: Optional[Dict[str, float]] = None

    def _modeled(self, idx: int) -> Dict[str, float]:
        p = self.plans[idx]
        s = score_candidate(self.payload_elems, self.n, p.candidate,
                            self.calibration, self.slice_elems)
        return {"collective_s": s["collective_s"],
                "stream_s": s["stream_s"], "overhead_s": s["overhead_s"]}

    # -- observation --------------------------------------------------------

    def observe(self, step_s: float, *, step: int,
                t0_perf_ns: Optional[int] = None) -> None:
        """One measured step (call AFTER the step's outputs are
        materialized).  Streams the residual record and may arm a
        pending switch decision for the next step boundary."""
        rec = self.attribution.observe(step_s)
        self.last_record = rec
        if rec is None:
            return
        trip = self.detector.update(rec["resid_rel"])
        # counters emit AFTER the detector absorbs this step's residual,
        # and a trip emits its CROSSING value (the detector has already
        # reset): anyone correlating the Perfetto counter track with the
        # adapt.switch instant must see the statistic reach threshold
        cusum_pos, cusum_neg = self.detector.pos, self.detector.neg
        if trip is not None:
            if trip[0] == "slow":
                cusum_pos = trip[1]
            else:
                cusum_neg = trip[1]
        self._emit(rec, step, t0_perf_ns, cusum_pos, cusum_neg)
        if trip is None or self._pending is not None:
            return
        direction, stat = trip
        eff = self.effective_inter_gbps(rec["excess_s_ewma"])
        target = self.retarget(eff)
        self._pending = SwitchDecision(target, {
            "direction": direction,
            "cusum_stat": round(stat, 4),
            "resid_rel_ewma": round(rec["resid_rel_ewma"], 4),
            "collective_excess_s_ewma": round(rec["excess_s_ewma"], 6),
            "effective_inter_gbps": round(eff, 6),
            "calibrated_inter_gbps":
                round(self.calibration.inter_gbps, 6),
            "detected_step": int(step),
        })

    def _emit(self, rec: Dict[str, float], step: int,
              t0_perf_ns: Optional[int], cusum_pos: float,
              cusum_neg: float) -> None:
        from ..obs import metrics as obs_metrics
        drift = {
            "tune.drift.resid_rel": rec["resid_rel"],
            "tune.drift.resid_rel_ewma": rec["resid_rel_ewma"],
            "tune.drift.collective_excess_s": rec["collective_excess_s"],
            "tune.drift.measured_collective_s":
                rec["measured_collective_s"],
            "tune.drift.modeled_collective_s":
                rec["modeled_collective_s"],
            "tune.drift.cusum_pos": cusum_pos,
            "tune.drift.cusum_neg": cusum_neg,
        }
        obs_metrics.host_observe(drift)
        ev = self.events
        if ev is None:
            return
        for name, v in drift.items():
            ev.counter(name, float(v))
        # the Perfetto attribution lane: one span per modeled stage plus
        # the measured step envelope, all anchored at the step's start,
        # so modeled-vs-measured reads as bar-vs-bar per step
        t0 = (t0_perf_ns if t0_perf_ns is not None
              else time.perf_counter_ns() - int(rec["step_s"] * 1e9))
        common = {"lane": "attribution", "step": int(step),
                  "plan": self.active}
        ev.emit("span", "attr.step_measured", t_ns=t0,
                dur_ns=int(rec["step_s"] * 1e9),
                attrs=dict(common, stage="measured step",
                           resid_rel=round(rec["resid_rel"], 4)))
        ev.emit("span", "attr.compute_baseline", t_ns=t0,
                dur_ns=int(rec["compute_s"] * 1e9),
                attrs=dict(common, stage="compute (baseline)"))
        ev.emit("span", "attr.collective_modeled",
                t_ns=t0 + int(rec["compute_s"] * 1e9),
                dur_ns=int(rec["modeled_collective_s"] * 1e9),
                attrs=dict(common, stage="collective (modeled)"))
        excess = max(0.0, rec["collective_excess_s"])
        if excess > 0:
            ev.emit("span", "attr.collective_excess",
                    t_ns=t0 + int((rec["compute_s"]
                                   + rec["modeled_collective_s"]) * 1e9),
                    dur_ns=int(excess * 1e9),
                    attrs=dict(common, stage="collective (excess)"))

    # -- re-pricing / switching ---------------------------------------------

    def effective_inter_gbps(self, excess_s: float) -> float:
        """The measured-regime link rate (docstring formula)."""
        modeled = self.attribution.modeled["collective_s"]
        denom = max(modeled + max(excess_s, 0.0), 1e-12)
        return max(self.calibration.inter_gbps * modeled / denom,
                   _EPS_GBPS)

    def retarget(self, effective_inter_gbps: float) -> int:
        """Argmin over the PRE-COMPILED candidate set, re-priced at the
        effective link rate — never over the full grid: only plans that
        are already traced are admissible (the J13 contract)."""
        calib = dataclasses.replace(self.calibration,
                                    inter_gbps=float(effective_inter_gbps))
        best, best_s = 0, float("inf")
        for i, p in enumerate(self.plans):
            s = score_candidate(self.payload_elems, self.n, p.candidate,
                                calib, self.slice_elems)["exposed_s"]
            if s < best_s:
                best, best_s = i, s
        return best

    def inject_shift(self, effective_inter_gbps: float,
                     step: int = -1) -> None:
        """Deterministic test/lint seam: arm the switch decision the
        detector WOULD arm at this effective rate, bypassing the timing
        path.  The chaos `slowdown@collective` cell proves the measured
        path; this seam lets graftlint J13 and the unit tests exercise
        the switch mechanics without depending on wall-clock noise."""
        target = self.retarget(effective_inter_gbps)
        self._pending = SwitchDecision(target, {
            "direction": "injected",
            "effective_inter_gbps": round(float(effective_inter_gbps), 6),
            "detected_step": int(step),
        })

    def take_pending(self) -> Optional[SwitchDecision]:
        dec, self._pending = self._pending, None
        return dec

    def note_switch(self, target: int) -> None:
        """Install ``target`` as the active plan: rebase attribution on
        its modeled stages and put the detector in its post-switch
        hysteresis window."""
        self.active = int(target)
        self.attribution.rebase(self._modeled(self.active))
        self.detector.reset(cooldown=True)


# ---------------------------------------------------------------------------
# the adaptive trainer
# ---------------------------------------------------------------------------

class AdaptiveTrainer:
    """A DPTrainer fleet over one mesh: the top-K tuned plans, each a
    fully constructed trainer, every jitted program traced up front, the
    controller deciding which one runs — plan switches at step
    boundaries with ZERO new traces (graftlint J13).

    Contract:
      - ``cfg.collective.codec`` must be "auto" (the candidate set IS
        the autotuner grid) and ``cfg.adapt.enabled`` True.
      - ``init_state`` resolves live calibration + the candidate set and
        returns the active trainer's state; the first ``step`` call
        prewarms every candidate (compile cost is paid ONCE, before the
        steady state, never at a switch).
      - ``step(state, batch)`` runs the active plan, feeds the measured
        wall time to the controller, and applies any pending switch at
        the NEXT boundary.  Switches between same-codec candidates pass
        the state through untouched (bitwise on the gradient path);
        codec switches re-pad masters/moments (value-exact) and re-zero
        the EF residual.
      - ``recompiles_across_switch`` counts traces beyond the prewarm
        baseline — banked 0 by ADAPT_BENCH and held there by obs-gate.
    """

    def __init__(self, loss_fn: Callable, mesh: Any, cfg: Any,
                 axis_name: str = "dp", *,
                 events: Optional[Any] = None,
                 calibration: Optional[Calibration] = None,
                 plans: Optional[List[TunedPlan]] = None) -> None:
        acfg = cfg.adapt
        if not acfg.enabled:
            raise ValueError("AdaptiveTrainer needs cfg.adapt.enabled=True "
                             "(use DPTrainer for a static plan)")
        if not needs_autotune(cfg.collective):
            raise ValueError(
                "AdaptiveTrainer needs collective.codec='auto': the "
                "candidate set is the autotuner grid — a hand-pinned "
                "codec leaves nothing to adapt between")
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.cfg = cfg
        self.ax = axis_name
        self.n = int(mesh.shape[axis_name])
        self.events = events
        self._calib_override = calibration
        self._plans_override = plans
        self.plans: List[TunedPlan] = []
        self.trainers: List[Any] = []
        self.controller: Optional[AdaptiveController] = None
        self.calibration: Optional[Calibration] = None
        self._params_like = None
        self._prewarmed = False
        self._trace_baseline = 0
        self._step_i = 0
        self.switches = 0
        self.switch_events: List[Dict[str, Any]] = []

    # -- construction -------------------------------------------------------

    @property
    def active(self) -> int:
        assert self.controller is not None, "call init_state first"
        return self.controller.active

    @property
    def trainer(self) -> Any:
        """The active underlying DPTrainer."""
        return self.trainers[self.active]

    def _resolve(self, params: Any) -> None:
        import jax
        import numpy as np
        from ..parallel.train import DPTrainer

        acfg = self.cfg.adapt
        calib = self._calib_override
        if calib is None:
            calib = load_calibration()
            if acfg.live_calibration:
                calib = live_calibrate(self.mesh, self.ax, base=calib)
        self.calibration = calib
        leaves = jax.tree_util.tree_leaves(params)
        total = sum(int(np.prod(l.shape)) if l.shape else 1
                    for l in leaves)
        coll = self.cfg.collective
        topology = "hier" if coll.topology == "hier" else None
        plans = self._plans_override
        if plans is None:
            # depth grid pinned to 1 for the same reason as
            # tune.resolve_collective: the separate-op ring cannot
            # consume a launch-ahead depth
            plans = tune_topk(total, self.n, acfg.n_candidates,
                              intra_size=coll.intra_size,
                              topology=topology, calibration=calib,
                              slice_elems=coll.slice_elems, depths=(1,))
        self.plans = list(plans)
        self.trainers = []
        for plan in self.plans:
            c = plan.candidate
            resolved = dataclasses.replace(
                coll, codec=c.codec, codec_opts=(),
                pipeline_depth=c.pipeline_depth,
                bucket_elems=c.bucket_elems, topology=c.topology,
                intra_size=(c.intra_size if c.topology == "hier"
                            else coll.intra_size))
            cfg_i = dataclasses.replace(self.cfg, collective=resolved)
            self.trainers.append(
                DPTrainer(self.loss_fn, self.mesh, cfg_i,
                          axis_name=self.ax))
        self.controller = AdaptiveController(
            self.plans, calib, payload_elems=total, n=self.n,
            slice_elems=coll.slice_elems,
            warmup_steps=acfg.warmup_steps, ewma_alpha=acfg.ewma_alpha,
            drift_rel=acfg.drift_rel,
            cusum_threshold=acfg.cusum_threshold,
            cooldown_steps=acfg.cooldown_steps, events=self.events)

    def init_state(self, params: Any) -> Any:
        import jax
        self._resolve(params)
        self._params_like = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
        return self.trainers[0].init_state(params)

    def _ghost_params(self) -> Any:
        import jax
        import jax.numpy as jnp
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._params_like)

    def prewarm(self, batch: Any) -> None:
        """Trace EVERY candidate's full program set up front: per
        trainer one init-shaped step, the master->params gather, and —
        for non-active candidates — one step on a SWITCH-shaped state
        (built through the exact migration path a real switch takes), so
        a later switch replays cached programs only.  The trace counts
        after this call are the J13 baseline; steady state and switches
        must add zero."""
        import jax
        assert self.controller is not None, "call init_state first"
        src = self.trainers[self.active]
        ghost = src.init_state(self._ghost_params())
        ghost, _ = src.step(ghost, batch)
        jax.block_until_ready(ghost.w_own)
        src.params_from_master(ghost.w_own)
        for i, tr in enumerate(self.trainers):
            if i == self.active:
                continue
            mstate = self._migrate(ghost, self.active, i)
            mstate, _ = tr.step(mstate, batch)
            jax.block_until_ready(mstate.w_own)
            tr.params_from_master(mstate.w_own)
            # and the reverse migration's programs (switching BACK):
            ghost = self._migrate(mstate, i, self.active)
            ghost, _ = src.step(ghost, batch)
            jax.block_until_ready(ghost.w_own)
        self._prewarmed = True
        self._trace_baseline = self.total_traces

    @property
    def total_traces(self) -> int:
        return sum(t.step_traces + t.gather_traces for t in self.trainers)

    @property
    def recompiles_across_switch(self) -> int:
        """Traces beyond the prewarm baseline — 0 is the J13 contract
        (and the banked obs-gate fact)."""
        if not self._prewarmed:
            return 0
        return self.total_traces - self._trace_baseline

    # -- switching ----------------------------------------------------------

    def _migrate(self, state: Any, src_i: int, tgt_i: int) -> Any:
        """State from candidate ``src_i``'s layout onto ``tgt_i``'s.
        Same codec => same flat layout => the state passes through
        UNTOUCHED (bitwise).  Otherwise the checkpoint-restore
        discipline: re-pad masters/moments onto the target layout
        (value-exact), rebuild the replicated params from the landed
        masters, re-zero the EF residual (bounded self-healing
        accumulator, exactly like restore)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        src, tgt = self.trainers[src_i], self.trainers[tgt_i]
        if tgt._meta is None:
            tgt._ensure_meta(self._params_like)
        if (src.cfg.collective.codec == tgt.cfg.collective.codec
                and src._meta.padded_len == tgt._meta.padded_len):
            return state
        from ..ops import fused_update
        from ..parallel.train import TrainState
        sh = NamedSharding(self.mesh, P(self.ax))
        w_own = jax.device_put(
            fused_update.repad_flat(state.w_own, tgt._meta), sh)
        opt_state = {
            k: jax.device_put(fused_update.repad_flat(v, tgt._meta), sh)
            for k, v in state.opt_state.items()}
        return TrainState(
            params=tgt.params_from_master(w_own), w_own=w_own,
            opt_state=opt_state, step=state.step,
            codec_state=tgt._init_codec_state())

    def _plan_label(self, i: int) -> str:
        c = self.plans[i].candidate
        return (f"{i}:{c.codec or 'none'}/{c.topology}"
                f"/b{c.bucket_elems}")

    def _apply_switch(self, state: Any, dec: SwitchDecision) -> Any:
        frm, to = self.active, dec.target
        state = self._migrate(state, frm, to)
        self.controller.note_switch(to)
        self.switches += 1
        event = {
            "step": self._step_i,
            "from_plan": self._plan_label(frm),
            "to_plan": self._plan_label(to),
            "from": self.plans[frm].describe(),
            "to": self.plans[to].describe(),
            "evidence": dict(dec.evidence),
            "bitwise": (self.plans[frm].candidate.codec
                        == self.plans[to].candidate.codec),
        }
        self.switch_events.append(event)
        if self.events is not None:
            self.events.instant(
                "adapt.switch", lane="attribution", stage="switch",
                step=self._step_i, from_plan=event["from_plan"],
                to_plan=event["to_plan"], **dec.evidence)
        from ..obs.metrics import host_observe
        host_observe({"adapt.switches": float(self.switches)})
        return state

    # -- stepping -----------------------------------------------------------

    def step(self, state: Any, batch: Any) -> Tuple[Any, Any]:
        import jax
        assert self.controller is not None, "call init_state first"
        if not self._prewarmed:
            self.prewarm(batch)
        dec = self.controller.take_pending()
        if dec is not None and dec.target != self.active:
            state = self._apply_switch(state, dec)
        elif dec is not None:
            # detected shift, but the re-priced argmin IS the active
            # plan: rebase so the new regime becomes the baseline
            self.controller.note_switch(dec.target)
        t0_ns = time.perf_counter_ns()
        state, out = self.trainers[self.active].step(state, batch)
        jax.block_until_ready((state, out))
        step_s = (time.perf_counter_ns() - t0_ns) / 1e9
        self.controller.observe(step_s, step=self._step_i,
                                t0_perf_ns=t0_ns)
        self._step_i += 1
        return state, out

    # -- passthroughs / telemetry -------------------------------------------

    @property
    def batch_spec(self) -> Any:
        return self.trainers[0].batch_spec if self.trainers else None

    def shard_batch(self, batch: Any) -> Any:
        return self.trainers[self.active].shard_batch(batch)

    def trace_counts(self) -> Dict[str, int]:
        """Per-candidate STEP trace counts — what graftlint J13 and the
        ADAPT_BENCH rows read: exactly 1 each after prewarm, and still 1
        each after any number of switches (gather traces ride
        ``total_traces``/``recompiles_across_switch``)."""
        return {self._plan_label(i): t.step_traces
                for i, t in enumerate(self.trainers)}

    def obs_static_metrics(self) -> Dict[str, Any]:
        """The active trainer's statics plus the adaptation plane's own
        banked facts: candidate set, calibration provenance (incl. the
        live tier), switch/trace accounting."""
        d = self.trainer.obs_static_metrics()
        d["adapt"] = {
            "n_candidates": len(self.plans),
            "active": self.active,
            "candidates": [p.describe() for p in self.plans],
            "calibration": (self.calibration.describe()
                            if self.calibration else None),
            "switches": self.switches,
            "recompiles_across_switch": self.recompiles_across_switch,
        }
        return d
