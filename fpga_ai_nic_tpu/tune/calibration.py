"""Measured-rate harvesting from banked benchmark artifacts — the half
of the autotune loop that turns `ops.ring_cost` from a model into a
MEASUREMENT-driven model.

The repo banks every benchmark as a committed JSON artifact
(BENCH_r*.json, CODEC_BENCH_r*.json, COLLECTIVE_r*.json and their
artifacts/ twins, each stamped with git sha + platform by
bench_common.save_artifact).  This loader extracts the rates the
collective cost model is parameterized by:

  codec rates    encode/decode GB/s per registered codec and payload
                 class (vmem / streaming), from the codec-matrix bench.
  link rate      the measured per-direction wire rate: a multi-device
                 ring sweep's ring_f32 busbw when one is banked on real
                 ICI, else the fused-kernel single-chip loopback rate
                 (flagged as a loopback proxy), else the CPU-mesh sweep
                 (flagged dryrun-class).

Source ranking (highest wins): **live** startup microbench on the mesh
the job actually landed on (``apply_live`` — measured by
``tune.adapt.live_calibrate`` at trainer construction, so it outranks
every banked artifact including a real multi-chip sweep: the banked
number describes SOME machine, the live number describes THIS one) >
banked multi-chip ICI sweep > single-chip fused loopback proxy >
CPU-mesh sweep (dryrun-class) > documented fallback constant.

Honesty rules (the provenance record every consumer banks alongside the
plan):

  - every contributing artifact is listed with its path, git sha and
    platform; rows measured on the virtual CPU mesh are flagged
    ``dryrun`` — they parameterize the model (better than a constant
    pulled from a datasheet) but any verdict built on them must carry
    the flag (the same rule the fused-opt bench applies to its timings);
  - a component with NO banked measurement falls back to the documented
    constants (`ops.ring_cost.DEFAULT_LINK_RATES` and the fallbacks
    below) and the calibration says so: ``calibrated=False`` for that
    component, so `gen_perf_md` can badge model-only rows.

No jax import — calibration must load (and fail meaningfully) on a
machine with a wedged TPU tunnel, exactly like tools/obs_gate.py.
"""

from __future__ import annotations

import glob
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# documented fallback constants (used ONLY when no banked artifact backs
# the component; the loader marks such components uncalibrated):
FALLBACK_INTER_GBPS = 12.5      # the reference's own 100GbE wire
                                # (hw/bfp_adapter.sv sat on a 100G MAC)
FALLBACK_INTRA_GBPS = 45.0      # ICI-class fast hop (DEFAULT_LINK_RATES)
FALLBACK_CODEC_GBPS = 5.0       # conservative codec stage rate
DEFAULT_DISPATCH_S = 50e-6      # per-collective issue cost (measured
                                # class: the queued trainer's issue spans)
DEFAULT_RTT_S = 5e-6            # per-hop launch latency the depth-D
                                # pipeline amortizes


@dataclass(frozen=True)
class ArtifactRecord:
    """Provenance of one contributing artifact."""
    path: str
    git_sha: Optional[str]
    platform: Optional[str]
    dryrun: bool                 # CPU-mesh / oversubscribed measurement

    def describe(self) -> Dict[str, Any]:
        return {"path": self.path, "git_sha": self.git_sha,
                "platform": self.platform, "dryrun": self.dryrun}


@dataclass(frozen=True)
class CodecRates:
    """Measured stage rates of one codec at one payload class.
    ``live`` marks rows measured by the startup mesh microbench
    (apply_live stamps it — never trust a caller's string alone)."""
    encode_gbps: float
    decode_gbps: float
    source: str
    dryrun: bool
    live: bool = False


@dataclass(frozen=True)
class Calibration:
    """The measured-rate set the autotuner scores with.  ``calibrated``
    is True when at least one component came from a banked measurement;
    per-component flags tell consumers exactly which numbers are
    measured and which are the documented fallbacks."""

    codec_rates: Mapping[str, Mapping[str, CodecRates]] = \
        field(default_factory=dict)      # name -> class -> rates
    inter_gbps: float = FALLBACK_INTER_GBPS
    inter_calibrated: bool = False
    inter_source: str = "fallback constant (FALLBACK_INTER_GBPS)"
    inter_dryrun: bool = False
    # True when the rate came from the `live` tier (a startup microbench
    # on THIS mesh, apply_live) rather than a banked artifact — the
    # provenance bit consumers bank so a plan scored on live rates can
    # never masquerade as artifact-derived (or vice versa)
    inter_live: bool = False
    intra_gbps: float = FALLBACK_INTRA_GBPS
    intra_calibrated: bool = False
    intra_source: str = "fallback constant (FALLBACK_INTRA_GBPS)"
    intra_dryrun: bool = False
    intra_live: bool = False
    dispatch_s: float = DEFAULT_DISPATCH_S
    rtt_s: float = DEFAULT_RTT_S
    artifacts: Tuple[ArtifactRecord, ...] = ()

    @property
    def calibrated(self) -> bool:
        return bool(self.codec_rates) or self.inter_calibrated \
            or self.intra_calibrated

    @property
    def dryrun(self) -> bool:
        """True when every measured component is dryrun-class (or none
        is measured at all) — a verdict built on this calibration needs
        the dryrun flag."""
        measured = [r.dryrun for by_class in self.codec_rates.values()
                    for r in by_class.values()]
        if self.inter_calibrated:
            measured.append(self.inter_dryrun)
        return all(measured) if measured else True

    def codec_stage_rates(self, name: Optional[str],
                          payload_class: str = "streaming"
                          ) -> Tuple[float, float, bool]:
        """(encode_gbps, decode_gbps, measured) for a codec at a payload
        class; codec None (uncompressed) has no stages (inf, inf)."""
        if name is None:
            return float("inf"), float("inf"), True
        by_class = self.codec_rates.get(name) or {}
        row = by_class.get(payload_class) \
            or next(iter(by_class.values()), None)
        if row is None or row.encode_gbps <= 0 or row.decode_gbps <= 0:
            return FALLBACK_CODEC_GBPS, FALLBACK_CODEC_GBPS, False
        return row.encode_gbps, row.decode_gbps, True

    def describe(self) -> Dict[str, Any]:
        """The provenance record banked next to every tuned plan (sha +
        artifact list, dryrun-class rows flagged) — obs_static_metrics
        and the tune-bench artifact both carry it."""
        return {
            "calibrated": self.calibrated,
            "dryrun": self.dryrun,
            "inter_gbps": round(self.inter_gbps, 3),
            "inter_calibrated": self.inter_calibrated,
            "inter_source": self.inter_source,
            "inter_live": self.inter_live,
            "intra_gbps": round(self.intra_gbps, 3),
            "intra_calibrated": self.intra_calibrated,
            "intra_source": self.intra_source,
            "intra_dryrun": self.intra_dryrun,
            "intra_live": self.intra_live,
            "dispatch_s": self.dispatch_s,
            "rtt_s": self.rtt_s,
            "codec_rates": {
                name: {klass: {"encode_gbps": r.encode_gbps,
                               "decode_gbps": r.decode_gbps,
                               "source": r.source, "dryrun": r.dryrun,
                               "live": r.live}
                       for klass, r in by_class.items()}
                for name, by_class in sorted(self.codec_rates.items())},
            "artifacts": [a.describe() for a in self.artifacts],
        }


# ---------------------------------------------------------------------------
# artifact harvesting
# ---------------------------------------------------------------------------

def _load(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _newest(root: str, pattern: str) -> Optional[str]:
    paths = sorted(glob.glob(os.path.join(root, pattern)))
    return paths[-1] if paths else None


def _is_dryrun_platform(platform: Optional[str]) -> bool:
    return platform is None or not str(platform).startswith("tpu")


def _record(path: str, d: dict) -> ArtifactRecord:
    prov = d.get("_provenance") or {}
    return ArtifactRecord(
        path=os.path.relpath(path, ROOT) if os.path.isabs(path) else path,
        git_sha=prov.get("git_sha"), platform=d.get("platform"),
        dryrun=_is_dryrun_platform(d.get("platform")))


def _harvest_codec_rates(path: str, d: dict
                         ) -> Dict[str, Dict[str, CodecRates]]:
    """Codec-matrix artifact rows -> codec_rates mapping."""
    out: Dict[str, Dict[str, CodecRates]] = {}
    dry = _is_dryrun_platform(d.get("platform"))
    src = os.path.basename(path)
    for row in d.get("rows") or []:
        enc, dec = row.get("encode_gbps"), row.get("decode_gbps")
        if not enc or not dec:
            continue
        out.setdefault(row["codec"], {})[row.get("class", "streaming")] = \
            CodecRates(float(enc), float(dec), src, dry)
    return out


def _harvest_collective_codec(path: str, d: dict
                              ) -> Dict[str, Dict[str, CodecRates]]:
    """The main collective artifact carries standalone BFP stage rates
    (codec_encode/decode_gbps) — a TPU-measured row when the codec
    matrix only has CPU rows."""
    enc, dec = d.get("codec_encode_gbps"), d.get("codec_decode_gbps")
    if not enc or not dec:
        return {}
    dry = _is_dryrun_platform(d.get("platform"))
    return {"bfp": {"streaming": CodecRates(
        float(enc), float(dec), os.path.basename(path), dry)}}


def load_calibration(root: Optional[str] = None,
                     artifacts: Optional[Sequence[Tuple[str, dict]]] = None
                     ) -> Calibration:
    """Build a Calibration from the banked artifacts under ``root`` (the
    repo by default).  ``artifacts`` injects (path, dict) pairs directly
    — the fixture seam for unit tests that must not depend on what the
    repo happens to have banked."""
    root = root or ROOT
    pairs: List[Tuple[str, dict]] = []
    if artifacts is not None:
        pairs = [(p, d) for p, d in artifacts if d]
    else:
        for pattern in ("artifacts/codec_bench_*.json",
                        "CODEC_BENCH_r*.json",
                        "artifacts/collective_tpu_*.json",
                        "COLLECTIVE_r*.json",
                        "artifacts/collective_2*.json"):
            p = _newest(root, pattern)
            if p:
                d = _load(p)
                if d:
                    pairs.append((p, d))

    codec_rates: Dict[str, Dict[str, CodecRates]] = {}
    records: List[ArtifactRecord] = []
    inter = (FALLBACK_INTER_GBPS, False,
             "fallback constant (FALLBACK_INTER_GBPS)", False)
    # rank measured link-rate candidates: real multi-chip ICI sweep >
    # single-chip fused loopback (a pipeline proxy) > CPU-mesh sweep
    # (dryrun-class).  Rank 0 = nothing measured.
    inter_rank = 0
    # the INTRA (fast-hop) rate: the fused-kernel single-chip loopback
    # runs the whole ring wire path THROUGH one chip, so its banked rate
    # is a genuine within-chip measurement — the honest intra candidate
    # the TUNE_BENCH calibration block was missing while the fallback
    # constant said `intra_calibrated: false`.  TPU loopback rows (rank
    # 2) outrank dryrun/CPU ones (rank 1); provenance carries the dryrun
    # flag either way.
    intra = (FALLBACK_INTRA_GBPS, False,
             "fallback constant (FALLBACK_INTRA_GBPS)", False)
    intra_rank = 0

    for path, d in pairs:
        rec = _record(path, d)
        contributed = False
        harvested = (_harvest_codec_rates(path, d)
                     if d.get("metric") == "codec_matrix"
                     else _harvest_collective_codec(path, d))
        for name, by_class in harvested.items():
            for klass, rates in by_class.items():
                cur = codec_rates.get(name, {}).get(klass)
                # a TPU row beats a dryrun row; first-seen otherwise
                # (pairs are ordered newest-first per family)
                if cur is None or (cur.dryrun and not rates.dryrun):
                    codec_rates.setdefault(name, {})[klass] = rates
                    contributed = True
        sweep = d.get("sweep") or d.get("mesh_sweep") or []
        ring_rows = [r.get("ring_f32_gbps") for r in sweep
                     if r.get("ring_f32_gbps")]
        if ring_rows:
            rank = 1 if rec.dryrun else 3
            if rank > inter_rank:
                inter = (max(ring_rows), True,
                         f"{os.path.basename(path)} ring_f32 busbw"
                         + (" (dryrun-class CPU mesh)" if rec.dryrun
                            else ""), rec.dryrun)
                inter_rank = rank
                contributed = True
        lb = d.get("fused_ring_loopback_gbps")
        if lb and not rec.dryrun and inter_rank < 2:
            inter = (float(lb), True,
                     f"{os.path.basename(path)} fused-ring loopback "
                     "(single-chip proxy for the wire-path rate)", False)
            inter_rank = 2
            contributed = True
        if lb:
            rank = 2 if not rec.dryrun else 1
            if rank > intra_rank:
                intra = (float(lb), True,
                         f"{os.path.basename(path)} fused-ring loopback "
                         "(within-chip wire-path rate)"
                         + (" (dryrun-class CPU mesh)" if rec.dryrun
                            else ""), rec.dryrun)
                intra_rank = rank
                contributed = True
        if contributed:
            records.append(rec)

    return Calibration(
        codec_rates=codec_rates,
        inter_gbps=inter[0], inter_calibrated=inter[1],
        inter_source=inter[2], inter_dryrun=inter[3],
        intra_gbps=intra[0], intra_calibrated=intra[1],
        intra_source=intra[2], intra_dryrun=intra[3],
        artifacts=tuple(records))


def fixture_calibration(inter_gbps: float = 50.0,
                        codec_gbps: float = 8.0,
                        topk_gbps: Optional[float] = None) -> Calibration:
    """The deterministic FIXTURE regime shared by the J13 lint surface
    (lint/jaxpr_sweep), the adaptive chaos cells (tools/chaos_bench /
    adapt_bench) and the unit tests — ONE definition, because the
    premise is load-bearing: at the default fast wire the argmin's plan
    0 is the uncompressed flat ring, so a forced regime shift has a
    cheaper wire format to move to.  Retuning it in one consumer but
    not another would silently make the other's switch scenario vacuous
    (or flip its plan identity).  Pure data, zero banked-artifact
    dependence."""
    tk = codec_gbps if topk_gbps is None else topk_gbps
    rates = {
        name: {klass: CodecRates(r, r, "fixture", False)
               for klass in ("vmem", "streaming")}
        for name, r in (("bfp", codec_gbps), ("int8", codec_gbps),
                        ("topk", tk))}
    return Calibration(
        codec_rates=rates, inter_gbps=inter_gbps, inter_calibrated=True,
        inter_source="fixture", intra_gbps=40.0,
        artifacts=(ArtifactRecord("fixture.json", "f" * 40, "tpu",
                                  False),))


# ---------------------------------------------------------------------------
# the `live` tier (startup mesh microbenches — tune.adapt.live_calibrate)
# ---------------------------------------------------------------------------

def apply_live(base: Calibration, *,
               inter_gbps: Optional[float] = None,
               intra_gbps: Optional[float] = None,
               codec_rates: Optional[Mapping[str, Mapping[str,
                                                          "CodecRates"]]]
               = None,
               dryrun: bool = False,
               source: str = "startup mesh microbench") -> Calibration:
    """Overlay LIVE-measured rates onto a banked calibration — the top
    of the source ranking (module docstring): a rate measured on the
    mesh the job actually landed on outranks every banked artifact,
    because the banked number describes some machine and the live one
    describes THIS one.

    Honest provenance rules (the same contract as the banked tiers):
    every overridden component's source string is prefixed ``live:`` and
    its ``*_live`` flag set, ``dryrun`` must reflect the platform the
    microbench ran on (a CPU-mesh live rate is still dryrun-class —
    better than any constant, but verdicts built on it carry the flag),
    and components with no live measurement keep their banked provenance
    untouched.  Pure arithmetic: no jax import (the measuring half lives
    in ``tune.adapt.live_calibrate``)."""
    import dataclasses
    kw: Dict[str, Any] = {}
    tag = f"live: {source}" + (" (dryrun-class CPU mesh)" if dryrun else "")
    if inter_gbps is not None and inter_gbps > 0:
        kw.update(inter_gbps=float(inter_gbps), inter_calibrated=True,
                  inter_source=tag, inter_dryrun=bool(dryrun),
                  inter_live=True)
    if intra_gbps is not None and intra_gbps > 0:
        kw.update(intra_gbps=float(intra_gbps), intra_calibrated=True,
                  intra_source=tag, intra_dryrun=bool(dryrun),
                  intra_live=True)
    if codec_rates:
        merged: Dict[str, Dict[str, CodecRates]] = {
            name: dict(by_class)
            for name, by_class in base.codec_rates.items()}
        for name, by_class in codec_rates.items():
            for klass, rates in by_class.items():
                # stamp the live provenance HERE, never trusting the
                # caller's string: the overridden row must be
                # distinguishable from an artifact-harvested one in
                # every banked describe(), same contract as inter/intra
                src = rates.source if rates.source.startswith("live:") \
                    else f"live: {rates.source}"
                merged.setdefault(name, {})[klass] = CodecRates(
                    rates.encode_gbps, rates.decode_gbps, src,
                    bool(dryrun), live=True)
        kw["codec_rates"] = merged
    return dataclasses.replace(base, **kw) if kw else base
