"""Trace-time collective autotuning: the ring_cost roofline, fed by
measured rates harvested from banked artifacts, picks ``codec``,
``pipeline_depth``, ``bucket_elems`` and the (flat vs hierarchical)
topology per payload — ``CollectiveConfig(codec="auto")`` resolved once
at trainer construction, static thereafter.  docs/TUNING.md.

  tune.calibration   artifact harvesting + provenance (no jax import);
                     the `live` tier overlay (apply_live)
  tune.autotune      candidate enumeration, scoring, argmin, config
                     resolution; tune_topk (the bounded candidate set)
  tune.adapt         the drift observatory: live startup calibration,
                     modeled-vs-measured attribution (tune.drift.*),
                     CUSUM regime-shift detection, recompile-free plan
                     switching (AdaptiveTrainer, graftlint J13)
"""

from .calibration import (Calibration, CodecRates, apply_live,  # noqa: F401
                          load_calibration)
from .autotune import (Candidate, TunedPlan, enumerate_candidates,  # noqa: F401
                       needs_autotune, payload_class, rescore,
                       resolve_collective, resolve_train_config,
                       score_candidate, tune, tune_topk)
from . import adapt  # noqa: F401

__all__ = [
    "Calibration", "CodecRates", "apply_live", "load_calibration",
    "Candidate", "TunedPlan", "enumerate_candidates", "needs_autotune",
    "payload_class", "rescore", "resolve_collective",
    "resolve_train_config", "score_candidate", "tune", "tune_topk",
    "adapt",
]
