"""Trace-time collective autotuning: the ring_cost roofline, fed by
measured rates harvested from banked artifacts, picks ``codec``,
``pipeline_depth``, ``bucket_elems`` and the (flat vs hierarchical)
topology per payload — ``CollectiveConfig(codec="auto")`` resolved once
at trainer construction, static thereafter.  docs/TUNING.md.

  tune.calibration   artifact harvesting + provenance (no jax import)
  tune.autotune      candidate enumeration, scoring, argmin, config
                     resolution
"""

from .calibration import (Calibration, CodecRates,  # noqa: F401
                          load_calibration)
from .autotune import (Candidate, TunedPlan, enumerate_candidates,  # noqa: F401
                       needs_autotune, payload_class, rescore,
                       resolve_collective, resolve_train_config,
                       score_candidate, tune)

__all__ = [
    "Calibration", "CodecRates", "load_calibration",
    "Candidate", "TunedPlan", "enumerate_candidates", "needs_autotune",
    "payload_class", "rescore", "resolve_collective",
    "resolve_train_config", "score_candidate", "tune",
]
