"""Trace-time collective autotuner — close the ring_cost <-> telemetry
loop.

Every run used to hand-pick ``codec``, ``pipeline_depth``,
``bucket_elems`` and (now) the collective topology.  This module picks
them by ARGMIN over an enumerated candidate set, scored with the
`ops.ring_cost` roofline parameterized by MEASURED rates harvested from
the banked benchmark artifacts (`tune.calibration`) — SparCML's
switch-strategy-by-payload-regime (arXiv:1802.08021) on EQuARX's
quantize-only-the-slow-hop topology (arXiv:2506.17615), driven by our
own telemetry instead of a datasheet.

Static by construction (R2-clean): resolution happens ONCE in Python at
trainer construction — `resolve_collective` maps a
``CollectiveConfig(codec="auto")`` template to a concrete frozen config
plus a `TunedPlan` record; nothing about the tuner is visible to jax
tracing, and the plan (choice + calibration provenance) is banked into
``obs_static_metrics()`` so obs-gate diffs tuning decisions across PRs.

Scoring model (docs/TUNING.md carries the full derivation; all terms in
seconds, per training-step collective of an E-element f32 payload over n
devices):

  stream (codec-dependent):
    flat:  t_stream = max(wire_bytes / W_inter, raw_bytes * (1/enc + 1/dec))
           over the 2(n-1)/n * E elements each device moves (RS + AG);
           encode and decode SHARE the VPU, so their costs ADD
           (ring_cost.hop_cost — the serial-VPU model).
    hier:  t_intra (raw f32 at W_intra, codec-FREE) + t_inter (the same
           max() on the slow hop's 2(ng-1)/ng * E/ni elements only).
  overhead (codec-INDEPENDENT, so the codec argmin is provably monotone
  in the link rate — halving W_inter can only move the choice toward
  cheaper wire formats):
    dispatch   n_buckets * dispatch_s
    latency    n_buckets * hops * rtt_s / D     (depth-D amortization)
    fill       n_buckets * (D - 1) * slice_raw_bytes / W_inter

  exposed_s    = overhead + t_stream * (E_last / E): the DDP premise —
                 every bucket but the LAST overlaps backward compute, so
                 the exposure a step pays is the tail bucket's stream
                 plus per-collective overheads.  This is the argmin
                 objective (it is what bucket_elems trades off).
  collective_s = overhead + t_stream: the full collective wall time (the
                 bench-measurable quantity; reported alongside).

Determinism: candidates are enumerated in sorted order and scores are
pure arithmetic over the calibration record — same artifacts in, same
plan out (tests/test_tune.py pins it).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .calibration import Calibration, load_calibration
from ..ops import ring_cost

# candidate grids (sorted; determinism depends on stable ordering)
DEPTH_CANDIDATES = (1, 2, 4, 8)
BUCKET_CANDIDATES = (1 << 18, 1 << 20, 1 << 22, 4 * 1024 * 1024)
# payload-class split mirrors the codec matrix's residency classes
VMEM_CLASS_MAX_BYTES = 4 * (1 << 20)


@dataclass(frozen=True)
class Candidate:
    codec: Optional[str]
    pipeline_depth: int
    bucket_elems: int
    topology: str               # "flat" | "hier"
    intra_size: int             # 1 for flat

    def key(self) -> tuple:
        """Deterministic sort/tie-break key (codec name with the
        uncompressed candidate first, then topology and the smaller
        schedule knobs) — determinism is the contract; relative merit on
        ties is the scoring model's job, not the sort's."""
        return (self.codec or "", self.topology, self.intra_size,
                self.pipeline_depth, self.bucket_elems)


@dataclass(frozen=True)
class TunedPlan:
    """The resolved choice + everything needed to audit it."""
    candidate: Candidate
    modeled_exposed_s: float
    modeled_collective_s: float
    wire_bytes_per_device: int      # exact, one all-reduce of the payload
    raw_bytes_per_device: int
    payload_elems: int
    n: int
    payload_class: str              # "vmem" | "streaming"
    calibrated: bool
    dryrun: bool
    n_candidates: int
    calibration: Dict[str, Any]     # provenance record (sha + artifacts)

    def describe(self) -> Dict[str, Any]:
        c = self.candidate
        return {
            "codec": c.codec or "none",
            "pipeline_depth": c.pipeline_depth,
            "bucket_elems": c.bucket_elems,
            "topology": c.topology,
            "intra_size": c.intra_size,
            "payload_elems": self.payload_elems,
            "payload_class": self.payload_class,
            "n_devices": self.n,
            "modeled_exposed_ms": round(self.modeled_exposed_s * 1e3, 4),
            "modeled_collective_ms":
                round(self.modeled_collective_s * 1e3, 4),
            "wire_bytes_per_device": self.wire_bytes_per_device,
            "raw_bytes_per_device": self.raw_bytes_per_device,
            "calibrated": self.calibrated,
            "dryrun": self.dryrun,
            "n_candidates": self.n_candidates,
            "calibration": self.calibration,
        }


def needs_autotune(coll: Any) -> bool:
    """Does this CollectiveConfig defer choices to the tuner?"""
    return getattr(coll, "codec", None) == "auto"


def payload_class(payload_elems: int) -> str:
    return ("vmem" if payload_elems * 4 <= VMEM_CLASS_MAX_BYTES
            else "streaming")


def _codec_obj(name: Optional[str]) -> Any:
    if name is None:
        return None
    from ..compress import get_codec          # lazy: needs jax
    return get_codec(name)


def _hier_intra_candidates(n: int, intra_size: int,
                           topology: Optional[str]) -> List[int]:
    """Admissible fast-hop group sizes.  A DECLARED intra_size > 1
    dividing n is used as-is (ni == n — the degenerate all-intra ring —
    only when "hier" is explicitly pinned); intra_size == 0 with "hier"
    pinned delegates the factorization to the tuner: every proper
    divisor becomes a candidate."""
    if topology not in (None, "hier"):
        return []
    if intra_size > 1 and n % intra_size == 0:
        if intra_size < n or topology == "hier":
            return [intra_size]
        return []
    if intra_size == 0 and topology == "hier":
        return [d for d in range(2, n) if n % d == 0]
    return []


def enumerate_candidates(n: int, intra_size: int = 0,
                         codecs: Optional[Sequence[Optional[str]]] = None,
                         topology: Optional[str] = None,
                         depths: Optional[Sequence[int]] = None
                         ) -> List[Candidate]:
    """The full fixed-config grid the tuner argmins over (and the bench
    matrix compares against).  ``intra_size`` > 1 (dividing n) admits
    the hierarchical topology at that declared factorization —
    ``topology="hier"`` with intra_size == 0 lets the tuner own the
    factorization (every proper divisor of n is a candidate);
    ``topology`` pins one topology ("flat"/"hier") instead of comparing
    both.  ``depths`` restricts the pipeline-depth grid (trainer
    resolution passes (1,): the separate-op ring cannot consume a
    launch-ahead depth, and an unrealizable amortization term would
    skew the bucket argmin)."""
    if codecs is None:
        from ..compress import available_codecs   # lazy: needs jax
        codecs = (None,) + tuple(available_codecs())
    topologies: List[Tuple[str, int]] = []
    if topology in (None, "flat"):
        topologies.append(("flat", 1))
    topologies += [("hier", ni)
                   for ni in _hier_intra_candidates(n, intra_size,
                                                    topology)]
    if not topologies:
        raise ValueError(
            f"no admissible topology: topology={topology!r} with "
            f"intra_size={intra_size} over n={n} (hier needs "
            "intra_size > 1 dividing n, or intra_size=0 with "
            "topology='hier' to delegate the factorization)")
    out = []
    for codec in sorted(codecs, key=lambda c: c or ""):
        for topo, ni in topologies:
            for depth in (depths or DEPTH_CANDIDATES):
                for bucket in BUCKET_CANDIDATES:
                    out.append(Candidate(codec, depth, bucket, topo, ni))
    return sorted(out, key=Candidate.key)


def score_candidate(payload_elems: int, n: int, cand: Candidate,
                    calib: Calibration,
                    slice_elems: int = 8192) -> Dict[str, Any]:
    """Modeled seconds for one training-step all-reduce (RS + AG) of an
    [payload_elems] f32 payload under ``cand`` — the formula in the
    module docstring.  Pure arithmetic: no jax, no device."""
    E = int(payload_elems)
    klass = payload_class(E)
    enc, dec, rates_measured = calib.codec_stage_rates(cand.codec, klass)
    codec = _codec_obj(cand.codec)

    def wire_bytes(elems: int) -> int:
        if codec is None:
            return elems * 4
        pe = codec.pad_elems
        return codec.wire_bytes(elems + (-elems) % pe)

    if cand.topology == "hier":
        ph = ring_cost.hier_phase_bytes(E, n, cand.intra_size, wire_bytes)
        intra = ring_cost.hop_cost(ph["intra_bytes"], ph["intra_bytes"],
                                   calib.intra_gbps)
        inter = ring_cost.hop_cost(ph["inter_raw_bytes"],
                                   ph["inter_wire_bytes"],
                                   calib.inter_gbps, enc, dec)
        t_stream = intra["t_s"] + inter["t_s"]
        hops = ph["hops"]
        wire_total = ph["intra_bytes"] + ph["inter_wire_bytes"]
        raw_total = ph["intra_bytes"] + ph["inter_raw_bytes"]
        stream_detail = {"intra": intra, "inter": inter}
    else:
        e_wire = 2 * (n - 1) * (E // n)
        raw_total = e_wire * 4
        wire_total = wire_bytes(e_wire)
        hop = ring_cost.hop_cost(raw_total, wire_total,
                                 calib.inter_gbps, enc, dec)
        t_stream = hop["t_s"]
        hops = 2 * (n - 1)
        stream_detail = {"flat": hop}

    nb = max(1, math.ceil(E / cand.bucket_elems))
    e_last = E - (nb - 1) * cand.bucket_elems
    tail_frac = e_last / E if E else 1.0
    D = cand.pipeline_depth
    # codec-INDEPENDENT overheads (see module docstring: this keeps the
    # codec argmin provably monotone in the link rate)
    t_overhead = nb * (calib.dispatch_s
                       + hops * calib.rtt_s / D
                       + (D - 1) * slice_elems * 4
                       / (calib.inter_gbps * 1e9))
    return {
        "exposed_s": t_overhead + t_stream * tail_frac,
        "collective_s": t_overhead + t_stream,
        "stream_s": t_stream,
        "overhead_s": t_overhead,
        "n_buckets": nb,
        "last_bucket_elems": e_last,
        "wire_bytes_per_device": int(wire_total),
        "raw_bytes_per_device": int(raw_total),
        "payload_class": klass,
        "rates_measured": rates_measured,
        "stream_detail": stream_detail,
    }


def tune(payload_elems: int, n: int, *, intra_size: int = 0,
         topology: Optional[str] = None,
         codecs: Optional[Sequence[Optional[str]]] = None,
         calibration: Optional[Calibration] = None,
         slice_elems: int = 8192,
         depths: Optional[Sequence[int]] = None) -> TunedPlan:
    """Argmin over the candidate grid — deterministic: candidates are
    scored in sorted order and ties break on the sort key, so the same
    calibration artifacts always produce the same plan.  Exactly
    ``tune_topk(..., k=1)[0]`` (the global argmin IS the best plan of
    its wire-format group) — one construction path, so a field added to
    TunedPlan can never drift between the two."""
    return tune_topk(payload_elems, n, 1, intra_size=intra_size,
                     topology=topology, codecs=codecs,
                     calibration=calibration, slice_elems=slice_elems,
                     depths=depths)[0]


def tune_topk(payload_elems: int, n: int, k: int = 3, *,
              intra_size: int = 0, topology: Optional[str] = None,
              codecs: Optional[Sequence[Optional[str]]] = None,
              calibration: Optional[Calibration] = None,
              slice_elems: int = 8192,
              depths: Optional[Sequence[int]] = None) -> List[TunedPlan]:
    """The argmin winner plus the best runner-up plans from DISTINCT
    (codec, topology, intra_size) groups of the same grid — the bounded
    pre-compiled candidate set of the online adaptation plane
    (tune.adapt): when the measured regime shifts (the SparCML
    break-even moving with the effective link rate), the detector
    re-prices exactly these candidates and switches to one that is
    ALREADY traced.  Grouping by wire format guarantees the set spans
    genuinely different regimes instead of k bucket-size variants of one
    plan; within a group the best-scoring schedule wins.  Element 0 is
    always identical to ``tune(...)`` (same grid, same tie-breaks), and
    the list is deterministic for the same calibration."""
    assert k >= 1, k
    calib = calibration if calibration is not None else load_calibration()
    cands = enumerate_candidates(n, intra_size, codecs, topology, depths)
    best_by_group: Dict[Tuple[str, str, int],
                        Tuple[float, Candidate, Dict[str, Any]]] = {}
    for cand in cands:
        s = score_candidate(payload_elems, n, cand, calib, slice_elems)
        group = (cand.codec or "", cand.topology, cand.intra_size)
        cur = best_by_group.get(group)
        if cur is None or s["exposed_s"] < cur[0]:
            best_by_group[group] = (s["exposed_s"], cand, s)
    # deterministic: score ascending, candidate sort key breaking ties
    ranked = sorted(best_by_group.values(),
                    key=lambda t: (t[0], t[1].key()))
    out = []
    for score, cand, s in ranked[:k]:
        out.append(TunedPlan(
            candidate=cand,
            modeled_exposed_s=s["exposed_s"],
            modeled_collective_s=s["collective_s"],
            wire_bytes_per_device=s["wire_bytes_per_device"],
            raw_bytes_per_device=s["raw_bytes_per_device"],
            payload_elems=int(payload_elems), n=int(n),
            payload_class=s["payload_class"],
            calibrated=calib.calibrated,
            dryrun=calib.dryrun,
            n_candidates=len(cands),
            calibration=calib.describe()))
    return out


def rescore(plan: TunedPlan, payload_elems: int,
            calibration: Optional[Calibration] = None,
            slice_elems: int = 8192) -> TunedPlan:
    """Re-price the CHOSEN candidate at the final payload length.  The
    flat layout pads to a multiple of the resolved codec's unit (which
    is only known after resolution), so the EXACT wire-byte declaration
    the obs-gate tune.* keys pin is computed here, against the padded
    length the collective actually moves.  Pass the SAME calibration
    and slice_elems tune() scored with — a silently different
    parameterization between argmin and banked plan is exactly the
    drift this subsystem exists to prevent."""
    import dataclasses
    calib = calibration if calibration is not None else load_calibration()
    s = score_candidate(payload_elems, plan.n, plan.candidate, calib,
                        slice_elems)
    return dataclasses.replace(
        plan,
        modeled_exposed_s=s["exposed_s"],
        modeled_collective_s=s["collective_s"],
        wire_bytes_per_device=s["wire_bytes_per_device"],
        raw_bytes_per_device=s["raw_bytes_per_device"],
        payload_elems=int(payload_elems),
        payload_class=s["payload_class"])


def resolve_collective(coll: Any, n: int, payload_elems: int,
                       calibration: Optional[Calibration] = None
                       ) -> Tuple[Any, "TunedPlan"]:
    """Map a ``CollectiveConfig(codec="auto", ...)`` template to the
    concrete frozen config the trainer runs on, plus the TunedPlan
    record.  Called ONCE at trainer construction (parallel.train /
    parallel.ddp / parallel.fsdp `_ensure_meta`) — static thereafter.

    A non-auto config passes through unchanged with plan=None."""
    import dataclasses
    if not needs_autotune(coll):
        return coll, None
    # an explicit flat topology with no declared factorization stays
    # flat; a declared intra_size admits hier; topology="hier" pins it
    # (with intra_size=0 the tuner owns the factorization)
    topology = "hier" if coll.topology == "hier" else None
    # depth grid pinned to 1: codec="auto" runs the separate-op ring
    # (fused_kernel rejected at construction), which cannot consume a
    # launch-ahead depth — scoring an unrealizable rtt/D amortization
    # would skew the bucket argmin against reality
    plan = tune(payload_elems, n, intra_size=coll.intra_size,
                topology=topology, calibration=calibration,
                slice_elems=coll.slice_elems, depths=(1,))
    c = plan.candidate
    resolved = dataclasses.replace(
        coll, codec=c.codec, codec_opts=(),
        pipeline_depth=c.pipeline_depth,
        bucket_elems=c.bucket_elems,
        topology=c.topology,
        intra_size=c.intra_size if c.topology == "hier" else coll.intra_size)
    return resolved, plan


def resolve_train_config(cfg: Any, n: int, params_like: Any,
                         calibration: Optional[Calibration] = None
                         ) -> Tuple[Any, Optional["TunedPlan"],
                                    Optional[Calibration]]:
    """The shared trainer-side resolution step (DP / FSDP / DDP /
    QueuedDDP all call exactly this): payload size from the params tree
    (or ShapeDtypeStructs), one calibration load shared by resolution
    AND the later padded-length rescore, the collective replaced inside
    the frozen TrainConfig.  Returns ``(new_cfg, plan, calibration)`` —
    ``(cfg, None, None)`` when nothing is deferred."""
    import dataclasses
    if not needs_autotune(cfg.collective):
        return cfg, None, None
    import jax
    import numpy as np
    calib = calibration if calibration is not None else load_calibration()
    leaves = jax.tree_util.tree_leaves(params_like)
    total = sum(int(np.prod(l.shape)) if l.shape else 1 for l in leaves)
    coll, plan = resolve_collective(cfg.collective, n, total,
                                    calibration=calib)
    return dataclasses.replace(cfg, collective=coll), plan, calib
