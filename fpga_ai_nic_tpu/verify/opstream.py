"""The shared protocol IR: one op-stream definition per collective route.

An *op stream* is the per-node wait/signal/transfer order of a protocol,
as plain data — the exact program the emitted kernel executes, factored
out of the kernel so the checked model and the shipped schedule cannot
drift (`ops.ring_pallas._rs_op_stream` and `._rs_plan` are now thin
delegates to this module).  Four routes are extracted:

  flat       the depth-D pipelined ring reduce-scatter
             (`ops.ring_pallas._rs_kernel`): barrier, prologue sends,
             per-step launch/consume with the (D+1)-slot credit window.
  streaming  the HBM-streaming variant (`_rs_stream_kernel`): the same
             wire protocol plus the slice-load prefetch window (ld),
             the recv-side store-load/writeback pair (st/wb) with the
             single-wait discipline, and — with a fused optimizer — the
             w/m/v 2-deep state window (optld/optwb per tensor).
  hier       `ops.ring_hier`'s two-hop schedule: the raw intra subring
             hops, the program-order intra->inter handoff, then the
             sliced double-buffered codec hops across groups
             (`ops.ring._send`'s scan), RS then AG.
  reshard    `parallel.reshard`'s transfer program: one exact-length
             single-pair ppermute per owner-changing intersection
             segment, in table order, plus the EF-residual ownership
             moves.

Two execution models give the streams small-step semantics shared by the
exhaustive checker (`verify.mc.check`) and the randomized fuzz backend
(`verify.mc.run_random`, which IS `simulate_rs_protocol` now):

  RingModel  neighbor wire slots cycling mod n_slots with blocking
             semaphores and asynchronous landings — a started RDMA
             lands at an arbitrary later scheduler event, exactly the
             freedom real hardware has.
  PairModel  tag-matched directed sends (the XLA ppermute hop): a send
             never blocks, a recv blocks until its (src, tag) payload
             landed.

Local DMA discipline (the ld/st/wb/opt windows) is *deterministic per
node* — no cross-node event can reorder it — so it is checked statically
by `check_dma_discipline` (single-wait per DMA, wait-after-start,
window/RAW predecessors waited, full drain at exit: the two
hardware-only semaphore deadlock classes round 3 caught by review are
mechanical checks here), keeping the interleaving state space to the
events that are actually concurrent.

No jax import or jax API anywhere in this module (the parent package's
``__init__`` does pull jax — the graftlint CLI pins the CPU platform
env before importing, so the checker never waits on a TPU tunnel).
"""

from __future__ import annotations

from typing import (Any, Dict, List, NamedTuple, Optional, Sequence,
                    Set, Tuple)

Op = Tuple[Any, ...]
Action = Tuple[Any, ...]

# fused-optimizer state-tensor counts (w rides as tensor 0 on top):
# mirrors optim.OptimizerSpec.n_state without importing jax —
# tests/test_verify.py pins the equivalence.
OPT_N_STATE: Dict[str, int] = {"sgd": 0, "momentum": 1, "adamw": 2}

# default launch-ahead depth — mirrors ops.ring_pallas._PIPE_DEPTH
# (the delegate passes its own constant explicitly; the equivalence is
# pinned by tests/test_verify.py).
DEFAULT_PIPE_DEPTH = 2


class ProtocolError(Exception):
    """A protocol violation raised by a model's apply/terminal check.
    ``kind`` is one of: deadlock, recv_overwrite, send_overwrite,
    ordering, credit, dma, termination — or ``budget``, which is NOT a
    protocol verdict: the exploration hit its state cap and is
    inconclusive (CheckResult.inconclusive)."""

    def __init__(self, kind: str, message: str) -> None:
        super().__init__(message)
        self.kind = kind
        self.message = message


# ---------------------------------------------------------------------------
# plan + op-stream extraction: flat ring RS
# ---------------------------------------------------------------------------

def rs_plan(n: int, S: int, depth: Optional[int],
            default_depth: int = DEFAULT_PIPE_DEPTH
            ) -> Tuple[int, int, bool]:
    """(D, n_slots, launch_first) for the deep-pipelined RS schedule —
    THE plan definition (`ops.ring_pallas._rs_plan` delegates here).

    D (launch-ahead / pipeline depth) and the comm-slot window n_slots
    are bound by three schedule invariants (checked for every plan by
    the model checker and stated in ops.ring_pallas):

      RAW   send q's source rows are finalized by consume q-S.
            Launching q BEFORE consume(g) at step g needs q-S <= g-1,
            i.e. D <= S-1; launching AFTER consume(g) relaxes to D <= S.
      SLOT  emission q overwrites wire slot q % n_slots; its downstream
            decode of arrival q - n_slots must come first: n_slots >=
            D+1 makes every credit edge point to a strictly earlier
            downstream step (acyclic wait-for graph).
      CAP   no more emissions than total = (n-1)*S.
    """
    total = (n - 1) * S
    D = max(1, min(default_depth if depth is None else depth, S, total))
    launch_first = D < S              # RAW: ahead-of-consume needs D<=S-1
    n_slots = min(total, D + 1)
    return D, n_slots, launch_first


def rs_op_stream(n: int, S: int, depth: Optional[int],
                 default_depth: int = DEFAULT_PIPE_DEPTH
                 ) -> Tuple[List[Op], int]:
    """The per-node op stream of the deep-pipelined (VMEM-resident) RS
    schedule — the exact wait/signal/transfer order `_rs_kernel`
    executes (every node runs the identical program)."""
    total = (n - 1) * S
    D, n_slots, launch_first = rs_plan(n, S, depth, default_depth)
    ops: List[Op] = [("barrier",)]
    for q in range(D):                    # prologue: fill the pipe
        ops.append(("send", q))

    def launch(q: int) -> None:
        if q >= total:
            return
        if q >= n_slots:
            ops.append(("wait_send", q - n_slots))
        if q >= n_slots:
            ops.append(("credit_wait",))
        ops.append(("send", q))

    def consume(g: int) -> None:
        ops.append(("wait_recv", g))
        ops.append(("decode", g))
        ops.append(("credit_signal",))

    for g in range(total):
        if launch_first:
            launch(g + D)
            consume(g)
        else:
            consume(g)
            launch(g + D)
    for j in range(max(0, total - n_slots), total):
        ops.append(("wait_send", j))
    ops.append(("credit_drain", min(total, n_slots)))
    return ops, n_slots


# ---------------------------------------------------------------------------
# op-stream extraction: HBM-streaming RS (+ fused-optimizer state window)
# ---------------------------------------------------------------------------

def rs_stream_op_stream(n: int, S: int, depth: Optional[int],
                        opt_kind: Optional[str] = None,
                        default_depth: int = DEFAULT_PIPE_DEPTH
                        ) -> Tuple[List[Op], int]:
    """The per-node op stream of `_rs_stream_kernel`: the flat-ring wire
    protocol plus the streaming-only DMA windows —

      ld      send-side slice load, 2-deep, prefetched ONE emission
              ahead when ``launch_first and D + 2 <= S`` (the prefetch
              RAW gate stated in the kernel);
      st/wb   recv-side store-load + writeback pair, 2-deep, single-wait
              discipline (1-lag head wait when launch_first, in-loop
              wait at D == S);
      optld/optwb<t>  with ``opt_kind``: the w/m/v state window — each
              final-hop consume streams 1 + n_state tensor slices
              through a 2-deep VMEM window with its own DMA pairs.

    DMA ops carry their static hazard predecessors:
    ``("dma_start", chan, i, ((chan', j), ...))`` asserts each (chan',
    j) was *waited* before this start (VMEM slot reuse + the wb->ld RAW)
    — `check_dma_discipline` verifies the discipline per node.
    """
    total = (n - 1) * S
    D, n_slots, launch_first = rs_plan(n, S, depth, default_depth)
    final_g0 = (n - 2) * S
    prefetch = launch_first and D + 2 <= S
    n_t = 0 if opt_kind is None else 1 + OPT_N_STATE[opt_kind]
    ops: List[Op] = [("barrier",)]

    def dma_start(chan: str, i: int, *conf: Tuple[str, int]) -> None:
        ops.append(("dma_start", chan, i,
                    tuple((c, j) for c, j in conf if j >= 0)))

    def dma_wait(chan: str, i: int) -> None:
        ops.append(("dma_wait", chan, i))

    def ld_start(i: int) -> None:
        # window: ld(i-2) drained; RAW: ld reads what wb(i-S) wrote
        dma_start("ld", i, ("ld", i - 2), ("wb", i - S))

    # prologue: fill the pipeline with emissions 0..D-1
    if prefetch:
        ld_start(0)
    for q in range(D):
        if prefetch:
            if q + 1 < total:
                ld_start(q + 1)
        else:
            ld_start(q)
        dma_wait("ld", q)
        ops.append(("encode", q))
        ops.append(("send", q))

    def launch(q: int) -> None:
        if q >= total:
            return
        if prefetch:
            if q + 1 < total:
                ld_start(q + 1)       # hide the next HBM read
        else:
            ld_start(q)
        if q >= n_slots:
            ops.append(("wait_send", q - n_slots))
        dma_wait("ld", q)
        ops.append(("encode", q))
        if q >= n_slots:
            ops.append(("credit_wait",))
        ops.append(("send", q))

    def consume(g: int) -> None:
        if opt_kind is not None and g >= final_g0 + 2:
            for t in range(n_t):      # VMEM window slot reuse guard
                dma_wait(f"optwb{t}", g - 2)
        if opt_kind is not None and g >= final_g0:
            for t in range(n_t):      # hide the state read under the
                dma_start(f"optld{t}", g,     # wire wait + decode
                          (f"optld{t}", g - 2), (f"optwb{t}", g - 2))
        dma_start("st", g, ("st", g - 2), ("wb", g - 2))
        ops.append(("wait_recv", g))
        dma_wait("st", g)
        ops.append(("decode", g))
        ops.append(("credit_signal",))
        dma_start("wb", g, ("wb", g - 2))
        if opt_kind is not None and g >= final_g0:
            for t in range(n_t):
                dma_wait(f"optld{t}", g)
            ops.append(("update", g))
            for t in range(n_t):
                dma_start(f"optwb{t}", g, (f"optwb{t}", g - 2))

    if launch_first:
        for g in range(total):
            if g >= 1:                # single wait, 1-iteration lag
                dma_wait("wb", g - 1)
            launch(g + D)
            consume(g)
    else:
        for g in range(total):        # RAW is immediate at D == S
            consume(g)
            dma_wait("wb", g)
            launch(g + D)

    if launch_first:
        dma_wait("wb", total - 1)
    if opt_kind is not None:
        for gg in range(max(final_g0, total - 2), total):
            for t in range(n_t):
                dma_wait(f"optwb{t}", gg)
    for j in range(max(0, total - n_slots), total):
        ops.append(("wait_send", j))
    ops.append(("credit_drain", min(total, n_slots)))
    return ops, n_slots


# ---------------------------------------------------------------------------
# static DMA discipline (deterministic per node — no interleaving needed)
# ---------------------------------------------------------------------------

def check_dma_discipline(ops: Sequence[Op]) -> List[str]:
    """Verify the per-node DMA discipline of an op stream: every wait
    follows its start, every DMA is waited exactly ONCE (two waits on
    one signal deadlock real hardware — invisibly to the lockstep
    interpreter), every start's declared hazard predecessors (VMEM slot
    reuse, wb->ld RAW) were waited first, and nothing is left in flight
    at exit.  Returns violation messages (empty = clean)."""
    started: Set[Tuple[str, int]] = set()
    waited: Set[Tuple[str, int]] = set()
    out: List[str] = []
    for pos, op in enumerate(ops):
        if op[0] == "dma_start":
            _, chan, i, conf = op
            key = (chan, i)
            if key in started and key not in waited:
                out.append(f"op {pos}: DMA {chan}[{i}] restarted while "
                           "still in flight")
            for c in conf:
                if c in started and c not in waited:
                    out.append(
                        f"op {pos}: DMA slot/RAW hazard — {chan}[{i}] "
                        f"starts before required wait of {c[0]}[{c[1]}]")
            started.add(key)
        elif op[0] == "dma_wait":
            _, chan, i = op
            key = (chan, i)
            if key not in started:
                out.append(f"op {pos}: wait on never-started DMA "
                           f"{chan}[{i}] (hardware deadlock)")
            elif key in waited:
                out.append(f"op {pos}: second wait on DMA {chan}[{i}] — "
                           "one signal per DMA (hardware deadlock)")
            waited.add(key)
    for key in sorted(started - waited):
        out.append(f"exit: DMA {key[0]}[{key[1]}] started but never "
                   "waited (unsynchronized buffer at kernel exit)")
    return out


# ---------------------------------------------------------------------------
# op-stream extraction: hierarchical two-hop schedule
# ---------------------------------------------------------------------------

def hier_op_stream(n: int, ni: int, s_inter: int = 1,
                   include_ag: bool = True) -> List[List[Op]]:
    """Per-node op streams of `ops.ring_hier`'s two-hop schedule over a
    flat axis of n = ni * ng devices (device d: group d // ni, intra
    position d % ni).

    RS: (ni-1) raw intra subring hops -> program-order handoff -> (ng-1)
    inter codec hops, each sliced into ``s_inter`` double-buffered
    payloads (`ops.ring._send`'s scan: send slice k, encode k+1, recv
    k).  AG (``include_ag``): the phases in reverse — (ng-1) inter
    gather hops (encode once, forward verbatim: one payload per hop)
    then (ni-1) raw intra gather hops."""
    if ni < 1 or n % ni:
        raise ValueError(f"intra size {ni} does not factor n={n}")
    ng = n // ni
    streams: List[List[Op]] = []
    for d in range(n):
        g, j = d // ni, d % ni
        ops: List[Op] = []
        # phase A — raw intra reduce-scatter hops
        for s in range(ni - 1):
            dst = g * ni + (j + 1) % ni
            src = g * ni + (j - 1) % ni
            ops.append(("send_to", dst, ("rs_intra", s)))
            ops.append(("recv_from", src, ("rs_intra", s)))
            ops.append(("local", "accumulate", ("rs_intra", s)))
        ops.append(("local", "handoff", ("intra->inter",)))
        # phase B — sliced double-buffered codec hops across groups
        for s in range(ng - 1):
            dst = ((g + 1) % ng) * ni + j
            src = ((g - 1) % ng) * ni + j
            ops.append(("local", "encode", ("rs_inter", s, 0)))
            for k in range(s_inter):
                ops.append(("send_to", dst, ("rs_inter", s, k)))
                if k + 1 < s_inter:   # encode k+1 while k is on the wire
                    ops.append(("local", "encode", ("rs_inter", s, k + 1)))
                ops.append(("recv_from", src, ("rs_inter", s, k)))
                ops.append(("local", "decode", ("rs_inter", s, k)))
        if include_ag:
            # phase B' — inter all-gather (encode once, forward verbatim)
            for s in range(ng - 1):
                dst = ((g + 1) % ng) * ni + j
                src = ((g - 1) % ng) * ni + j
                ops.append(("send_to", dst, ("ag_inter", s)))
                ops.append(("recv_from", src, ("ag_inter", s)))
            # phase A' — raw intra all-gather
            for s in range(ni - 1):
                dst = g * ni + (j + 1) % ni
                src = g * ni + (j - 1) % ni
                ops.append(("send_to", dst, ("ag_intra", s)))
                ops.append(("recv_from", src, ("ag_intra", s)))
        streams.append(ops)
    return streams


# ---------------------------------------------------------------------------
# op-stream extraction: reshard transfer program
# ---------------------------------------------------------------------------

class Seg(NamedTuple):
    """One intersection-table segment (mirrors parallel.reshard.Transfer
    without importing jax; tests pin the equivalence)."""

    src: int
    dst: int
    src_off: int
    dst_off: int
    length: int


def reshard_segments(live: int, chunk_src: int,
                     chunk_tgt: int) -> Tuple[Seg, ...]:
    """Source->target shard intersections of a [live] flat vector: cut
    [0, live) at every chunk boundary of either layout.  The jax-free
    twin of `parallel.reshard.intersection_table` — the segments
    PARTITION the live range (asserted)."""
    assert live > 0 and chunk_src > 0 and chunk_tgt > 0
    cuts = {0, live}
    cuts.update(range(chunk_src, live, chunk_src))
    cuts.update(range(chunk_tgt, live, chunk_tgt))
    edges = sorted(cuts)
    table = []
    for a, b in zip(edges, edges[1:]):
        src, dst = a // chunk_src, a // chunk_tgt
        table.append(Seg(src=src, dst=dst, src_off=a - src * chunk_src,
                         dst_off=a - dst * chunk_tgt, length=b - a))
    assert sum(t.length for t in table) == live
    return tuple(table)


def reshard_owners(n_src: int, n_tgt: int) -> Tuple[int, ...]:
    """EF-residual old-device -> new-owner map (jax-free twin of
    `parallel.reshard.residual_owners`)."""
    assert n_src > 0 and n_tgt > 0
    return tuple(i * n_tgt // n_src for i in range(n_src))


def reshard_op_stream(live: int, chunk_src: int, chunk_tgt: int,
                      n_union: int,
                      residual_owners_map: Optional[Sequence[int]] = None
                      ) -> List[List[Op]]:
    """Per-node op streams of the lowered reshard program
    (`parallel.reshard.lower_apply`): the intersection segments in table
    order — an exact-length single-pair send/recv when the owner
    changes, a resident copy when it does not — then the EF-residual
    ownership moves in ascending-source order (the golden twin's sum
    order)."""
    segs = reshard_segments(live, chunk_src, chunk_tgt)
    streams: List[List[Op]] = [[] for _ in range(n_union)]
    for si, t in enumerate(segs):
        if t.src == t.dst:
            if t.src < n_union:
                streams[t.src].append(("local", "copy", ("seg", si)))
            continue
        assert t.src < n_union and t.dst < n_union, (t, n_union)
        streams[t.src].append(("send_to", t.dst, ("seg", si)))
        streams[t.dst].append(("recv_from", t.src, ("seg", si)))
    if residual_owners_map is not None:
        for i, owner in enumerate(residual_owners_map):
            if i == owner:
                streams[i].append(("local", "resid_keep", ("resid", i)))
                continue
            streams[i].append(("send_to", owner, ("resid", i)))
            streams[owner].append(("recv_from", i, ("resid", i)))
    return streams


# ---------------------------------------------------------------------------
# execution model 1: the ring credit-window protocol
# ---------------------------------------------------------------------------

class RingState:
    """Mutable interleaving state of a RingModel run.  Cloned only at
    branch points; the counterexample trace is a shared linked list so
    clones are O(state), not O(history)."""

    __slots__ = ("pc", "arrived", "slots", "credits", "flight",
                 "inflight_slots", "trace")

    def __init__(self, n: int, n_slots: int) -> None:
        self.pc = [0] * n
        self.arrived = [False] * n
        self.slots = [[-1] * n_slots for _ in range(n)]
        self.credits = [0] * n
        self.flight: Set[Tuple[int, int]] = set()
        # (dst, wire slot) -> number of in-flight transfers targeting it
        self.inflight_slots: Dict[Tuple[int, int], int] = {}
        self.trace: Optional[Tuple[Any, Any]] = None

    def clone(self) -> "RingState":
        st = RingState.__new__(RingState)
        st.pc = list(self.pc)
        st.arrived = list(self.arrived)
        st.slots = [list(s) for s in self.slots]
        st.credits = list(self.credits)
        st.flight = set(self.flight)
        st.inflight_slots = dict(self.inflight_slots)
        st.trace = self.trace
        return st

    def key(self) -> Tuple[Any, ...]:
        return (tuple(self.pc), tuple(self.arrived),
                tuple(map(tuple, self.slots)), tuple(self.credits),
                frozenset(self.flight))


class RingModel:
    """Small-step semantics of the ring credit-window protocol: n nodes
    running the IDENTICAL op stream, wire slots cycling mod n_slots,
    blocking semaphores, asynchronous landings.  Violations raised as
    ProtocolError; message wording is stable API (the fuzz backend's
    callers match on it)."""

    route = "ring"

    def __init__(self, n: int, ops: Sequence[Op], n_slots: int,
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.n = n
        self.ops = list(ops)
        self.n_slots = n_slots
        self.meta = dict(meta or {})
        self.total_sends = sum(1 for op in self.ops if op[0] == "send")
        self.credit_bound = min(self.total_sends, n_slots) \
            if self.total_sends else n_slots
        # strict_terminal adds the at-exit checks (no undecoded frame
        # left in a window, no leaked credits) on top of the legacy
        # simulator semantics; simulate_rs_protocol turns it off to
        # keep its published failure wording exact
        self.strict_terminal = True
        self.send_pos: Dict[int, int] = {
            op[1]: i for i, op in enumerate(self.ops) if op[0] == "send"}
        # emissions whose decode is NOT preceded by its wait_recv in
        # program order: landing q then commutes with NOTHING — the
        # decode-before-landing interleaving is realizable and must be
        # branched on, never resolved by an eager landing (in a correct
        # stream every decode is guarded and this set is empty; a
        # mutated stream that drops a wait_recv lands here — the POR
        # soundness hole the review's mutation sweep caught)
        first_wait: Dict[int, int] = {}
        self.unguarded_decodes: Set[int] = set()
        for i, op in enumerate(self.ops):
            if op[0] == "wait_recv" and op[1] not in first_wait:
                first_wait[op[1]] = i
            elif op[0] == "decode" and op[1] not in self.unguarded_decodes:
                if first_wait.get(op[1]) is None:
                    self.unguarded_decodes.add(op[1])

    # -- helpers -----------------------------------------------------------

    def _ctx(self) -> str:
        return " ".join(f"{k}={v}" for k, v in self.meta.items())

    def init_state(self) -> RingState:
        return RingState(self.n, self.n_slots)

    def node_count(self) -> int:
        return self.n

    def _landed(self, st: RingState, i: int, q: int) -> bool:
        pos = self.send_pos.get(q)
        return pos is not None and st.pc[i] > pos and (i, q) not in st.flight

    def _runnable(self, st: RingState, i: int) -> bool:
        if st.pc[i] >= len(self.ops):
            return False
        op = self.ops[st.pc[i]]
        kind = op[0]
        if kind == "barrier":
            return (not st.arrived[i]) or (st.arrived[(i - 1) % self.n]
                                           and st.arrived[(i + 1) % self.n])
        if kind == "wait_send":
            return self._landed(st, i, op[1])
        if kind == "credit_wait":
            return st.credits[i] >= 1
        if kind == "wait_recv":
            return st.slots[i][op[1] % self.n_slots] == op[1]
        if kind == "credit_drain":
            return st.credits[i] >= op[1]
        return True       # send / decode / credit_signal / dma / local

    def enabled(self, st: RingState) -> List[Action]:
        acts: List[Action] = [("node", i) for i in range(self.n)
                              if self._runnable(st, i)]
        acts.extend(("wire", s, q) for (s, q) in st.flight)
        return acts

    # -- transition --------------------------------------------------------

    def apply(self, st: RingState, act: Action) -> None:
        if act[0] == "wire":
            _, src, q = act
            dst = (src + 1) % self.n
            slot = q % self.n_slots
            st.trace = (("wire", src, q, dst, slot), st.trace)
            if st.slots[dst][slot] != -1:
                raise ProtocolError(
                    "recv_overwrite",
                    f"recv-slot overwrite: emission {q} landed on "
                    f"undecoded frame {st.slots[dst][slot]} in node "
                    f"{dst}'s slot {slot} ({self._ctx()})")
            st.slots[dst][slot] = q
            st.flight.discard((src, q))
            k = (dst, slot)
            c = st.inflight_slots.get(k, 0) - 1
            if c:
                st.inflight_slots[k] = c
            else:
                st.inflight_slots.pop(k, None)
            return
        i = act[1]
        op = self.ops[st.pc[i]]
        kind = op[0]
        st.trace = (("node", i, op), st.trace)
        if kind == "barrier":
            st.arrived[i] = True          # signal phase
            if not (st.arrived[(i - 1) % self.n]
                    and st.arrived[(i + 1) % self.n]):
                return                    # signaled; wait phase blocks
        elif kind == "send":
            q = op[1]
            slot = q % self.n_slots
            if any(s == i and t % self.n_slots == slot
                   for (s, t) in st.flight):
                raise ProtocolError(
                    "send_overwrite",
                    f"send-slot overwrite: emission {q} encoded over an "
                    f"in-flight frame in slot {slot} ({self._ctx()})")
            st.flight.add((i, q))
            k = ((i + 1) % self.n, slot)
            st.inflight_slots[k] = st.inflight_slots.get(k, 0) + 1
        elif kind == "decode":
            g = op[1]
            slot = g % self.n_slots
            got = st.slots[i][slot]
            if got != g:
                raise ProtocolError(
                    "ordering",
                    f"ordering corruption: decode of emission {g} found "
                    f"{'empty slot' if got == -1 else got} "
                    f"({self._ctx()})")
            st.slots[i][slot] = -1
        elif kind == "credit_signal":
            left = (i - 1) % self.n
            st.credits[left] += 1
            if st.credits[left] > self.credit_bound:
                raise ProtocolError(
                    "credit",
                    f"credit overflow: node {left} holds "
                    f"{st.credits[left]} credits for a {self.credit_bound}"
                    f"-slot window ({self._ctx()})")
        elif kind == "credit_wait":
            st.credits[i] -= 1
        elif kind == "credit_drain":
            st.credits[i] -= op[1]
        # wait_send / wait_recv / dma_* / encode / update / local:
        # guard already checked in _runnable; pc advance only
        st.pc[i] += 1

    # -- termination -------------------------------------------------------

    def finished(self, st: RingState) -> bool:
        return (not st.flight
                and all(p >= len(self.ops) for p in st.pc))

    def check_terminal(self, st: RingState) -> None:
        if not self.strict_terminal:
            return
        for i in range(self.n):
            for slot, got in enumerate(st.slots[i]):
                if got != -1:
                    raise ProtocolError(
                        "termination",
                        f"undecoded frame {got} left in node {i}'s slot "
                        f"{slot} at termination ({self._ctx()})")
        for i, c in enumerate(st.credits):
            if c != 0:
                raise ProtocolError(
                    "credit",
                    f"credit leak: node {i} terminates holding {c} "
                    f"credits ({self._ctx()})")

    def deadlock_message(self, st: RingState) -> str:
        nxt = [self.ops[p] if p < len(self.ops) else None for p in st.pc]
        return (f"protocol deadlock: {self._ctx()} pc={st.pc} next={nxt} "
                f"credits={st.credits} in_flight={sorted(st.flight)}")

    # -- partial-order reduction -------------------------------------------

    def pick_action(self, st: RingState,
                    acts: Sequence[Action]) -> Optional[Action]:
        """Singleton persistent set: an action that commutes with every
        other enabled action (and cannot race a future one — in-flight
        landings stay enabled until executed, so every latent conflict
        has an enabled witness).  An action whose violation condition is
        already live is returned too: the schedule freedom that makes it
        fire exists, so exploring it first IS the counterexample.
        Returns None when only mutually-dependent actions remain (full
        branch)."""
        for act in acts:
            if act[0] == "wire":
                _, src, q = act
                dst = (src + 1) % self.n
                slot = q % self.n_slots
                if st.slots[dst][slot] != -1:
                    return act            # violation live: explore it
                if q in self.unguarded_decodes:
                    continue              # decode(q) may run BEFORE this
                                          # landing (no wait_recv guard):
                                          # both orders must be explored
                if st.inflight_slots.get((dst, slot), 0) > 1:
                    continue              # racing same-slot landing
                if self._slot_sensitive(st, dst, slot):
                    continue              # dst decode of this slot pending
                if self._send_pending(st, src, slot):
                    continue              # src send-overwrite race
                return act
            i = act[1]
            op = self.ops[st.pc[i]]
            kind = op[0]
            if kind == "send":
                slot = op[1] % self.n_slots
                if any(s == i and t % self.n_slots == slot
                       for (s, t) in st.flight):
                    return act            # violation live: explore it
                return act
            if kind in ("decode", "wait_recv"):
                slot = op[1] % self.n_slots
                if st.inflight_slots.get((i, slot), 0) > 0:
                    continue              # landing may race this slot
                return act
            if kind == "credit_signal":
                left = (i - 1) % self.n
                if st.credits[left] >= self.credit_bound:
                    return act            # overflow live: explore it
                return act
            # barrier / credit_wait / credit_drain / wait_send / dma /
            # encode / update / local: commute with everything enabled
            return act
        return None

    def _slot_sensitive(self, st: RingState, dst: int, slot: int) -> bool:
        # only an ENABLED partner can conflict: decode is always
        # enabled, but a wait_recv blocked on this slot is not a
        # partner — the landing merely enables it (they commute)
        if st.pc[dst] >= len(self.ops):
            return False
        op = self.ops[st.pc[dst]]
        return op[0] == "decode" and op[1] % self.n_slots == slot

    def _send_pending(self, st: RingState, src: int, slot: int) -> bool:
        if st.pc[src] >= len(self.ops):
            return False
        op = self.ops[st.pc[src]]
        return op[0] == "send" and op[1] % self.n_slots == slot


# ---------------------------------------------------------------------------
# execution model 2: tag-matched pair transfers (the XLA ppermute hop)
# ---------------------------------------------------------------------------

class PairState:
    """Mutable interleaving state of a PairModel run."""

    __slots__ = ("pc", "flight", "landed", "trace")

    def __init__(self, n: int) -> None:
        self.pc = [0] * n
        self.flight: Set[Tuple[int, int, Any]] = set()
        self.landed: Set[Tuple[int, int, Any]] = set()
        self.trace: Optional[Tuple[Any, Any]] = None

    def clone(self) -> "PairState":
        st = PairState.__new__(PairState)
        st.pc = list(self.pc)
        st.flight = set(self.flight)
        st.landed = set(self.landed)
        st.trace = self.trace
        return st

    def key(self) -> Tuple[Any, ...]:
        return (tuple(self.pc), frozenset(self.flight),
                frozenset(self.landed))


class PairModel:
    """Small-step semantics of directed tag-matched transfers: a send
    never blocks (the payload is in flight until its landing event), a
    recv blocks until its exact (src, tag) payload has landed and then
    consumes it.  Models the lowered single-pair ppermute programs
    (reshard) and the subring hop chains (hier), where the failure modes
    are mismatched program orders (deadlock) and orphaned payloads
    (ordering)."""

    route = "pair"

    def __init__(self, streams: Sequence[Sequence[Op]],
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.streams = [list(s) for s in streams]
        self.n = len(self.streams)
        self.meta = dict(meta or {})

    def _ctx(self) -> str:
        return " ".join(f"{k}={v}" for k, v in self.meta.items())

    def init_state(self) -> PairState:
        return PairState(self.n)

    def node_count(self) -> int:
        return self.n

    def _runnable(self, st: PairState, i: int) -> bool:
        if st.pc[i] >= len(self.streams[i]):
            return False
        op = self.streams[i][st.pc[i]]
        if op[0] == "recv_from":
            return (op[1], i, op[2]) in st.landed
        return True

    def enabled(self, st: PairState) -> List[Action]:
        acts: List[Action] = [("node", i) for i in range(self.n)
                              if self._runnable(st, i)]
        acts.extend(("wire",) + t for t in st.flight)
        return acts

    def apply(self, st: PairState, act: Action) -> None:
        if act[0] == "wire":
            t = (act[1], act[2], act[3])
            st.trace = (("wire",) + t, st.trace)
            st.flight.discard(t)
            st.landed.add(t)
            return
        i = act[1]
        op = self.streams[i][st.pc[i]]
        st.trace = (("node", i, op), st.trace)
        if op[0] == "send_to":
            t = (i, op[1], op[2])
            if t in st.flight or t in st.landed:
                raise ProtocolError(
                    "send_overwrite",
                    f"duplicate emission: payload {op[2]!r} {i}->{op[1]} "
                    f"sent while a previous copy is outstanding "
                    f"({self._ctx()})")
            st.flight.add(t)
        elif op[0] == "recv_from":
            st.landed.discard((op[1], i, op[2]))
        st.pc[i] += 1

    def finished(self, st: PairState) -> bool:
        return (not st.flight
                and all(st.pc[i] >= len(self.streams[i])
                        for i in range(self.n)))

    def check_terminal(self, st: PairState) -> None:
        if st.landed:
            orphan = sorted(st.landed)[0]
            raise ProtocolError(
                "termination",
                f"orphan payload (ordering corruption): {orphan[2]!r} "
                f"{orphan[0]}->{orphan[1]} landed but never consumed "
                f"({self._ctx()}; {len(st.landed)} total)")

    def deadlock_message(self, st: PairState) -> str:
        nxt = [self.streams[i][p] if p < len(self.streams[i]) else None
               for i, p in enumerate(st.pc)]
        return (f"protocol deadlock: {self._ctx()} pc={st.pc} next={nxt} "
                f"in_flight={sorted(st.flight)}")

    def pick_action(self, st: PairState,
                    acts: Sequence[Action]) -> Optional[Action]:
        # every action commutes with every other: tags are unique per
        # payload, sends never block, landings only enable — so the
        # first enabled action is always a singleton persistent set
        return acts[0] if acts else None
