"""The shared protocol IR: one op-stream EMITTER per collective route.

An *op stream* is the per-node wait/signal/transfer order of a protocol,
as plain data.  Since PR 14 every checked route is a **true delegate**
of its kernel/lowering: the schedule is emitted exactly once, by an
emitter in this module, and consumed twice —

  - by `ListSink` here, producing the abstract op list the exhaustive
    checker (`verify.mc.check`) and the randomized fuzz backend
    (`verify.mc.run_random`, which IS `simulate_rs_protocol`) explore;
  - by the real lowering's sink, mapping the SAME abstract ops onto
    DMA starts/waits, semaphore signals, ppermute hops and VPU calls
    (`ops.ring_pallas._KernelSink` inside the Pallas kernels;
    `ops.ring_hier`, `parallel.reshard` and `serve.handoff` consume the
    phase/action programs below for their XLA lowerings).

Transcription drift is therefore structurally impossible: there is no
second definition to drift (tests pin the delegation by identity, not
by structural comparison).  Six routes:

  flat       the depth-D pipelined ring reduce-scatter
             (`ops.ring_pallas._rs_kernel`): barrier, prologue sends,
             per-step launch/consume with the (D+1)-slot credit window
             (`RsEmitter`; optional fused-opt update + integrity ops).
  streaming  the HBM-streaming variant (`_rs_stream_kernel`): the same
             wire protocol plus the slice-load prefetch window (ld),
             the recv-side store-load/writeback pair (st/wb) with the
             single-wait discipline, and — with a fused optimizer — the
             w/m/v 2-deep state window (`RsStreamEmitter`).
  ag         the HBM-streaming interleaved-emission ring all-gather
             (`_ag_stream_kernel`): the `ag_schedule` emission order
             (P1/P2) under the S+2 slot window with credits
             (`AgStreamEmitter`) — the schedule that until PR 14 was
             only *statically asserted*, now explored exhaustively.
  hier       `ops.ring_hier`'s two-hop schedule: the raw intra subring
             hops, the program-order intra->inter handoff, then the
             sliced double-buffered codec hops across groups
             (`ops.ring._send`'s scan), RS then AG — phases, perms and
             conservation message ids all from `hier_program`.
  reshard    `parallel.reshard`'s transfer program: one exact-length
             single-pair ppermute per owner-changing intersection
             segment, in table order, plus the EF-residual ownership
             moves (`reshard_leaf_actions`/`reshard_residual_actions`,
             message ids included).
  handoff    `serve.handoff`'s KV-migration pair program: one gathered
             page block per layer per K/V crossing the 2-device pair
             mesh, plus the integrity verdict exchange
             (`handoff_program`).

With ``integrity=True`` the emitters add the PR-12 checksum ops as
paired ``chk_emit``/``chk_arrive`` IR ops carrying their conservation
message id and odd weight — the static M2 pass
(`check_weight_conservation`) verifies every emission has exactly one
arrival partner with the SAME weight, all weights odd and
program-distinct, freezing the weight-collision bug class review caught
twice in PR 12 as a tool.

Two execution models give the streams small-step semantics shared by the
exhaustive checker (`verify.mc.check`) and the randomized fuzz backend
(`verify.mc.run_random`, which IS `simulate_rs_protocol` now):

  RingModel  neighbor wire slots cycling mod n_slots with blocking
             semaphores and asynchronous landings — a started RDMA
             lands at an arbitrary later scheduler event, exactly the
             freedom real hardware has.
  PairModel  tag-matched directed sends (the XLA ppermute hop): a send
             never blocks, a recv blocks until its (src, tag) payload
             landed.

Local DMA discipline (the ld/st/wb/opt windows) is *deterministic per
node* — no cross-node event can reorder it — so it is checked statically
by `check_dma_discipline` (single-wait per DMA, wait-after-start,
window/RAW predecessors waited, full drain at exit: the two
hardware-only semaphore deadlock classes round 3 caught by review are
mechanical checks here), keeping the interleaving state space to the
events that are actually concurrent.

No jax import or jax API anywhere in this module (the parent package's
``__init__`` does pull jax — the graftlint CLI pins the CPU platform
env before importing, so the checker never waits on a TPU tunnel).
"""

from __future__ import annotations

from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Set, Tuple)

Op = Tuple[Any, ...]
Action = Tuple[Any, ...]

# fused-optimizer state-tensor counts (w rides as tensor 0 on top):
# mirrors optim.OptimizerSpec.n_state without importing jax —
# tests/test_verify.py pins the equivalence.
OPT_N_STATE: Dict[str, int] = {"sgd": 0, "momentum": 1, "adamw": 2}

# default launch-ahead depth — mirrors ops.ring_pallas._PIPE_DEPTH
# (the delegate passes its own constant explicitly; the equivalence is
# pinned by tests/test_verify.py).
DEFAULT_PIPE_DEPTH = 2


def msg_weight(msg: int) -> int:
    """THE odd conservation weight of message ``msg`` — the jax-free
    twin of `ops.integrity.hop_weight` (2*msg + 1 mod 2^32; odd, hence
    invertible, so a single corrupted word can never vanish from the
    weighted sum).  tests/test_verify.py pins the equivalence; the M2
    pass (`check_weight_conservation`) checks oddness and
    program-distinctness of the weights the emitters attach."""
    return (2 * msg + 1) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# the sink interface: every emitter emits through one of these
# ---------------------------------------------------------------------------

class OpSink:
    """Abstract-op consumer.  An emitter calls exactly these methods, in
    per-node program order; `ListSink` collects them as the checked op
    stream, and each lowering implements a sink that maps them onto its
    real DMA/semaphore/collective calls
    (`ops.ring_pallas._KernelSink`).  ``when(cond)`` is the predication seam: with a python
    bool it either runs or skips the decorated thunk (the checker and
    the unrolled interpreter schedule); with a traced bool the kernel
    sink lowers it to `pl.when` (the rolled hardware schedule) — one
    emitter text therefore serves both execution styles."""

    def when(self, cond: Any) -> Any:
        raise NotImplementedError

    def barrier(self) -> None:
        raise NotImplementedError

    def send(self, q: Any, src: Any = None) -> None:
        raise NotImplementedError

    def wait_send(self, j: Any) -> None:
        raise NotImplementedError

    def wait_recv(self, g: Any) -> None:
        raise NotImplementedError

    def credit_wait(self) -> None:
        raise NotImplementedError

    def credit_signal(self) -> None:
        raise NotImplementedError

    def credit_drain(self, k: int) -> None:
        raise NotImplementedError

    def encode(self, q: Any, src: Any = None) -> None:
        raise NotImplementedError

    def decode(self, g: Any) -> None:
        raise NotImplementedError

    def update(self, g: Any) -> None:
        raise NotImplementedError

    def local(self, name: str, *args: Any) -> None:
        raise NotImplementedError

    def dma_start(self, chan: str, i: Any, *conf: Tuple[str, Any]) -> None:
        raise NotImplementedError

    def dma_wait(self, chan: str, i: Any) -> None:
        raise NotImplementedError

    def chk_emit(self, msg: Any, carry: str = "wire",
                 weight: Optional[int] = None) -> None:
        raise NotImplementedError

    def chk_arrive(self, msg: Any, carry: str = "wire",
                   weight: Optional[int] = None) -> None:
        raise NotImplementedError


class ListSink(OpSink):
    """Collects the abstract op stream (the checker's view).  Driven
    only with concrete indices/conditions — ``when`` evaluates its bool
    immediately.  Checksum ops record ``(kind, carry, msg, weight)``
    with the weight resolved through `msg_weight` unless overridden (the
    override exists for M2's bad fixtures, which must be able to inject
    a weight collision)."""

    def __init__(self) -> None:
        self.ops: List[Op] = []

    def when(self, cond: Any) -> Any:
        def deco(f: Any) -> None:
            if cond:
                f()
        return deco

    def barrier(self) -> None:
        self.ops.append(("barrier",))

    def send(self, q: Any, src: Any = None) -> None:
        # ``src`` is a lowering hint (which buffer the frame leaves
        # from — the AG forward reuses its arrival's recv slot); the
        # wire protocol is src-agnostic, so the IR op records only q
        self.ops.append(("send", q))

    def wait_send(self, j: Any) -> None:
        self.ops.append(("wait_send", j))

    def wait_recv(self, g: Any) -> None:
        self.ops.append(("wait_recv", g))

    def credit_wait(self) -> None:
        self.ops.append(("credit_wait",))

    def credit_signal(self) -> None:
        self.ops.append(("credit_signal",))

    def credit_drain(self, k: int) -> None:
        self.ops.append(("credit_drain", k))

    def encode(self, q: Any, src: Any = None) -> None:
        self.ops.append(("encode", q))

    def decode(self, g: Any) -> None:
        self.ops.append(("decode", g))

    def update(self, g: Any) -> None:
        self.ops.append(("update", g))

    def local(self, name: str, *args: Any) -> None:
        self.ops.append(("local", name, tuple(args)))

    def dma_start(self, chan: str, i: Any, *conf: Tuple[str, Any]) -> None:
        self.ops.append(("dma_start", chan, i,
                         tuple((c, j) for c, j in conf if j >= 0)))

    def dma_wait(self, chan: str, i: Any) -> None:
        self.ops.append(("dma_wait", chan, i))

    def chk_emit(self, msg: Any, carry: str = "wire",
                 weight: Optional[int] = None) -> None:
        self.ops.append(("chk_emit", carry, msg,
                         msg_weight(msg) if weight is None else weight))

    def chk_arrive(self, msg: Any, carry: str = "wire",
                   weight: Optional[int] = None) -> None:
        self.ops.append(("chk_arrive", carry, msg,
                         msg_weight(msg) if weight is None else weight))


class ProtocolError(Exception):
    """A protocol violation raised by a model's apply/terminal check.
    ``kind`` is one of: deadlock, recv_overwrite, send_overwrite,
    ordering, credit, dma, termination — or ``budget``, which is NOT a
    protocol verdict: the exploration hit its state cap and is
    inconclusive (CheckResult.inconclusive)."""

    def __init__(self, kind: str, message: str) -> None:
        super().__init__(message)
        self.kind = kind
        self.message = message


# ---------------------------------------------------------------------------
# plan + op-stream extraction: flat ring RS
# ---------------------------------------------------------------------------

def rs_plan(n: int, S: int, depth: Optional[int],
            default_depth: int = DEFAULT_PIPE_DEPTH
            ) -> Tuple[int, int, bool]:
    """(D, n_slots, launch_first) for the deep-pipelined RS schedule —
    THE plan definition (`ops.ring_pallas._rs_plan` delegates here).

    D (launch-ahead / pipeline depth) and the comm-slot window n_slots
    are bound by three schedule invariants (checked for every plan by
    the model checker and stated in ops.ring_pallas):

      RAW   send q's source rows are finalized by consume q-S.
            Launching q BEFORE consume(g) at step g needs q-S <= g-1,
            i.e. D <= S-1; launching AFTER consume(g) relaxes to D <= S.
      SLOT  emission q overwrites wire slot q % n_slots; its downstream
            decode of arrival q - n_slots must come first: n_slots >=
            D+1 makes every credit edge point to a strictly earlier
            downstream step (acyclic wait-for graph).
      CAP   no more emissions than total = (n-1)*S.
    """
    total = (n - 1) * S
    D = max(1, min(default_depth if depth is None else depth, S, total))
    launch_first = D < S              # RAW: ahead-of-consume needs D<=S-1
    n_slots = min(total, D + 1)
    return D, n_slots, launch_first


class RsEmitter:
    """THE deep-pipelined (VMEM-resident) RS program — the exact
    wait/signal/transfer order `_rs_kernel` executes (every node runs
    the identical program).  The kernel consumes this emitter through
    its `_KernelSink`; the checker consumes it through `ListSink`
    (`rs_op_stream`); there is no second copy of the schedule.

    ``opt_kind`` adds the fused-optimizer final-hop ``update`` ops;
    ``integrity`` adds the paired ``chk_emit``/``chk_arrive`` checksum
    ops exactly where the kernel reads the frames (post-encode on the
    send side, post-wait_recv on the receive side)."""

    def __init__(self, n: int, S: int, depth: Optional[int],
                 opt_kind: Optional[str] = None, integrity: bool = False,
                 default_depth: int = DEFAULT_PIPE_DEPTH) -> None:
        self.n = n
        self.S = S
        self.total = (n - 1) * S
        self.D, self.n_slots, self.launch_first = rs_plan(
            n, S, depth, default_depth)
        self.final_g0 = (n - 2) * S
        self.opt_kind = opt_kind
        self.integrity = integrity

    def launch(self, sink: OpSink, q: Any) -> None:
        @sink.when(q < self.total)
        def _launch() -> None:
            @sink.when(q >= self.n_slots)
            def _reuse() -> None:         # frame slot q % n_slots drained?
                sink.wait_send(q - self.n_slots)
            sink.encode(q)
            if self.integrity:
                sink.chk_emit(q)
            @sink.when(q >= self.n_slots)
            def _credit() -> None:        # downstream freed the slot?
                sink.credit_wait()
            sink.send(q)

    def consume(self, sink: OpSink, g: Any) -> None:
        sink.wait_recv(g)
        if self.integrity:
            sink.chk_arrive(g)
        sink.decode(g)
        if self.opt_kind is not None:
            @sink.when(g >= self.final_g0)
            def _update() -> None:        # this slice lands in OUR chunk
                sink.update(g)
        sink.credit_signal()

    def prologue(self, sink: OpSink) -> None:
        sink.barrier()
        for q in range(self.D):           # fill the pipe (no reuse:
            self.launch(sink, q)          # D < n_slots, guards all false)

    def step(self, sink: OpSink, g: Any) -> None:
        if self.launch_first:
            self.launch(sink, g + self.D)
            self.consume(sink, g)
        else:
            self.consume(sink, g)
            self.launch(sink, g + self.D)

    def epilogue(self, sink: OpSink) -> None:
        for j in range(max(0, self.total - self.n_slots), self.total):
            sink.wait_send(j)
        sink.credit_drain(min(self.total, self.n_slots))

    def stream(self) -> Tuple[List[Op], int]:
        sink = ListSink()
        self.prologue(sink)
        for g in range(self.total):
            self.step(sink, g)
        self.epilogue(sink)
        return sink.ops, self.n_slots


def rs_op_stream(n: int, S: int, depth: Optional[int],
                 default_depth: int = DEFAULT_PIPE_DEPTH,
                 opt_kind: Optional[str] = None,
                 integrity: bool = False) -> Tuple[List[Op], int]:
    """The checked view of `RsEmitter` (one emitter, two consumers)."""
    return RsEmitter(n, S, depth, opt_kind=opt_kind, integrity=integrity,
                     default_depth=default_depth).stream()


# ---------------------------------------------------------------------------
# op-stream extraction: HBM-streaming RS (+ fused-optimizer state window)
# ---------------------------------------------------------------------------

class RsStreamEmitter:
    """THE HBM-streaming RS program — the flat-ring wire protocol plus
    the streaming-only DMA windows, consumed by `_rs_stream_kernel`'s
    sink AND by the checker:

      ld      send-side slice load, 2-deep, prefetched ONE emission
              ahead when ``launch_first and D + 2 <= S`` (the prefetch
              RAW gate);
      st/wb   recv-side store-load + writeback pair, 2-deep, single-wait
              discipline (1-lag head wait when launch_first, in-loop
              wait at D == S);
      optld/optwb<t>  with ``opt_kind``: the w/m/v state window — each
              final-hop consume streams 1 + n_state tensor slices
              through a 2-deep VMEM window with its own DMA pairs.

    DMA ops carry their static hazard predecessors:
    ``("dma_start", chan, i, ((chan', j), ...))`` asserts each (chan',
    j) was *waited* before this start (VMEM slot reuse + the wb->ld RAW)
    — `check_dma_discipline` verifies the discipline per node (the
    lowering sink ignores the predecessor annotations; they are the
    checker's evidence, not schedule)."""

    def __init__(self, n: int, S: int, depth: Optional[int],
                 opt_kind: Optional[str] = None, integrity: bool = False,
                 default_depth: int = DEFAULT_PIPE_DEPTH) -> None:
        self.n = n
        self.S = S
        self.total = (n - 1) * S
        self.D, self.n_slots, self.launch_first = rs_plan(
            n, S, depth, default_depth)
        self.final_g0 = (n - 2) * S
        self.prefetch = self.launch_first and self.D + 2 <= S
        self.opt_kind = opt_kind
        self.n_t = 0 if opt_kind is None else 1 + OPT_N_STATE[opt_kind]
        self.integrity = integrity

    def _ld(self, sink: OpSink, i: Any) -> None:
        # window: ld(i-2) drained; RAW: ld reads what wb(i-S) wrote
        sink.dma_start("ld", i, ("ld", i - 2), ("wb", i - self.S))

    def prologue(self, sink: OpSink) -> None:
        sink.barrier()
        if self.prefetch:
            self._ld(sink, 0)
        for q in range(self.D):           # fill the pipeline: emissions
            if self.prefetch:             # 0..D-1, no slot reuse yet
                if q + 1 < self.total:
                    self._ld(sink, q + 1)
            else:
                self._ld(sink, q)
            sink.dma_wait("ld", q)
            sink.encode(q)
            if self.integrity:
                sink.chk_emit(q)
            sink.send(q)

    def launch(self, sink: OpSink, q: Any) -> None:
        @sink.when(q < self.total)
        def _launch() -> None:
            if self.prefetch:
                @sink.when(q + 1 < self.total)
                def _prefetch() -> None:  # hide the next HBM read
                    self._ld(sink, q + 1)
            else:
                self._ld(sink, q)
            @sink.when(q >= self.n_slots)
            def _reuse() -> None:         # frame slot drained?
                sink.wait_send(q - self.n_slots)
            sink.dma_wait("ld", q)
            sink.encode(q)
            if self.integrity:
                sink.chk_emit(q)
            @sink.when(q >= self.n_slots)
            def _credit() -> None:
                sink.credit_wait()
            sink.send(q)

    def consume(self, sink: OpSink, g: Any) -> None:
        if self.opt_kind is not None:
            @sink.when(g >= self.final_g0 + 2)
            def _opt_slot_free() -> None:  # VMEM window slot reuse guard
                for t in range(self.n_t):
                    sink.dma_wait(f"optwb{t}", g - 2)

            @sink.when(g >= self.final_g0)
            def _opt_ld() -> None:         # hide the state read under
                for t in range(self.n_t):  # the wire wait + decode
                    sink.dma_start(f"optld{t}", g,
                                   (f"optld{t}", g - 2),
                                   (f"optwb{t}", g - 2))
        sink.dma_start("st", g, ("st", g - 2), ("wb", g - 2))
        sink.wait_recv(g)
        if self.integrity:
            sink.chk_arrive(g)
        sink.dma_wait("st", g)
        sink.decode(g)
        sink.credit_signal()
        sink.dma_start("wb", g, ("wb", g - 2))
        if self.opt_kind is not None:
            @sink.when(g >= self.final_g0)
            def _opt_update() -> None:     # grad wb streams out above
                for t in range(self.n_t):  # while the VPU updates here
                    sink.dma_wait(f"optld{t}", g)
                sink.update(g)
                for t in range(self.n_t):
                    sink.dma_start(f"optwb{t}", g, (f"optwb{t}", g - 2))

    def step(self, sink: OpSink, g: Any) -> None:
        if self.launch_first:
            @sink.when(g >= 1)
            def _wb_prev() -> None:        # single wait, 1-iteration lag
                sink.dma_wait("wb", g - 1)
            self.launch(sink, g + self.D)
            self.consume(sink, g)
        else:
            self.consume(sink, g)          # RAW is immediate at D == S
            sink.dma_wait("wb", g)
            self.launch(sink, g + self.D)

    def epilogue(self, sink: OpSink) -> None:
        if self.launch_first:
            sink.dma_wait("wb", self.total - 1)
        if self.opt_kind is not None:
            for gg in range(max(self.final_g0, self.total - 2),
                            self.total):
                for t in range(self.n_t):
                    sink.dma_wait(f"optwb{t}", gg)
        for j in range(max(0, self.total - self.n_slots), self.total):
            sink.wait_send(j)
        sink.credit_drain(min(self.total, self.n_slots))

    def stream(self) -> Tuple[List[Op], int]:
        sink = ListSink()
        self.prologue(sink)
        for g in range(self.total):
            self.step(sink, g)
        self.epilogue(sink)
        return sink.ops, self.n_slots


def rs_stream_op_stream(n: int, S: int, depth: Optional[int],
                        opt_kind: Optional[str] = None,
                        default_depth: int = DEFAULT_PIPE_DEPTH,
                        integrity: bool = False) -> Tuple[List[Op], int]:
    """The checked view of `RsStreamEmitter` (one emitter, two
    consumers)."""
    return RsStreamEmitter(n, S, depth, opt_kind=opt_kind,
                           integrity=integrity,
                           default_depth=default_depth).stream()


# ---------------------------------------------------------------------------
# static DMA discipline (deterministic per node — no interleaving needed)
# ---------------------------------------------------------------------------

def check_dma_discipline(ops: Sequence[Op]) -> List[str]:
    """Verify the per-node DMA discipline of an op stream: every wait
    follows its start, every DMA is waited exactly ONCE (two waits on
    one signal deadlock real hardware — invisibly to the lockstep
    interpreter), every start's declared hazard predecessors (VMEM slot
    reuse, wb->ld RAW) were waited first, and nothing is left in flight
    at exit.  Returns violation messages (empty = clean)."""
    started: Set[Tuple[str, int]] = set()
    waited: Set[Tuple[str, int]] = set()
    out: List[str] = []
    for pos, op in enumerate(ops):
        if op[0] == "dma_start":
            _, chan, i, conf = op
            key = (chan, i)
            if key in started and key not in waited:
                out.append(f"op {pos}: DMA {chan}[{i}] restarted while "
                           "still in flight")
            for c in conf:
                if c in started and c not in waited:
                    out.append(
                        f"op {pos}: DMA slot/RAW hazard — {chan}[{i}] "
                        f"starts before required wait of {c[0]}[{c[1]}]")
            started.add(key)
        elif op[0] == "dma_wait":
            _, chan, i = op
            key = (chan, i)
            if key not in started:
                out.append(f"op {pos}: wait on never-started DMA "
                           f"{chan}[{i}] (hardware deadlock)")
            elif key in waited:
                out.append(f"op {pos}: second wait on DMA {chan}[{i}] — "
                           "one signal per DMA (hardware deadlock)")
            waited.add(key)
    for key in sorted(started - waited):
        out.append(f"exit: DMA {key[0]}[{key[1]}] started but never "
                   "waited (unsynchronized buffer at kernel exit)")
    return out


# ---------------------------------------------------------------------------
# the paged gather-attend DMA program (consumed by
# ops.paged_attend_pallas AND the checker — the serving fast path's
# per-page schedule, one definition)
# ---------------------------------------------------------------------------


def negate(cond: Any) -> Any:
    """Logical negation across the two predication styles ``when``
    serves: python bools (the checker and the unrolled interpreter
    schedules) take ``not``; traced bools (the rolled kernel schedule)
    take ``~`` — ``not`` on a tracer raises, and ``~True`` is the
    python int -2.  Jax-free on purpose: tracers only ever reach this
    through a kernel sink."""
    if isinstance(cond, bool):
        return not cond
    return ~cond


class PagedAttendEmitter:
    """One definition of the paged gather-attend decode kernel's
    per-(request, kv-head) DMA schedule (`ops.paged_attend_pallas`) —
    the PR-14 discipline applied to the serving fast path: the SAME
    stream drives the kernel lowering (through its sink) and the
    graftmc ``gather`` family (`verify.mc.build_gather`), so the gather
    protocol that ships is the protocol that was checked.

    ``n_pages`` table slots per sequence; the first ``n_live`` hold
    every visible position (``live(i)``: a python bool per slot for the
    checker, a traced bool for the rolled kernel).  Per live page i the
    stream is a ``depth``-deep double buffer over dedicated VMEM spans
    (page i lands at rows [i*page_size, (i+1)*page_size) of the K/V
    tile buffers — transfers never share a destination), with the DMA
    *semaphores* cycling mod depth:

        wait kpg[i]; wait vpg[i]        (the prologue started 0..depth-1)
        start kpg[i+depth], vpg[i+depth] if that slot is live — declared
                                        hazard predecessor: page i, just
                                        waited, which shares its
                                        semaphore slot (i mod depth)
        attend_tile(i)                  (scores tile from the landed
                                        K page)

    Dead slots (i >= n_live) emit only ``dead_fill`` — their pages are
    NEVER transferred.  The allocated-extent bytes the reference gather
    pays for dead slots are exactly the bytes this schedule saves, and
    `check_gather_coverage` pins the other direction: every live
    (page, offset) is read exactly once, zero overlap."""

    K_CHAN = "kpg"
    V_CHAN = "vpg"

    def __init__(self, n_pages: int, depth: int = 2) -> None:
        assert n_pages >= 1 and depth >= 1, (n_pages, depth)
        self.n_pages = n_pages
        self.depth = depth

    def stream(self, sink: OpSink, live: Callable[[int], Any]) -> None:
        P, depth = self.n_pages, self.depth
        for i in range(min(depth, P)):
            @sink.when(live(i))
            def _prologue(i: int = i) -> None:
                # predecessors i-depth are pre-history (index < 0):
                # stated so the semaphore-reuse invariant reads the same
                # on every start; ListSink filters them out
                sink.dma_start(self.K_CHAN, i, (self.K_CHAN, i - depth))
                sink.dma_start(self.V_CHAN, i, (self.V_CHAN, i - depth))
        for i in range(P):
            @sink.when(live(i))
            def _live_tile(i: int = i) -> None:
                sink.dma_wait(self.K_CHAN, i)
                sink.dma_wait(self.V_CHAN, i)
                if i + depth < P:
                    @sink.when(live(i + depth))
                    def _launch(i: int = i) -> None:
                        sink.dma_start(self.K_CHAN, i + depth,
                                       (self.K_CHAN, i))
                        sink.dma_start(self.V_CHAN, i + depth,
                                       (self.V_CHAN, i))
                sink.local("attend_tile", i)

            @sink.when(negate(live(i)))
            def _dead_tile(i: int = i) -> None:
                sink.local("dead_fill", i)
        sink.local("softmax")
        sink.local("pv")


def paged_attend_op_stream(n_pages: int, n_live: int,
                           depth: int = 2) -> List[Op]:
    """The checker's view of one (request, kv-head) grid cell's gather
    schedule: ``n_live`` of ``n_pages`` table slots hold visible
    positions.  Consumed by `verify.mc.build_gather` (the exhaustive
    ``gather`` envelope family); tests/test_paged_attend.py pins it
    against the kernel's own emission."""
    assert 0 <= n_live <= n_pages, (n_live, n_pages)
    sink = ListSink()
    PagedAttendEmitter(n_pages, depth).stream(sink, lambda i: i < n_live)
    return sink.ops


def check_gather_coverage(ops: Sequence[Op], n_pages: int,
                          n_live: int) -> List[str]:
    """The gather family's coverage/exclusivity obligations, on top of
    the generic per-node DMA discipline (`check_dma_discipline`): every
    live page's K and V are transferred exactly once and waited before
    its attend (each live (page, offset) read exactly once — no
    overlap, no hole), every dead slot is dead-filled exactly once and
    transfers NOTHING (the saved allocated-extent bytes are real), and
    the epilogue reduces the tiles exactly once.  Returns violation
    messages (empty = clean)."""
    out: List[str] = []
    starts: Dict[Tuple[str, int], int] = {}
    waited_at: Dict[Tuple[str, int], int] = {}
    attends: List[int] = []
    dead: List[int] = []
    tail: List[str] = []
    chans = (PagedAttendEmitter.K_CHAN, PagedAttendEmitter.V_CHAN)
    for pos, op in enumerate(ops):
        if op[0] == "dma_start":
            key = (op[1], op[2])
            starts[key] = starts.get(key, 0) + 1
        elif op[0] == "dma_wait":
            waited_at.setdefault((op[1], op[2]), pos)
        elif op[0] == "local":
            name, args = op[1], op[2]
            if name == "attend_tile":
                i = args[0]
                attends.append(i)
                for chan in chans:
                    if waited_at.get((chan, i)) is None:
                        out.append(
                            f"op {pos}: attend of page {i} before its "
                            f"{chan} DMA was waited — reads an unlanded "
                            "tile")
            elif name == "dead_fill":
                dead.append(args[0])
            else:
                tail.append(name)
    if attends != list(range(n_live)):
        out.append(f"live coverage broken: attends={attends}, want "
                   f"pages 0..{n_live - 1} each exactly once, in order")
    if dead != list(range(n_live, n_pages)):
        out.append(f"dead slots mishandled: dead_fill={dead}, want "
                   f"{list(range(n_live, n_pages))}")
    for (chan, i), c in sorted(starts.items()):
        if i >= n_live:
            out.append(f"dead page {i} transferred on {chan} — the "
                       "allocated-extent bytes the schedule exists to "
                       "save")
        elif c != 1:
            out.append(f"{chan}[{i}] transferred {c} times — "
                       "overlapping reads of one (page, offset) span")
    for i in range(n_live):
        for chan in chans:
            if (chan, i) not in starts:
                out.append(f"live page {i} never transferred on {chan} "
                           "— a hole in the gathered span")
    if tail != ["softmax", "pv"]:
        out.append("epilogue must reduce the landed tiles exactly "
                   f"once: got {tail}, want ['softmax', 'pv']")
    return out


# ---------------------------------------------------------------------------
# the hierarchical two-hop program (consumed by ops.ring_hier AND the
# checker — phases, perms and conservation message ids, one definition)
# ---------------------------------------------------------------------------

def intra_perm(n: int, ni: int) -> List[Tuple[int, int]]:
    """Next-neighbor inside each group of ni consecutive ranks — THE
    intra-subring permutation (`ops.ring_hier._intra_perm` delegates
    here; the checker derives per-node src/dst from the same list)."""
    return [(g * ni + j, g * ni + (j + 1) % ni)
            for g in range(n // ni) for j in range(ni)]


def inter_perm(n: int, ni: int) -> List[Tuple[int, int]]:
    """Next-group, same intra position: the inter rings (THE
    definition, as `intra_perm`)."""
    ng = n // ni
    return [(g * ni + j, ((g + 1) % ng) * ni + j)
            for g in range(ng) for j in range(ni)]


class HierPhase(NamedTuple):
    """One phase of the hierarchical schedule: ``hops`` ring hops over
    ``perm``, each hop carrying ``slices`` wire messages.  ``msg(s, k)``
    is hop s / slice k's id in the owning conservation carry — the SAME
    arithmetic `ops.ring_hier` feeds `integrity.hop_weight` (traced hop
    indices welcome), so the checksum weights the lowering uses and the
    weights M2 checks cannot diverge."""

    kind: str                  # rs_intra | rs_inter | ag_inter | ag_intra
    hops: int
    slices: int                # wire messages per hop (s_inter on rs_inter)
    base: int                  # carry message id of (hop 0, slice 0)
    perm: Tuple[Tuple[int, int], ...]

    def msg(self, s: Any, k: Any = 0) -> Any:
        return self.base + s * self.slices + k


class HierProgram(NamedTuple):
    """The full two-hop schedule of `ops.ring_hier` over n = ni * ng
    devices.  The RS phases share one conservation carry ("rs": intra
    hop s is message s, inter hop s slice k is (ni-1) + s*s_inter + k);
    the AG phases share another ("ag": inter hop s is message s, intra
    hop s is (ng-1) + s) — exactly the counters `hier_reduce_scatter` /
    `hier_all_gather` consume."""

    n: int
    ni: int
    ng: int
    s_inter: int
    rs_intra: HierPhase
    rs_inter: HierPhase
    ag_inter: HierPhase
    ag_intra: HierPhase


def hier_program(n: int, ni: int, s_inter: int = 1) -> HierProgram:
    """Build THE hierarchical phase program (validates the declared
    factorization, as `ops.ring_hier.check_factorization`)."""
    if ni < 1 or n % ni:
        raise ValueError(f"intra size {ni} does not factor n={n}")
    ng = n // ni
    pa = tuple(intra_perm(n, ni))
    pb = tuple(inter_perm(n, ni))
    return HierProgram(
        n=n, ni=ni, ng=ng, s_inter=s_inter,
        rs_intra=HierPhase("rs_intra", ni - 1, 1, 0, pa),
        rs_inter=HierPhase("rs_inter", ng - 1, s_inter, ni - 1, pb),
        ag_inter=HierPhase("ag_inter", ng - 1, 1, 0, pb),
        ag_intra=HierPhase("ag_intra", ni - 1, 1, ng - 1, pa))


def _perm_neighbors(perm: Sequence[Tuple[int, int]],
                    d: int) -> Tuple[int, int]:
    """(dst, src) of node d under a permutation list."""
    dst = next(b for a, b in perm if a == d)
    src = next(a for a, b in perm if b == d)
    return dst, src


def hier_op_stream(n: int, ni: int, s_inter: int = 1,
                   include_ag: bool = True,
                   integrity: bool = False) -> List[List[Op]]:
    """Per-node op streams of the hierarchical schedule, derived from
    `hier_program` (the same phases/perms/message-ids `ops.ring_hier`
    lowers — no second definition).

    RS: (ni-1) raw intra subring hops -> program-order handoff -> (ng-1)
    inter codec hops, each sliced into ``s_inter`` double-buffered
    payloads (`ops.ring._send`'s scan: send slice k, encode k+1, recv
    k).  AG (``include_ag``): the phases in reverse — (ng-1) inter
    gather hops (encode once, forward verbatim: one payload per hop)
    then (ni-1) raw intra gather hops.  ``integrity`` adds the paired
    chk ops per wire message (pre-send / post-recv, the `ops.ring._send`
    placement) with the program's carry ("rs"/"ag") message ids."""
    prog = hier_program(n, ni, s_inter)
    streams: List[List[Op]] = []
    for d in range(n):
        sink = ListSink()

        def ring_hop(phase: HierPhase, s: int, carry: str,
                     decode: bool = False, accumulate: bool = False,
                     sliced: bool = False) -> None:
            dst, src = _perm_neighbors(phase.perm, d)
            if sliced:
                sink.local("encode", phase.kind, s, 0)
            for k in range(phase.slices):
                if integrity:
                    sink.chk_emit(phase.msg(s, k), carry=carry)
                tag = ((phase.kind, s, k) if sliced else (phase.kind, s))
                sink.ops.append(("send_to", dst, tag))
                if sliced and k + 1 < phase.slices:
                    sink.local("encode", phase.kind, s, k + 1)
                sink.ops.append(("recv_from", src, tag))
                if integrity:
                    sink.chk_arrive(phase.msg(s, k), carry=carry)
                if sliced and decode:
                    sink.local("decode", phase.kind, s, k)
            if accumulate:
                sink.local("accumulate", phase.kind, s)

        for s in range(prog.rs_intra.hops):       # phase A: raw intra RS
            ring_hop(prog.rs_intra, s, "rs", accumulate=True)
        sink.local("handoff", "intra->inter")
        for s in range(prog.rs_inter.hops):       # phase B: sliced codec
            ring_hop(prog.rs_inter, s, "rs", decode=True, sliced=True)
        if include_ag:
            for s in range(prog.ag_inter.hops):   # B': inter all-gather
                ring_hop(prog.ag_inter, s, "ag")
            for s in range(prog.ag_intra.hops):   # A': raw intra gather
                ring_hop(prog.ag_intra, s, "ag")
        streams.append(sink.ops)
    return streams


# ---------------------------------------------------------------------------
# op-stream extraction: reshard transfer program
# ---------------------------------------------------------------------------

class Seg(NamedTuple):
    """One intersection-table segment (mirrors parallel.reshard.Transfer
    without importing jax; tests pin the equivalence)."""

    src: int
    dst: int
    src_off: int
    dst_off: int
    length: int


def reshard_segments(live: int, chunk_src: int,
                     chunk_tgt: int) -> Tuple[Seg, ...]:
    """Source->target shard intersections of a [live] flat vector: cut
    [0, live) at every chunk boundary of either layout.  The jax-free
    twin of `parallel.reshard.intersection_table` — the segments
    PARTITION the live range (asserted)."""
    assert live > 0 and chunk_src > 0 and chunk_tgt > 0
    cuts = {0, live}
    cuts.update(range(chunk_src, live, chunk_src))
    cuts.update(range(chunk_tgt, live, chunk_tgt))
    edges = sorted(cuts)
    table = []
    for a, b in zip(edges, edges[1:]):
        src, dst = a // chunk_src, a // chunk_tgt
        table.append(Seg(src=src, dst=dst, src_off=a - src * chunk_src,
                         dst_off=a - dst * chunk_tgt, length=b - a))
    assert sum(t.length for t in table) == live
    return tuple(table)


def reshard_owners(n_src: int, n_tgt: int) -> Tuple[int, ...]:
    """EF-residual old-device -> new-owner map — THE definition
    (`parallel.reshard.residual_owners` delegates here): contiguous
    groups, every old residual has exactly one new home (mass is
    conserved), fresh devices beyond the assignment start at zero."""
    assert n_src > 0 and n_tgt > 0
    return tuple(i * n_tgt // n_src for i in range(n_src))


def union_layout(live: int, n_src: int, padded_src: int, n_tgt: int,
                 padded_tgt: int) -> Tuple[int, int, int, int]:
    """(chunk_src, chunk_tgt, n_union, seed_len) — THE union-mesh layout
    arithmetic of a mesh-shape change (`parallel.reshard.make_plan`
    consumes this; `verify.mc.reshard_layout` derives its grid cells
    from it).  Shrink: the union layout IS the source layout, no
    seeding; grow: the source re-lays onto n_union devices first with
    the smallest even chunking that holds the live elements."""
    assert padded_src % n_src == 0, (padded_src, n_src)
    assert padded_tgt % n_tgt == 0, (padded_tgt, n_tgt)
    n_union = max(n_src, n_tgt)
    if n_tgt <= n_src:
        chunk_src, seed_len = padded_src // n_src, padded_src
    else:
        chunk_src = -(-live // n_union)
        seed_len = n_union * chunk_src
    return chunk_src, padded_tgt // n_tgt, n_union, seed_len


class SegMove(NamedTuple):
    """One intersection segment as a transfer-program action: a
    ``"xfer"`` crosses the wire (single-pair send/recv, conservation
    message ``msg``), a ``"copy"`` stays resident (never checksummed)."""

    kind: str                  # "xfer" | "copy"
    seg_index: int
    src: int
    dst: int
    src_off: int
    dst_off: int
    length: int
    msg: int


class ResidMove(NamedTuple):
    """One EF-residual ownership move (``"keep"`` stays resident)."""

    kind: str                  # "xfer" | "keep"
    src: int
    dst: int
    msg: int


def reshard_msg_bases(n_segs: int,
                      n_flat_leaves: int) -> Tuple[Tuple[int, ...], int]:
    """(per-leaf message bases, residual base) of the single
    program-wide conservation counter: leaf li's segments are messages
    [li*n_segs, (li+1)*n_segs), the residual moves follow — every
    message in the transfer gets a DISTINCT odd weight (a product of
    two odd per-axis weights would collide across leaves: the PR-12
    class M2 freezes)."""
    return (tuple(li * n_segs for li in range(n_flat_leaves)),
            n_flat_leaves * n_segs)


def reshard_leaf_actions(table: Sequence[Any],
                         base: int = 0) -> List[SegMove]:
    """One flat leaf's transfer actions in table order — THE program
    `parallel.reshard._move_chunk` executes (message ids included) and
    the checker expands."""
    return [SegMove("copy" if t.src == t.dst else "xfer", ti,
                    t.src, t.dst, t.src_off, t.dst_off, t.length,
                    base + ti)
            for ti, t in enumerate(table)]


def reshard_residual_actions(owners: Sequence[int],
                             base: int = 0) -> List[ResidMove]:
    """The EF-residual moves in ascending-source order (the golden
    twin's sum order) — THE program `parallel.reshard._move_residual`
    executes."""
    return [ResidMove("keep" if i == owner else "xfer", i, owner,
                      base + i)
            for i, owner in enumerate(owners)]


def reshard_op_stream(live: int, chunk_src: int, chunk_tgt: int,
                      n_union: int,
                      residual_owners_map: Optional[Sequence[int]] = None,
                      n_flat_leaves: int = 1,
                      integrity: bool = False) -> List[List[Op]]:
    """Per-node op streams of the lowered reshard program
    (`parallel.reshard.lower_apply`), derived from the SAME action
    lists the lowering consumes: per leaf, the intersection segments in
    table order — an exact-length single-pair send/recv when the owner
    changes, a resident copy when it does not — then the EF-residual
    ownership moves in ascending-source order.  ``integrity`` adds the
    paired chk ops with the program-wide message counter
    (`reshard_msg_bases`)."""
    segs = reshard_segments(live, chunk_src, chunk_tgt)
    bases, resid_base = reshard_msg_bases(len(segs), n_flat_leaves)
    sinks = [ListSink() for _ in range(n_union)]

    def xfer(src: int, dst: int, tag: Op, msg: int) -> None:
        assert src < n_union and dst < n_union, (tag, n_union)
        if integrity:
            sinks[src].chk_emit(msg)
        sinks[src].ops.append(("send_to", dst, tag))
        sinks[dst].ops.append(("recv_from", src, tag))
        if integrity:
            sinks[dst].chk_arrive(msg)

    for li in range(n_flat_leaves):
        for act in reshard_leaf_actions(segs, bases[li]):
            if act.kind == "copy":
                if act.src < n_union:
                    sinks[act.src].local("copy", "seg", li, act.seg_index)
                continue
            xfer(act.src, act.dst, ("seg", li, act.seg_index), act.msg)
    if residual_owners_map is not None:
        for ra in reshard_residual_actions(residual_owners_map,
                                           resid_base):
            if ra.kind == "keep":
                sinks[ra.src].local("resid_keep", "resid", ra.src)
                continue
            xfer(ra.src, ra.dst, ("resid", ra.src), ra.msg)
    return [s.ops for s in sinks]


# ---------------------------------------------------------------------------
# the streaming all-gather: schedule + emitter (consumed by
# ops.ring_pallas._ag_stream_kernel AND the checker)
# ---------------------------------------------------------------------------

def ag_schedule(n: int, S: int, n_slots: int) -> Tuple[
        List[int], List[int], List[int], List[int], Set[int], List[int]]:
    """Explicit interleaved emission schedule for the streaming gather —
    THE definition (`ops.ring_pallas._ag_schedule` is this function;
    the kernel consumes it directly and via its SMEM copy).

    Every node runs the SAME emission sequence E (the reference's
    SEND_LOCAL/FORWARD beat multiplexing, hw/all_reduce.sv:891-1086),
    built by simulating one node: per arrival step m, emit own slice m+1
    (while the own phase lasts) and forward arrival m onward unless its
    content is at the last hop.  Because arrivals ARE the upstream's
    emissions in E order, wire slots and semaphores cycle by EMISSION
    index j (mod n_slots on BOTH ends), and a node's m-th arrival has the
    content of E[m] one hop deeper.  Simple closed forms exist only for
    n >= 4 or S <= 2 (for n == 3, S >= 3 the terminal arrivals interleave
    non-contiguously and punch holes in any arithmetic j assignment), so
    the schedule is built explicitly — it is static per (n, S).

    Two properties are asserted here per (n, S) because the kernel's
    safety rests on them:

      P1  m_e(m) < m: arrival m's emission is issued at a consume step
          STRICTLY before step m on the identical upstream program — so
          in the interpreter's lockstep-primitive model the data has
          landed before consume(m) decodes it, and on hardware wait_recv
          can always be satisfied.
      P2  j - m_e(j) <= S: no emission runs more than S ahead of its
          consume step (the own phase emits two frames per step for S-1
          steps, which is the worst case).  With n_slots >= S + 1, the
          overwrite of wire slot j % n_slots (emission j) therefore comes
          after the decode of arrival j - n_slots in program order
          (interpreter safety), and the credit window never dead-ends
          (hardware): emission j's credit waits on downstream consume
          j - n_slots <= m_e(j) - 1, a strictly earlier step, so every
          cross-node dependency edge points from (step m, node) to
          (step < m, neighbor) and the dependency graph is acyclic for
          ARBITRARY S and n.  n_slots = S + 2 adds one slot of margin.

    Since PR 14 the static sweep is no longer the only evidence: the
    full wait/credit protocol over this schedule (`AgStreamEmitter`) is
    explored exhaustively by graftmc over the standard envelope, with
    asynchronous landings — the "statically asserted" ledger row is
    retired (docs/KNOWN_FAILURES.md).

    Returns (content[m], fwd_j[m], own_at[m], own_j[k], own_js,
    tail_own_js):
      content[m]   (chunk_depth_hops - 1) * S + slice of arrival m
      fwd_j[m]     emission index of arrival m's onward forward, -1 if
                   terminal (content at depth n-2)
      own_at[m]    own slice emitted AFTER consuming arrival m (-1 none)
      own_j[k]     emission index of own slice k
      own_js       set(own_j) — membership drives the pre-wait rule
      tail_own_js  own emissions never followed by a same-slot emission
                   (their send semaphores drain at kernel exit)
    """
    total = (n - 1) * S
    own_j = [0] * S
    content = [0] * total
    fwd_j = [-1] * total
    own_at = [-1] * total
    step_at = {0: -1}                   # emission index -> consume step
    j = 0

    def emit_own(k: int) -> None:
        nonlocal j
        own_j[k] = j
        j += 1

    emit_own(0)
    # arrival m's content: my arrival stream is the upstream's emission
    # stream; its k-th own is my depth-0 content (chunk idx-1, slice k),
    # and its forward of ITS arrival m' is my (content[m'] + one hop)
    emissions: List[Tuple[str, int]] = [("own", 0)]     # E, in order

    for m in range(total):
        kind, val = emissions[m]
        content[m] = val if kind == "own" else content[val] + S
        # EXECUTED order within a step: the forward fires inside
        # consume(m), the next own slice after it — emission indices
        # MUST follow that order or the credit pairing slips.  The
        # original transcription assigned own(m+1) the smaller index
        # while the kernel sends fwd(m) first; graftmc's first
        # exhaustive run over this route found the resulting
        # one-credit under-wait as a recv-slot overwrite at
        # (n=5, S=5) — the bug class the static P1/P2 sweep is blind
        # to, and the reason this schedule is now model-checked.
        if content[m] < (n - 2) * S:    # not yet at the last hop
            fwd_j[m] = j
            step_at[j] = m
            j += 1
            emissions.append(("fwd", m))
        if m + 1 < S:
            own_at[m] = m + 1
            step_at[j] = m
            emit_own(m + 1)
            emissions.append(("own", m + 1))
    assert j == total and len(emissions) == total, (j, len(emissions))
    assert sorted(content) == list(range(total))
    assert all(step_at[m] < m for m in range(total)), (n, S)        # P1
    assert all(jj - st <= S for jj, st in step_at.items()), (n, S)  # P2
    # P3 (the invariant the graftmc run added): emission indices follow
    # the EXECUTED per-step order (fwd(m) before own(m+1)), so credit
    # waits happen in ascending j and "emission j waits on downstream
    # consume j - n_slots" holds count-exactly.
    assert all(fwd_j[m] < own_j[own_at[m]] for m in range(total)
               if fwd_j[m] >= 0 and own_at[m] >= 0), (n, S)

    # single-wait bookkeeping for send semaphores: a forward's send is
    # waited at its own consume step; an own send is waited by the NEXT
    # same-slot emission's pre-wait iff that emission exists AND the
    # preceding same-slot emission was an own (forwards self-wait)
    own_js = set(own_j)
    tail_own_js = [oj for oj in own_j
                   if oj + n_slots >= total]   # no same-slot successor
    return content, fwd_j, own_at, own_j, own_js, tail_own_js


def ag_n_slots(n: int, S: int) -> int:
    """THE slot-window rule of the streaming gather: covers the own
    phase's maximum emission lead (== S, P2) with one slot of margin
    (`_ag_stream_call` consumes this)."""
    return min((n - 1) * S, S + 2)


class AgSchedule:
    """Python-table accessor over `ag_schedule` — the checker's and the
    unrolled kernel path's schedule view.  The rolled kernel path
    substitutes an SMEM-reading twin with the same four methods
    (`ops.ring_pallas._SmemAgSchedule`), built from THIS object's
    tables, so there is one schedule and two reading styles."""

    def __init__(self, n: int, S: int, n_slots: int) -> None:
        (self.content_t, self.fwd_j_t, self.own_at_t, self.own_j_t,
         self.own_js, self.tail_own_js) = ag_schedule(n, S, n_slots)

    def content(self, m: int) -> int:
        return self.content_t[m]

    def fwd_j(self, m: int) -> int:
        return self.fwd_j_t[m]

    def own_at(self, m: int) -> int:
        return self.own_at_t[m]

    def own_j(self, k: int) -> int:
        return self.own_j_t[k]

    def is_own_j(self, j: int) -> bool:
        return j >= 0 and j in self.own_js


class AgStreamEmitter:
    """THE HBM-streaming interleaved-emission all-gather program — the
    exact wait/signal/transfer order `_ag_stream_kernel` executes
    (every node runs the identical program; wire slots and semaphores
    cycle by emission index j % n_slots on BOTH ends).  The kernel
    consumes this emitter through its `_KernelSink` with either
    schedule accessor; the checker consumes it through `ListSink`
    (`ag_op_stream`).

    Per arrival m: 1-lag writeback wait, wire wait, the onward forward
    (emission fwd_j(m): pre-wait if the previous same-slot emission was
    an un-waited own send, credit past the window, send), decode into
    the st window, the forward's own send-drain wait, credit signal,
    writeback start — then the next own-slice emission if this step
    schedules one (ld window, pre-wait, encode, own-store window,
    credit, send).  ``lockstep=True`` swaps decode ahead of the forward
    (the interpreter's primitive-lockstep ordering; hardware keeps
    forward-then-decode for overlap — both orders are checked)."""

    def __init__(self, n: int, S: int,
                 n_slots: Optional[int] = None) -> None:
        self.n = n
        self.S = S
        self.total = (n - 1) * S
        self.n_slots = ag_n_slots(n, S) if n_slots is None else n_slots
        self.sched = AgSchedule(n, S, self.n_slots)

    def send_own(self, sink: OpSink, k: Any, acc: Any) -> None:
        j = acc.own_j(k)
        sink.dma_start("ld", k, ("ld", k - 2))
        @sink.when(acc.is_own_j(j - self.n_slots))
        def _pre_wait() -> None:      # previous same-slot emission was an
            sink.wait_send(j - self.n_slots)   # own send (unwaited) AND
                                      # its frame lives in this buffer
                                      # slot: drain before overwriting
        sink.dma_wait("ld", k)
        sink.encode(j, src=k)
        @sink.when(k >= 2)
        def _own_slot() -> None:      # own-store VMEM window reuse
            sink.dma_wait("ownwb", k - 2)
        sink.local("own_store", k)    # the replica stores its own wire
        sink.dma_start("ownwb", k, ("ownwb", k - 2))      # bytes
        @sink.when(j >= self.n_slots)
        def _credit() -> None:
            sink.credit_wait()
        sink.send(j)

    def consume(self, sink: OpSink, m: Any, acc: Any,
                lockstep: bool = False) -> None:
        @sink.when(m >= 1)
        def _wb_prev() -> None:       # 1-lag single wait: st slot reuse
            sink.dma_wait("wb", m - 1)      # at m covers wb(m-2)
        sink.wait_recv(m)
        jf = acc.fwd_j(m)
        fwd = jf >= 0                 # -1 when arrival m is terminal

        def start_forward() -> None:
            @sink.when(acc.is_own_j(jf - self.n_slots))
            def _pre_wait() -> None:
                sink.wait_send(jf - self.n_slots)
            @sink.when(jf >= self.n_slots)
            def _credit() -> None:
                sink.credit_wait()
            sink.send(jf, src=m)      # forward straight out of the
                                      # arrival's recv slot

        if lockstep:
            # interpreter primitive-lockstep ordering: all reads first,
            # then emissions (see the kernel docstring); hardware keeps
            # forward-then-decode for overlap
            sink.decode(m)
            sink.when(fwd)(start_forward)
        else:
            sink.when(fwd)(start_forward)
            sink.decode(m)
        @sink.when(fwd)
        def _fwd_done() -> None:      # recv slot is upstream's next
            sink.wait_send(jf)        # target: drain my forward first
        sink.credit_signal()
        sink.dma_start("wb", m, ("wb", m - 2))

    def prologue(self, sink: OpSink, acc: Any) -> None:
        sink.barrier()
        self.send_own(sink, 0, acc)

    def step(self, sink: OpSink, m: Any, acc: Any,
             lockstep: bool = False) -> None:
        self.consume(sink, m, acc, lockstep=lockstep)
        k = acc.own_at(m)             # next own-slice emission, if this
        @sink.when(k >= 0)            # arrival step schedules one
        def _own() -> None:
            self.send_own(sink, k, acc)

    def epilogue(self, sink: OpSink) -> None:
        sink.dma_wait("wb", self.total - 1)
        sink.dma_wait("ownwb", self.S - 1)
        if self.S >= 2:
            sink.dma_wait("ownwb", self.S - 2)
        for jk in self.sched.tail_own_js:     # own sends with no
            sink.wait_send(jk)                # same-slot successor
        sink.credit_drain(min(self.total, self.n_slots))

    def stream(self, lockstep: bool = False) -> Tuple[List[Op], int]:
        sink = ListSink()
        self.prologue(sink, self.sched)
        for m in range(self.total):
            self.step(sink, m, self.sched, lockstep=lockstep)
        self.epilogue(sink)
        return sink.ops, self.n_slots


def ag_op_stream(n: int, S: int, n_slots: Optional[int] = None,
                 lockstep: bool = False) -> Tuple[List[Op], int]:
    """The checked view of `AgStreamEmitter` (one emitter, two
    consumers).  ``n_slots`` overrides the protocol window (the
    anti-vacuity mutants shrink it); the default is `ag_n_slots`."""
    return AgStreamEmitter(n, S, n_slots=n_slots).stream(
        lockstep=lockstep)


# ---------------------------------------------------------------------------
# the KV-handoff pair program (consumed by serve.handoff AND the checker)
# ---------------------------------------------------------------------------

class HandoffMove(NamedTuple):
    """One gathered page block crossing the pair: pool index in
    layer-major K-then-V order (== its odd-multiplier index in
    `ops.integrity.gathered_page_checksums`, so a block-order change is
    a weight change M2 sees)."""

    pool: int
    msg: int


def handoff_program(n_layers: int) -> List[HandoffMove]:
    """THE block order of one KV migration — `serve.handoff.lower_apply`
    iterates exactly this list to drive its gather/ppermute/scatter
    trio per block, and the ledger-compare weights are the same ``msg``
    indices."""
    return [HandoffMove(i, i) for i in range(2 * n_layers)]


def handoff_op_stream(n_layers: int,
                      integrity: bool = False) -> List[List[Op]]:
    """Per-node op streams of the KV-handoff pair program, derived from
    `handoff_program`: the source gathers and sends each page block in
    block order; the destination receives and scatters each.  With
    ``integrity`` the per-block ledger compare rides as paired chk ops
    (carry "page", weight = the block's gathered_page_checksums odd
    multiplier) and the replicated verdict psum as a symmetric vote
    exchange — the destination's vote depends on every landed block
    (it is computed from the scattered pages), the source's only on its
    ledger."""
    src, dst = ListSink(), ListSink()
    for mv in handoff_program(n_layers):
        if integrity:
            src.chk_emit(mv.msg, carry="page")
        src.local("gather", mv.pool)
        src.ops.append(("send_to", 1, ("pool", mv.pool)))
        dst.ops.append(("recv_from", 0, ("pool", mv.pool)))
        if integrity:
            dst.chk_arrive(mv.msg, carry="page")
        dst.local("scatter", mv.pool)
    if integrity:
        # the conservation/verdict psum: each side contributes its vote
        # and consumes the peer's — the destination's vote is data-
        # dependent on every scattered block above (program order)
        src.ops.append(("send_to", 1, ("vote", 0)))
        src.ops.append(("recv_from", 1, ("vote", 1)))
        dst.ops.append(("send_to", 0, ("vote", 1)))
        dst.ops.append(("recv_from", 0, ("vote", 0)))
    return [src.ops, dst.ops]


# ---------------------------------------------------------------------------
# M2: the static checksum-weight conservation pass
# ---------------------------------------------------------------------------

def check_weight_conservation(streams: Sequence[Any]) -> List[str]:
    """M2 — the static pass over a checked program's ``chk_emit`` /
    ``chk_arrive`` ops (PR-12's weight-collision bug class, caught by
    review twice, frozen as a tool): per conservation carry,

      - every emission message has arrival partners, 1:1 by count, and
        every partner carries the SAME weight (a send/recv weighted
        differently can never telescope to zero — the verdict would
        trip on clean wires, or worse, stay green on corrupt ones);
      - every weight is ODD (odd = invertible mod 2^32: single-word
        corruption can never vanish from the weighted sum);
      - weights are program-distinct: two DIFFERENT messages sharing a
        weight alias in the conservation sum — a swap of their payloads
        cancels exactly (the collision class).

    ``streams``: a single op list (RingModel — every node runs it) or a
    per-node list of op lists (PairModel).  Returns violation messages
    (empty = clean); a program with no chk ops is trivially clean —
    COVERAGE is J12's job, soundness of the weights is M2's."""
    if streams and streams[0] and isinstance(streams[0][0], str):
        node_streams: Sequence[Sequence[Op]] = [streams]  # single program
    else:
        node_streams = streams
    emits: Dict[Tuple[str, Any], List[int]] = {}
    arrives: Dict[Tuple[str, Any], List[int]] = {}
    out: List[str] = []
    for ops in node_streams:
        for op in ops:
            if op[0] not in ("chk_emit", "chk_arrive"):
                continue
            _, carry, msg, w = op
            (emits if op[0] == "chk_emit" else arrives).setdefault(
                (carry, msg), []).append(w)
            if w % 2 == 0:
                out.append(f"M2: message {carry}/{msg} has EVEN weight "
                           f"{w} — a single-word corruption at an even "
                           "weight can vanish mod 2^32")
    for key in sorted(set(emits) | set(arrives), key=str):
        es, ar = emits.get(key, []), arrives.get(key, [])
        carry, msg = key
        if len(es) != len(ar):
            out.append(f"M2: message {carry}/{msg} has {len(es)} "
                       f"emission(s) but {len(ar)} arrival(s) — every "
                       "emission needs exactly one arrival partner")
        ws = set(es) | set(ar)
        if len(ws) > 1:
            out.append(f"M2: message {carry}/{msg} weighted "
                       f"inconsistently across emit/arrive: {sorted(ws)}")
    by_carry: Dict[str, Dict[int, Set[Any]]] = {}
    for (carry, msg), ws in list(emits.items()) + list(arrives.items()):
        for w in ws:
            by_carry.setdefault(carry, {}).setdefault(w, set()).add(msg)
    for carry, wmap in sorted(by_carry.items()):
        for w, msgs in sorted(wmap.items()):
            if len(msgs) > 1:
                out.append(
                    f"M2: weight collision in carry {carry!r}: messages "
                    f"{sorted(msgs, key=str)} all weighted {w} — their "
                    "corruptions alias in the conservation sum (the "
                    "PR-12 class)")
    return out


# ---------------------------------------------------------------------------
# execution model 1: the ring credit-window protocol
# ---------------------------------------------------------------------------

class RingState:
    """Mutable interleaving state of a RingModel run.  Cloned only at
    branch points; the counterexample trace is a shared linked list so
    clones are O(state), not O(history)."""

    __slots__ = ("pc", "arrived", "slots", "credits", "flight",
                 "inflight_slots", "trace")

    def __init__(self, n: int, n_slots: int) -> None:
        self.pc = [0] * n
        self.arrived = [False] * n
        self.slots = [[-1] * n_slots for _ in range(n)]
        self.credits = [0] * n
        self.flight: Set[Tuple[int, int]] = set()
        # (dst, wire slot) -> number of in-flight transfers targeting it
        self.inflight_slots: Dict[Tuple[int, int], int] = {}
        self.trace: Optional[Tuple[Any, Any]] = None

    def clone(self) -> "RingState":
        st = RingState.__new__(RingState)
        st.pc = list(self.pc)
        st.arrived = list(self.arrived)
        st.slots = [list(s) for s in self.slots]
        st.credits = list(self.credits)
        st.flight = set(self.flight)
        st.inflight_slots = dict(self.inflight_slots)
        st.trace = self.trace
        return st

    def key(self) -> Tuple[Any, ...]:
        return (tuple(self.pc), tuple(self.arrived),
                tuple(map(tuple, self.slots)), tuple(self.credits),
                frozenset(self.flight))


class RingModel:
    """Small-step semantics of the ring credit-window protocol: n nodes
    running the IDENTICAL op stream, wire slots cycling mod n_slots,
    blocking semaphores, asynchronous landings.  Violations raised as
    ProtocolError; message wording is stable API (the fuzz backend's
    callers match on it)."""

    route = "ring"

    def __init__(self, n: int, ops: Sequence[Op], n_slots: int,
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.n = n
        self.ops = list(ops)
        self.n_slots = n_slots
        self.meta = dict(meta or {})
        self.total_sends = sum(1 for op in self.ops if op[0] == "send")
        self.credit_bound = min(self.total_sends, n_slots) \
            if self.total_sends else n_slots
        # strict_terminal adds the at-exit checks (no undecoded frame
        # left in a window, no leaked credits) on top of the legacy
        # simulator semantics; simulate_rs_protocol turns it off to
        # keep its published failure wording exact
        self.strict_terminal = True
        self.send_pos: Dict[int, int] = {
            op[1]: i for i, op in enumerate(self.ops) if op[0] == "send"}
        # emissions whose decode is NOT preceded by its wait_recv in
        # program order: landing q then commutes with NOTHING — the
        # decode-before-landing interleaving is realizable and must be
        # branched on, never resolved by an eager landing (in a correct
        # stream every decode is guarded and this set is empty; a
        # mutated stream that drops a wait_recv lands here — the POR
        # soundness hole the review's mutation sweep caught)
        first_wait: Dict[int, int] = {}
        self.unguarded_decodes: Set[int] = set()
        for i, op in enumerate(self.ops):
            if op[0] == "wait_recv" and op[1] not in first_wait:
                first_wait[op[1]] = i
            elif op[0] == "decode" and op[1] not in self.unguarded_decodes:
                if first_wait.get(op[1]) is None:
                    self.unguarded_decodes.add(op[1])

    # -- helpers -----------------------------------------------------------

    def _ctx(self) -> str:
        return " ".join(f"{k}={v}" for k, v in self.meta.items())

    def init_state(self) -> RingState:
        return RingState(self.n, self.n_slots)

    def node_count(self) -> int:
        return self.n

    def _landed(self, st: RingState, i: int, q: int) -> bool:
        pos = self.send_pos.get(q)
        return pos is not None and st.pc[i] > pos and (i, q) not in st.flight

    def _runnable(self, st: RingState, i: int) -> bool:
        if st.pc[i] >= len(self.ops):
            return False
        op = self.ops[st.pc[i]]
        kind = op[0]
        if kind == "barrier":
            return (not st.arrived[i]) or (st.arrived[(i - 1) % self.n]
                                           and st.arrived[(i + 1) % self.n])
        if kind == "wait_send":
            return self._landed(st, i, op[1])
        if kind == "credit_wait":
            return st.credits[i] >= 1
        if kind == "wait_recv":
            return st.slots[i][op[1] % self.n_slots] == op[1]
        if kind == "credit_drain":
            return st.credits[i] >= op[1]
        return True       # send / decode / credit_signal / dma / local

    def enabled(self, st: RingState) -> List[Action]:
        acts: List[Action] = [("node", i) for i in range(self.n)
                              if self._runnable(st, i)]
        acts.extend(("wire", s, q) for (s, q) in st.flight)
        return acts

    # -- transition --------------------------------------------------------

    def apply(self, st: RingState, act: Action) -> None:
        if act[0] == "wire":
            _, src, q = act
            dst = (src + 1) % self.n
            slot = q % self.n_slots
            st.trace = (("wire", src, q, dst, slot), st.trace)
            if st.slots[dst][slot] != -1:
                raise ProtocolError(
                    "recv_overwrite",
                    f"recv-slot overwrite: emission {q} landed on "
                    f"undecoded frame {st.slots[dst][slot]} in node "
                    f"{dst}'s slot {slot} ({self._ctx()})")
            st.slots[dst][slot] = q
            st.flight.discard((src, q))
            k = (dst, slot)
            c = st.inflight_slots.get(k, 0) - 1
            if c:
                st.inflight_slots[k] = c
            else:
                st.inflight_slots.pop(k, None)
            return
        i = act[1]
        op = self.ops[st.pc[i]]
        kind = op[0]
        st.trace = (("node", i, op), st.trace)
        if kind == "barrier":
            st.arrived[i] = True          # signal phase
            if not (st.arrived[(i - 1) % self.n]
                    and st.arrived[(i + 1) % self.n]):
                return                    # signaled; wait phase blocks
        elif kind == "send":
            q = op[1]
            slot = q % self.n_slots
            if any(s == i and t % self.n_slots == slot
                   for (s, t) in st.flight):
                raise ProtocolError(
                    "send_overwrite",
                    f"send-slot overwrite: emission {q} encoded over an "
                    f"in-flight frame in slot {slot} ({self._ctx()})")
            st.flight.add((i, q))
            k = ((i + 1) % self.n, slot)
            st.inflight_slots[k] = st.inflight_slots.get(k, 0) + 1
        elif kind == "decode":
            g = op[1]
            slot = g % self.n_slots
            got = st.slots[i][slot]
            if got != g:
                raise ProtocolError(
                    "ordering",
                    f"ordering corruption: decode of emission {g} found "
                    f"{'empty slot' if got == -1 else got} "
                    f"({self._ctx()})")
            st.slots[i][slot] = -1
        elif kind == "credit_signal":
            left = (i - 1) % self.n
            st.credits[left] += 1
            if st.credits[left] > self.credit_bound:
                raise ProtocolError(
                    "credit",
                    f"credit overflow: node {left} holds "
                    f"{st.credits[left]} credits for a {self.credit_bound}"
                    f"-slot window ({self._ctx()})")
        elif kind == "credit_wait":
            st.credits[i] -= 1
        elif kind == "credit_drain":
            st.credits[i] -= op[1]
        # wait_send / wait_recv / dma_* / encode / update / local:
        # guard already checked in _runnable; pc advance only
        st.pc[i] += 1

    # -- termination -------------------------------------------------------

    def finished(self, st: RingState) -> bool:
        return (not st.flight
                and all(p >= len(self.ops) for p in st.pc))

    def check_terminal(self, st: RingState) -> None:
        if not self.strict_terminal:
            return
        for i in range(self.n):
            for slot, got in enumerate(st.slots[i]):
                if got != -1:
                    raise ProtocolError(
                        "termination",
                        f"undecoded frame {got} left in node {i}'s slot "
                        f"{slot} at termination ({self._ctx()})")
        for i, c in enumerate(st.credits):
            if c != 0:
                raise ProtocolError(
                    "credit",
                    f"credit leak: node {i} terminates holding {c} "
                    f"credits ({self._ctx()})")

    def deadlock_message(self, st: RingState) -> str:
        nxt = [self.ops[p] if p < len(self.ops) else None for p in st.pc]
        return (f"protocol deadlock: {self._ctx()} pc={st.pc} next={nxt} "
                f"credits={st.credits} in_flight={sorted(st.flight)}")

    # -- partial-order reduction -------------------------------------------

    def pick_action(self, st: RingState,
                    acts: Sequence[Action]) -> Optional[Action]:
        """Singleton persistent set: an action that commutes with every
        other enabled action (and cannot race a future one — in-flight
        landings stay enabled until executed, so every latent conflict
        has an enabled witness).  An action whose violation condition is
        already live is returned too: the schedule freedom that makes it
        fire exists, so exploring it first IS the counterexample.
        Returns None when only mutually-dependent actions remain (full
        branch)."""
        for act in acts:
            if act[0] == "wire":
                _, src, q = act
                dst = (src + 1) % self.n
                slot = q % self.n_slots
                if st.slots[dst][slot] != -1:
                    return act            # violation live: explore it
                if q in self.unguarded_decodes:
                    continue              # decode(q) may run BEFORE this
                                          # landing (no wait_recv guard):
                                          # both orders must be explored
                if st.inflight_slots.get((dst, slot), 0) > 1:
                    continue              # racing same-slot landing
                if self._slot_sensitive(st, dst, slot):
                    continue              # dst decode of this slot pending
                if self._send_pending(st, src, slot):
                    continue              # src send-overwrite race
                return act
            i = act[1]
            op = self.ops[st.pc[i]]
            kind = op[0]
            if kind == "send":
                slot = op[1] % self.n_slots
                if any(s == i and t % self.n_slots == slot
                       for (s, t) in st.flight):
                    return act            # violation live: explore it
                return act
            if kind in ("decode", "wait_recv"):
                slot = op[1] % self.n_slots
                if st.inflight_slots.get((i, slot), 0) > 0:
                    continue              # landing may race this slot
                return act
            if kind == "credit_signal":
                left = (i - 1) % self.n
                if st.credits[left] >= self.credit_bound:
                    return act            # overflow live: explore it
                return act
            # barrier / credit_wait / credit_drain / wait_send / dma /
            # encode / update / local: commute with everything enabled
            return act
        return None

    def _slot_sensitive(self, st: RingState, dst: int, slot: int) -> bool:
        # only an ENABLED partner can conflict: decode is always
        # enabled, but a wait_recv blocked on this slot is not a
        # partner — the landing merely enables it (they commute)
        if st.pc[dst] >= len(self.ops):
            return False
        op = self.ops[st.pc[dst]]
        return op[0] == "decode" and op[1] % self.n_slots == slot

    def _send_pending(self, st: RingState, src: int, slot: int) -> bool:
        if st.pc[src] >= len(self.ops):
            return False
        op = self.ops[st.pc[src]]
        return op[0] == "send" and op[1] % self.n_slots == slot


# ---------------------------------------------------------------------------
# execution model 2: tag-matched pair transfers (the XLA ppermute hop)
# ---------------------------------------------------------------------------

class PairState:
    """Mutable interleaving state of a PairModel run."""

    __slots__ = ("pc", "flight", "landed", "trace")

    def __init__(self, n: int) -> None:
        self.pc = [0] * n
        self.flight: Set[Tuple[int, int, Any]] = set()
        self.landed: Set[Tuple[int, int, Any]] = set()
        self.trace: Optional[Tuple[Any, Any]] = None

    def clone(self) -> "PairState":
        st = PairState.__new__(PairState)
        st.pc = list(self.pc)
        st.flight = set(self.flight)
        st.landed = set(self.landed)
        st.trace = self.trace
        return st

    def key(self) -> Tuple[Any, ...]:
        return (tuple(self.pc), frozenset(self.flight),
                frozenset(self.landed))


class PairModel:
    """Small-step semantics of directed tag-matched transfers: a send
    never blocks (the payload is in flight until its landing event), a
    recv blocks until its exact (src, tag) payload has landed and then
    consumes it.  Models the lowered single-pair ppermute programs
    (reshard) and the subring hop chains (hier), where the failure modes
    are mismatched program orders (deadlock) and orphaned payloads
    (ordering)."""

    route = "pair"

    def __init__(self, streams: Sequence[Sequence[Op]],
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.streams = [list(s) for s in streams]
        self.n = len(self.streams)
        self.meta = dict(meta or {})

    def _ctx(self) -> str:
        return " ".join(f"{k}={v}" for k, v in self.meta.items())

    def init_state(self) -> PairState:
        return PairState(self.n)

    def node_count(self) -> int:
        return self.n

    def _runnable(self, st: PairState, i: int) -> bool:
        if st.pc[i] >= len(self.streams[i]):
            return False
        op = self.streams[i][st.pc[i]]
        if op[0] == "recv_from":
            return (op[1], i, op[2]) in st.landed
        return True

    def enabled(self, st: PairState) -> List[Action]:
        acts: List[Action] = [("node", i) for i in range(self.n)
                              if self._runnable(st, i)]
        acts.extend(("wire",) + t for t in st.flight)
        return acts

    def apply(self, st: PairState, act: Action) -> None:
        if act[0] == "wire":
            t = (act[1], act[2], act[3])
            st.trace = (("wire",) + t, st.trace)
            st.flight.discard(t)
            st.landed.add(t)
            return
        i = act[1]
        op = self.streams[i][st.pc[i]]
        st.trace = (("node", i, op), st.trace)
        if op[0] == "send_to":
            t = (i, op[1], op[2])
            if t in st.flight or t in st.landed:
                raise ProtocolError(
                    "send_overwrite",
                    f"duplicate emission: payload {op[2]!r} {i}->{op[1]} "
                    f"sent while a previous copy is outstanding "
                    f"({self._ctx()})")
            st.flight.add(t)
        elif op[0] == "recv_from":
            st.landed.discard((op[1], i, op[2]))
        st.pc[i] += 1

    def finished(self, st: PairState) -> bool:
        return (not st.flight
                and all(st.pc[i] >= len(self.streams[i])
                        for i in range(self.n)))

    def check_terminal(self, st: PairState) -> None:
        if st.landed:
            orphan = sorted(st.landed)[0]
            raise ProtocolError(
                "termination",
                f"orphan payload (ordering corruption): {orphan[2]!r} "
                f"{orphan[0]}->{orphan[1]} landed but never consumed "
                f"({self._ctx()}; {len(st.landed)} total)")

    def deadlock_message(self, st: PairState) -> str:
        nxt = [self.streams[i][p] if p < len(self.streams[i]) else None
               for i, p in enumerate(st.pc)]
        return (f"protocol deadlock: {self._ctx()} pc={st.pc} next={nxt} "
                f"in_flight={sorted(st.flight)}")

    def pick_action(self, st: PairState,
                    acts: Sequence[Action]) -> Optional[Action]:
        # every action commutes with every other: tags are unique per
        # payload, sends never block, landings only enable — so the
        # first enabled action is always a singleton persistent set
        return acts[0] if acts else None


# ---------------------------------------------------------------------------
# execution model 3: single-node async-DMA programs (the paged gather)
# ---------------------------------------------------------------------------


class GatherState:
    """Mutable interleaving state of a GatherModel run — one program
    counter plus the two async-DMA populations (issued-not-landed,
    landed-not-waited)."""

    __slots__ = ("pc", "flight", "landed", "trace")

    def __init__(self) -> None:
        self.pc = 0
        self.flight: Set[Tuple[str, int]] = set()
        self.landed: Set[Tuple[str, int]] = set()
        self.trace: Optional[Tuple[Any, Any]] = None

    def clone(self) -> "GatherState":
        st = GatherState.__new__(GatherState)
        st.pc = self.pc
        st.flight = set(self.flight)
        st.landed = set(self.landed)
        st.trace = self.trace
        return st

    def key(self) -> Tuple[Any, ...]:
        return (self.pc, frozenset(self.flight), frozenset(self.landed))


class GatherModel:
    """Small-step semantics of a single-node async-DMA program (the
    paged gather-attend schedule): ``dma_start`` issues a transfer whose
    completion is an ASYNCHRONOUS hardware event (a ``land`` action at
    an arbitrary later scheduler step); ``dma_wait`` blocks until that
    page's transfer has landed, then consumes its semaphore.  The
    dynamic failure mode this model owns is semaphore-slot aliasing —
    the semaphores cycle mod ``depth``, so a start whose slot still
    holds an unconsumed (in-flight or landed-but-unwaited) transfer
    would let the EARLIER completion satisfy the LATER wait: an
    overlapping-slot read serving attend data that never landed.  The
    static obligations (exact live-page coverage, per-node DMA
    discipline) run first in `verify.mc._static_violations`."""

    route = "gather"

    def __init__(self, ops: Sequence[Op], depth: int,
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.ops = list(ops)
        self.depth = depth
        self.meta = dict(meta or {})

    def _ctx(self) -> str:
        return " ".join(f"{k}={v}" for k, v in self.meta.items())

    def init_state(self) -> GatherState:
        return GatherState()

    def node_count(self) -> int:
        return 1

    def _runnable(self, st: GatherState) -> bool:
        if st.pc >= len(self.ops):
            return False
        op = self.ops[st.pc]
        if op[0] == "dma_wait":
            return (op[1], op[2]) in st.landed
        return True

    def enabled(self, st: GatherState) -> List[Action]:
        acts: List[Action] = [("node", 0)] if self._runnable(st) else []
        acts.extend(("land", chan, i) for (chan, i) in sorted(st.flight))
        return acts

    def apply(self, st: GatherState, act: Action) -> None:
        if act[0] == "land":
            _, chan, i = act
            st.trace = (act, st.trace)
            st.flight.discard((chan, i))
            st.landed.add((chan, i))
            return
        op = self.ops[st.pc]
        st.trace = (("node", 0, op), st.trace)
        if op[0] == "dma_start":
            _, chan, i, _conf = op
            slot = i % self.depth
            clash = sorted((c, j) for (c, j) in (st.flight | st.landed)
                           if c == chan and j % self.depth == slot)
            if clash:
                raise ProtocolError(
                    "dma",
                    f"overlapping-slot read: {chan}[{i}] starts into "
                    f"semaphore slot {slot} while {clash[0][0]}"
                    f"[{clash[0][1]}] is unconsumed there — its landing "
                    f"would satisfy the wrong wait ({self._ctx()})")
            st.flight.add((chan, i))
        elif op[0] == "dma_wait":
            st.landed.discard((op[1], op[2]))
        st.pc += 1

    def finished(self, st: GatherState) -> bool:
        return (st.pc >= len(self.ops) and not st.flight
                and not st.landed)

    def check_terminal(self, st: GatherState) -> None:
        # drain is part of `finished`; a started-never-waited stream can
        # never terminate (its landed entry persists) and surfaces as
        # the static exit-drain violation / a dynamic deadlock instead
        return

    def deadlock_message(self, st: GatherState) -> str:
        nxt = self.ops[st.pc] if st.pc < len(self.ops) else None
        return (f"protocol deadlock: {self._ctx()} pc={st.pc} next={nxt} "
                f"in_flight={sorted(st.flight)} "
                f"landed={sorted(st.landed)}")

    def pick_action(self, st: GatherState,
                    acts: Sequence[Action]) -> Optional[Action]:
        # a landing only moves a transfer flight -> landed: the
        # start-clash predicate reads the UNION of the two sets, so
        # landings commute with every node step and with each other,
        # and node steps are the only pc mutators.  The first enabled
        # action is therefore always a singleton persistent set —
        # violations included: a clashing start raises on apply in
        # EVERY interleaving (the predicate is interleaving-invariant),
        # so no schedule freedom is needed to witness it.
        return acts[0] if acts else None


# ---------------------------------------------------------------------------
# the serving control-plane emitter (graftsched, verify.sched)
# ---------------------------------------------------------------------------

# request lifecycle vocabulary — the exact strings
# `runtime.requests.{WAITING,PREFILL,DECODE,FINISHED}` carry, redeclared
# here so the emitter (and the sched model built on it) never imports
# the numpy-bearing runtime package.  tests/test_sched.py pins the
# equality, the same discipline as OPT_N_STATE above.
SCHED_WAITING = "waiting"
SCHED_PREFILL = "prefill"
SCHED_DECODE = "decode"
SCHED_FINISHED = "finished"


class SchedEmitter:
    """ONE definition of every discrete policy decision the serving
    control plane makes — the PR-14 emitter discipline applied to the
    scheduler/fleet/autoscaler instead of a wire protocol.

    The wire emitters above produce op *streams*; the control plane's
    analogue is its transition *rules*: watermark admission, LIFO
    eviction, least-loaded routing, kill-victim choice, the
    migrate/reroute/replay trichotomy, the CUSUM detector step and the
    scale/shed gates.  Each rule is a pure function of plain ints and
    strings, emitted once here and consumed twice —

      - by the real hot paths (`serve.scheduler.ContinuousBatcher`,
        `serve.fleet.ServeFleet`, `serve.autoscale.Autoscaler`,
        `tune.adapt.DriftDetector`) as thin delegates, and
      - by the exhaustive control-plane model (`verify.sched.SchedModel`)
        the graftmc corpus explores,

    so the checker's verdicts are about the SHIPPED policies, not a
    transcription of them (tests pin the delegation by identity and by
    source inspection — there is no second definition to drift).

    Selection rules take parallel value sequences and return an INDEX
    into the caller's candidate list (or None when empty): the caller
    keeps its own object types (Request/Replica vs the model's plain
    lists) while the comparison logic stays single-sourced.
    """

    # -- batcher: commitment-aware watermark admission ----------------------

    @staticmethod
    def replay_target(n_tokens: int) -> int:
        """Positions a (re)admission must prefill before decode resumes:
        every position the cache must already hold — prompt + generated
        minus the newest token, whose K/V the resuming decode step
        writes itself (== ``Request.n_tokens``)."""
        return n_tokens

    @staticmethod
    def admission_need(replay_len: int) -> int:
        """Positions the free-page watermark must cover to admit: the
        replay plus ONE decode step, so admission can never immediately
        thrash (the PR-10 admit-thrash bug class)."""
        return replay_len + 1

    @staticmethod
    def committed_target(state: str, replay_len: int,
                         n_tokens: int) -> int:
        """Positions a LIVE request will claim without a new admission
        decision: its full replay + first decode while prefilling, its
        next position while decoding."""
        return (replay_len + 1 if state == SCHED_PREFILL
                else n_tokens + 1)

    @staticmethod
    def committed_outstanding(entries: Sequence[Tuple[int, int]]) -> int:
        """Pages promised but not yet allocated (allocation is lazy),
        over (target_pages, held_pages) pairs for every live request."""
        return sum(max(0, target - held) for target, held in entries)

    @staticmethod
    def admit_ok(free: int, committed: int, need: int) -> bool:
        """The watermark: admit only while the UNCOMMITTED free pages
        cover the candidate's own need."""
        return free - committed >= need

    @staticmethod
    def pick_victim(admit_seqs: Sequence[int]) -> Optional[int]:
        """LIFO eviction: the NEWEST-admitted candidate (index into the
        caller's page-holding, non-protected live list).  Newest-first
        is the termination argument: the oldest request monotonically
        progresses, so any workload whose single worst request fits the
        pool terminates."""
        if not admit_seqs:
            return None
        return max(range(len(admit_seqs)),
                   key=lambda i: admit_seqs[i])

    @staticmethod
    def pick_oldest(admit_seqs: Sequence[int]) -> Optional[int]:
        """Oldest-admitted candidate — the prefill-chunk scheduling
        order (a long prompt never starves an older one)."""
        if not admit_seqs:
            return None
        return min(range(len(admit_seqs)),
                   key=lambda i: admit_seqs[i])

    @staticmethod
    def decode_order(admit_seqs: Sequence[int]) -> List[int]:
        """Decode-batch service order: oldest first (eviction cascades
        triggered by page claims then only ever hit newer requests)."""
        return sorted(range(len(admit_seqs)),
                      key=lambda i: admit_seqs[i])

    @staticmethod
    def prefill_chunk_len(chunk: int, replay_len: int,
                          start: int) -> int:
        """True (unpadded) token count of this tick's prefill chunk."""
        return min(chunk, replay_len - start)

    # -- fleet: routing + membership ----------------------------------------

    @staticmethod
    def route_least_loaded(loads: Sequence[Tuple[int, int]]
                           ) -> Optional[int]:
        """Deterministic least-loaded routing with stable ties: index of
        the minimum (load, replica_idx) pair — what makes a seeded
        fleet run replay exactly."""
        if not loads:
            return None
        return min(range(len(loads)), key=lambda i: loads[i])

    @staticmethod
    def pick_kill_victim(loads: Sequence[Tuple[int, int]]
                         ) -> Optional[int]:
        """Chaos kill target: the loaded-MOST candidate (maximum blast
        radius), stable ties by lowest replica idx."""
        if not loads:
            return None
        return max(range(len(loads)),
                   key=lambda i: (loads[i][0], -loads[i][1]))

    @staticmethod
    def migration_action(state: str, has_pages: bool,
                         migratable: bool) -> str:
        """The kill path's per-request trichotomy: 'migrate' live KV to
        a survivor when the pool buffers are still addressable,
        'reroute' a pageless request (zero work lost — NOT a replay),
        'replay' otherwise (KV lost, generated tokens kept)."""
        if (migratable and state in (SCHED_DECODE, SCHED_PREFILL)
                and has_pages):
            return "migrate"
        if not has_pages:
            return "reroute"
        return "replay"

    # -- autoscaler: CUSUM detection + action gates -------------------------

    @staticmethod
    def load_residual(queue_depth: float, target_per_decode: float,
                      n_decode: int) -> float:
        """The controller's detector input: relative queue-depth excess
        over what the decode pool should absorb."""
        return queue_depth / (target_per_decode * n_decode) - 1.0

    @staticmethod
    def cusum_step(pos: float, neg: float, cooldown: int, resid: float,
                   drift: float, threshold: float, cooldown_steps: int
                   ) -> Tuple[float, float, int,
                              Optional[Tuple[str, float]]]:
        """One two-sided CUSUM update with hysteresis — the
        `tune.adapt.DriftDetector` step as a pure function of
        (pos, neg, cooldown).  Returns the new statistics plus None or
        the ("slow"|"fast", stat) trip; a trip resets both sides and
        arms the cooldown (no opposite-direction trip can land inside
        the window — the no-flap invariant the sched model checks)."""
        if cooldown > 0:
            return pos, neg, cooldown - 1, None
        r = float(resid)
        pos = max(0.0, pos + r - drift)
        neg = max(0.0, neg + (-r) - drift)
        if pos >= threshold:
            trip = ("slow", pos)
        elif neg >= threshold:
            trip = ("fast", neg)
        else:
            return pos, neg, 0, None
        return 0.0, 0.0, cooldown_steps, trip

    @staticmethod
    def scale_up_fallback(n_prefill_pure: int,
                          rebalance_idx: int) -> str:
        """With no spare device left, a 'slow' trip rebalances a SURPLUS
        pure-prefill replica to role='both' — never the last one — else
        the trip is suppressed (counted, actionless)."""
        return ("rebalance"
                if n_prefill_pure >= 2 and rebalance_idx >= 0
                else "suppress")

    @staticmethod
    def scale_down_ok(n_decode_pure: int, min_decode: int,
                      queue_depth: float, scale_in_idx: int) -> bool:
        """A 'fast' trip drains a pure decode replica only above the
        floor, with an empty queue, and with a valid target."""
        return (n_decode_pure > min_decode and queue_depth == 0
                and scale_in_idx >= 0)

    @staticmethod
    def shed_action(hold: bool, free_frac: float, lo: float,
                    hi: float) -> Optional[str]:
        """The admission shed valve's hysteresis band on the free-page
        fraction: 'shed_on' below lo, 'shed_off' above hi, None inside
        the band (the lo < hi gap is what keeps the valve from
        chattering at the boundary)."""
        if not hold and free_frac < lo:
            return "shed_on"
        if hold and free_frac > hi:
            return "shed_off"
        return None


# the singleton every consumer binds — tests assert delegation by
# IDENTITY against this exact object (`serve.scheduler._RULES is
# SCHED_RULES`), the PR-14 TestDelegationIdentity discipline
SCHED_RULES = SchedEmitter()
