"""Counterexample replay: a violating interleaving as a per-node op
trace and as a Perfetto-loadable timeline.

The model checker attaches the exact action sequence that reached a
violation (`mc.Violation.trace`).  Two renderings:

  format_trace   the per-node op trace as text — every node's column of
                 executed ops with the global scheduler step of each,
                 followed by the interleaved tail around the violation.
  perfetto_trace the same interleaving through `obs.timeline`'s
                 Chrome-trace exporter: node programs as host-thread
                 lanes (one span per op), wire transfers as ticket
                 spans on the collective-queue lane (send step ->
                 landing step, so an in-flight frame is a visible bar),
                 and the violation as a flow-terminating instant.  Load
                 the JSON in https://ui.perfetto.dev — a deadlock's
                 wait-for cycle shows as every node lane ending in a
                 blocked wait with no ticket span able to retire.

Scheduler steps have no wall-clock meaning; the export places step k at
k microseconds so Perfetto's timeline is simply the interleaving order.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Tuple

from ..obs import events as events_lib
from ..obs import timeline as timeline_lib
from .mc import Violation

_STEP_NS = 1_000          # one scheduler step = 1 us on the timeline
_OP_DUR_NS = 800


def _op_text(entry: Tuple[Any, ...]) -> str:
    if entry[0] == "wire":
        if len(entry) == 5:               # ring: (wire, src, q, dst, slot)
            _, src, q, dst, slot = entry
            return f"emission {q} lands {src}->{dst} slot {slot}"
        _, src, dst, tag = entry          # pair: (wire, src, dst, tag)
        return f"payload {tag!r} lands {src}->{dst}"
    _, i, op = entry
    return " ".join(str(x) for x in op)


def format_trace(violation: Violation, tail: int = 24) -> str:
    """The violating interleaving as text: one column per node (each op
    with its global scheduler step), then the interleaved last ``tail``
    steps, then the violation."""
    trace = violation.trace
    per_node: Dict[int, List[str]] = {}
    for step, entry in enumerate(trace):
        if entry[0] == "node":
            per_node.setdefault(entry[1], []).append(
                f"[{step}] {_op_text(entry)}")
    ctx = " ".join(f"{k}={v}" for k, v in violation.meta.items())
    lines = [f"counterexample ({ctx}):",
             f"  {violation.kind}: {violation.message}", "",
             "per-node op trace:"]
    for i in sorted(per_node):
        lines.append(f"  node {i}:")
        for s in per_node[i]:
            lines.append(f"    {s}")
    lines.append("")
    lines.append(f"interleaved tail (last {min(tail, len(trace))} of "
                 f"{len(trace)} steps):")
    for step in range(max(0, len(trace) - tail), len(trace)):
        entry = trace[step]
        actor = (f"node {entry[1]}" if entry[0] == "node" else "wire  ")
        lines.append(f"  [{step:4d}] {actor}  {_op_text(entry)}")
    lines.append(f"  [{len(trace):4d}] VIOLATION  {violation.message}")
    return "\n".join(lines)


def _host_events(violation: Violation) -> List[Dict[str, Any]]:
    """The interleaving as obs.events-shaped host events for
    `obs.timeline.chrome_trace`."""
    trace = violation.trace
    out: List[Dict[str, Any]] = []
    # wire transfers: send step -> landing step as queue-lane tickets.
    # uids are STABLE enumeration indices, never str hashes — the
    # export must be byte-identical run to run (PYTHONHASHSEED) and
    # collision-free across timeline.py's uid % 64 lane assignment
    send_step: Dict[Any, int] = {}
    uid_of: Dict[Any, int] = {}

    def uid_for(key: Any) -> int:
        return uid_of.setdefault(key, len(uid_of))

    for step, entry in enumerate(trace):
        t_ns = step * _STEP_NS
        if entry[0] == "node":
            _, i, op = entry
            if op[0] in ("send", "send_to"):
                send_step[(i,) + tuple(op[1:])] = step
            out.append({"kind": events_lib.SPAN, "name": _op_text(entry),
                        "t_unix_ns": t_ns, "dur_ns": _OP_DUR_NS,
                        "tid": i, "attrs": {"node": i, "op": op[0]}})
            continue
        # landing: close the ticket opened by the matching send
        if len(entry) == 5:
            _, src, q, dst, slot = entry
            key: Any = (src, q)
            name = f"wire {src}->{dst} emission {q}"
        else:
            _, src, dst, tag = entry
            key = (src, dst, tag)
            name = f"wire {src}->{dst} {tag!r}"
        start = send_step.pop(key, step)
        out.append({"kind": events_lib.SPAN, "name": name,
                    "t_unix_ns": start * _STEP_NS,
                    "dur_ns": max(_OP_DUR_NS, (step - start) * _STEP_NS),
                    "tid": 0,
                    "attrs": {"lane": "queue", "uid": uid_for(key),
                              "src": src}})
    # transfers still in flight at the violation: open-ended tickets
    for key, start in sorted(send_step.items(), key=lambda kv: kv[1]):
        out.append({"kind": events_lib.SPAN,
                    "name": f"wire IN FLIGHT {key}",
                    "t_unix_ns": start * _STEP_NS,
                    "dur_ns": (len(trace) - start) * _STEP_NS,
                    "tid": 0,
                    "attrs": {"lane": "queue", "uid": uid_for(key),
                              "in_flight": True}})
    out.append({"kind": events_lib.INSTANT,
                "name": f"VIOLATION: {violation.kind}",
                "t_unix_ns": len(trace) * _STEP_NS, "tid": 0,
                "attrs": {"message": violation.message,
                          **violation.meta}})
    return out


def perfetto_trace(violation: Violation) -> Dict[str, Any]:
    """The violating interleaving as a Chrome-trace JSON object (the
    same exporter the telemetry plane uses — obs.timeline)."""
    header = {"source": "graftmc", "violation": violation.kind,
              **{str(k): v for k, v in violation.meta.items()}}
    return timeline_lib.chrome_trace(_host_events(violation),
                                     header=header)


def export_counterexample(model: Any, violation: Violation,
                          out_dir: str) -> Tuple[str, str]:
    """Write both renderings next to each other; returns (txt, json)
    paths.  Called by the corpus on any violation so a red
    `make modelcheck` always leaves an inspectable artifact."""
    os.makedirs(out_dir, exist_ok=True)
    route = str(violation.meta.get("route", getattr(model, "route", "mc")))
    cell = "_".join(str(violation.meta[k]) for k in sorted(violation.meta)
                    if k != "route")
    base = os.path.join(out_dir, f"mc_counterexample_{route}"
                        + (f"_{cell}" if cell else ""))
    txt = base + ".txt"
    with open(txt, "w") as fh:
        fh.write(format_trace(violation) + "\n")
    js = base + ".json"
    timeline_lib.write(js, perfetto_trace(violation))
    return txt, js
