"""graftsched — the exhaustive control-plane model of the serving
plane (docs/MODELCHECK.md "The control-plane family").

The wire families (flat/streaming/ag/hier/reshard/handoff/gather) model
PROTOCOLS: op streams with asynchronous landings.  The serving control
plane — `serve.scheduler.ContinuousBatcher`, `serve.fleet.ServeFleet`,
`serve.autoscale.Autoscaler` — is the same shape of artifact one level
up: a bounded concurrent state machine whose bug classes (admit-thrash,
page leaks, evict/readmit livelock, scaler flapping) were each caught by
EXAMPLE during development, never exhaustively.  This module closes that
gap the PR-14 way: a small-step model whose every policy decision is a
call into the ONE-definition `opstream.SchedEmitter` rules the real hot
paths also consume, explored exhaustively by `verify.mc.check` over the
(R x P x K x fault) envelope.

The model (one `apply` per micro-phase of a fleet tick, mirroring
`ServeFleet.tick`'s exact order):

  boundary      the only genuine nondeterminism besides handoff faults:
                in a "kill" cell a replica preemption may land at ANY
                tick boundary (before routing — the same
                `state_buffers_alive` gate the real chaos site has), or
                not at all.  Exhaustive over fault timing.
  route         arrivals -> least-loaded prefill replica (held while
                the shed valve is closed; deferred, never dropped).
  drain         completed prefills hand off to decode replicas.  The
                handoff is split begin/land so a mid-handoff state (dst
                pages reserved, src pages still resident) is a real
                explored state; in a "handoff-fail" cell the land may
                fail (bounded by the fault budget) and the request
                degrades to the replay tier.  A full decode fleet PARKS
                the request (backpressure, not replay).
  engine        one replica's engine tick: watermark admission, decode
                page claims (oldest first), then the prefill chunk —
                whose page demand may evict the newest selected
                decoder, exactly `ServeEngine._tick`'s order.
  decode_drain  evictions on a decode replica replay through a prefill
                worker (front of queue).
  scaler        `Autoscaler.observe_tick`: the CUSUM step, scale/
                rebalance/shed gates, then the liveness bookkeeping.

Checked invariants (ProtocolError kinds):

  conservation  free + promised + resident == pool per ALIVE replica at
                EVERY state — mid-handoff (the in-flight reservation
                counts at the destination) and post-kill included;
                free >= 0.  Pages on a dead replica die with its pool.
  watermark     at every admission EVENT the sum of committed targets
                on that replica must fit the pool ("over-commit").
                Scoped to admissions because a kill-path migration may
                legally over-commit a survivor transiently — the
                eviction tier absorbs it; admission never may.
  liveness      every submitted request reaches FINISHED on every path
                (checked terminally + via the tick bound).
  livelock      a strictly-increasing progress measure: total generated
                tokens must grow within ``STALL_LIMIT`` consecutive
                ticks while any request is unfinished (the evict/
                readmit livelock class), plus a hard per-cell tick
                bound.
  flap          no opposite-direction scale actions within the cooldown
                window (the hysteresis invariant).

Anti-vacuity mutants (``mutate=``): "leak_evict" (eviction returns one
page short -> conservation), "drop_watermark" (admission skips the
watermark -> over-commit), "no_evict" (a dry pool never evicts ->
livelock), "drop_cooldown" (the detector's hysteresis — re-arm
cooldown AND drift slack — disabled -> flap).  Two ride as GRAFTMC_FIXTURE fixtures; the full
mutation sweep is pinned POR-vs-naive by tests/test_sched.py.

Soundness boundary: page_size is 1 (pages == positions, `pages_for` is
the identity) and prompt_len is 1 — page granularity is an exact linear
rescale the allocator fuzz covers, not a scheduling behavior.  All
nondeterminism is fault TIMING; every deterministic segment is a
singleton persistent set (`pick_action`), so POR explores exactly the
fault-timing tree and the naive DFS must agree cell-for-cell.

No jax/numpy import anywhere — plain-Python state exploration, same as
the rest of the package.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .opstream import (SCHED_DECODE, SCHED_FINISHED, SCHED_PREFILL,
                       SCHED_RULES, SCHED_WAITING, Action, ProtocolError)

__all__ = ["SchedConfig", "SchedState", "SchedModel", "build_sched",
           "sched_cells", "SCHED_FAULTS", "SCHED_MUTANTS",
           "SCHED_VIOLATION_KINDS"]

SCHED_FAULTS: Tuple[str, ...] = ("none", "kill", "handoff-fail")

# the anti-vacuity mutation surface (SchedModel(mutate=...)) and the
# invariant each one must trip — tests/test_sched.py sweeps these
SCHED_MUTANTS: Dict[str, str] = {
    "leak_evict": "conservation",
    "drop_watermark": "watermark",
    "no_evict": "livelock",
    "drop_cooldown": "flap",
}

SCHED_VIOLATION_KINDS: Tuple[str, ...] = (
    "conservation", "watermark", "liveness", "livelock", "flap")

# request record layout (plain lists: cheap clone + hashable key)
R_STATE, R_REP, R_HELD, R_REPLAY, R_DONE, R_GEN, R_SEQ = range(7)
# replica record layout
P_ALIVE, P_ROLE, P_FREE, P_WAIT = range(4)

STALL_LIMIT = 10


class SchedConfig:
    """One envelope cell's constants.  The detector constants are the
    REAL rules at model scale: drift/threshold shrunk so trips are
    reachable inside a handful of ticks (the rule functions themselves
    are the shared `SCHED_RULES` — only the operating point moves)."""

    def __init__(self, n_reqs: int, pages: int, n_replicas: int,
                 fault: str) -> None:
        assert fault in SCHED_FAULTS, fault
        self.n_reqs = n_reqs
        self.pages = pages               # usable pages per replica pool
        self.n_replicas = n_replicas
        self.fault = fault
        self.prompt_len = 1
        # worst-case footprint prompt + max_new must fit one pool — the
        # `validate_shape` precondition the liveness claim leans on
        self.max_new = 1 if pages == 2 else (2 if pages == 3 else 3)
        self.slots = 2                   # decode slots per replica
        self.prefill_chunk = 1
        self.spares = 1                  # spare devices for scale-out
        # autoscaler operating point (see class docstring)
        self.target_per_decode = 1.0
        self.drift = 0.5
        self.threshold = 1.0
        # = the no-flap window; clean runs cannot flap because after
        # any trip the detector sleeps cooldown ticks, so an opposite
        # trip lands at earliest T + cooldown + 1 — OUTSIDE the window
        self.cooldown_ticks = 3
        self.min_decode = 1
        self.shed_lo = 0.10
        self.shed_hi = 0.30

    def roles(self) -> List[str]:
        if self.n_replicas == 1:
            return ["both"]
        return ["prefill"] + ["decode"] * (self.n_replicas - 1)


class SchedState:
    """The full control-plane state: requests, per-replica ledgers, the
    in-flight handoff reservation, detector statistics and the liveness
    bookkeeping.  ``trace`` is the reversed action list (shared-tail
    cons cells, the `RingState` idiom)."""

    __slots__ = ("phase", "intake", "reqs", "reps", "inflight", "tried",
                 "pos", "neg", "cooldown", "hold", "spares",
                 "fault_left", "tick", "last_dir", "last_tick", "stall",
                 "last_tokens", "seq", "trace")

    def __init__(self, cfg: SchedConfig) -> None:
        self.phase: Tuple[Any, ...] = ("boundary",)
        self.intake: List[int] = list(range(cfg.n_reqs))
        self.reqs: List[List[Any]] = [
            [SCHED_WAITING, -1, 0, cfg.prompt_len, 0, 0, -1]
            for _ in range(cfg.n_reqs)]
        self.reps: List[List[Any]] = [
            [1, role, cfg.pages, []] for role in cfg.roles()]
        self.inflight: Optional[Tuple[int, int, int]] = None
        self.tried: List[int] = []       # drain attempts this tick
        self.pos = 0.0
        self.neg = 0.0
        self.cooldown = 0
        self.hold = False
        self.spares = cfg.spares
        self.fault_left = 0 if cfg.fault == "none" else 1
        self.tick = 0
        self.last_dir = ""               # last scale action direction
        self.last_tick = -1
        self.stall = 0
        self.last_tokens = 0
        self.seq = 0                     # admission-order counter
        self.trace: Optional[Tuple[Any, Any]] = None

    def clone(self) -> "SchedState":
        st = SchedState.__new__(SchedState)
        st.phase = self.phase
        st.intake = list(self.intake)
        st.reqs = [list(r) for r in self.reqs]
        st.reps = [[r[P_ALIVE], r[P_ROLE], r[P_FREE], list(r[P_WAIT])]
                   for r in self.reps]
        st.inflight = self.inflight
        st.tried = list(self.tried)
        st.pos = self.pos
        st.neg = self.neg
        st.cooldown = self.cooldown
        st.hold = self.hold
        st.spares = self.spares
        st.fault_left = self.fault_left
        st.tick = self.tick
        st.last_dir = self.last_dir
        st.last_tick = self.last_tick
        st.stall = self.stall
        st.last_tokens = self.last_tokens
        st.seq = self.seq
        st.trace = self.trace
        return st

    def key(self) -> Tuple[Any, ...]:
        return (self.phase, tuple(self.intake),
                tuple(tuple(r) for r in self.reqs),
                tuple((r[P_ALIVE], r[P_ROLE], r[P_FREE],
                       tuple(r[P_WAIT])) for r in self.reps),
                self.inflight, tuple(self.tried), self.pos, self.neg,
                self.cooldown, self.hold, self.spares, self.fault_left,
                self.tick, self.last_dir, self.last_tick, self.stall,
                self.last_tokens, self.seq)


class SchedModel:
    """Small-step model conforming to the `verify.mc.check` contract.
    Every policy decision is a `SCHED_RULES` call — the model never
    re-derives a rule the serving plane ships."""

    route = "sched"

    def __init__(self, cfg: SchedConfig, meta: Dict[str, Any],
                 mutate: Optional[str] = None) -> None:
        assert mutate is None or mutate in SCHED_MUTANTS, mutate
        self.cfg = cfg
        self.meta = dict(meta)
        self.mutate = mutate
        # generous liveness bound: a clean run terminates well inside
        # it on every fault timing; exceeding it IS the livelock verdict
        self.max_ticks = (16 + 8 * cfg.n_reqs * cfg.max_new
                          + 6 * cfg.n_replicas)

    # -- mc.check contract ---------------------------------------------------

    def node_count(self) -> int:
        return self.cfg.n_replicas + self.cfg.spares

    def init_state(self) -> SchedState:
        return SchedState(self.cfg)

    def finished(self, st: SchedState) -> bool:
        return all(q[R_STATE] == SCHED_FINISHED for q in st.reqs)

    def check_terminal(self, st: SchedState) -> None:
        for rid, q in enumerate(st.reqs):
            if q[R_STATE] != SCHED_FINISHED:
                raise ProtocolError(
                    "liveness",
                    f"request {rid} never finished (state "
                    f"{q[R_STATE]!r}, {q[R_GEN]}/{self.cfg.max_new} "
                    "tokens) — an admitted request must terminate")
        for k, rep in enumerate(st.reps):
            if rep[P_ALIVE] and rep[P_FREE] != self.cfg.pages:
                raise ProtocolError(
                    "conservation",
                    f"replica {k} pool not fully free at termination: "
                    f"{rep[P_FREE]}/{self.cfg.pages} — pages leaked")

    def deadlock_message(self, st: SchedState) -> str:
        return (f"control-plane deadlock at phase {st.phase} tick "
                f"{st.tick} ({self._ctx()})")

    def enabled(self, st: SchedState) -> List[Action]:
        ph = st.phase[0]
        # quiescence IS termination (the run_random contract: no enabled
        # action + finished() -> clean exit); mid-tick phases still step
        # so the trailing scaler/conservation checks run
        if ph == "boundary" and self.finished(st):
            return []
        if ph == "boundary":
            acts: List[Action] = [("tick",)]
            if (self.cfg.fault == "kill" and st.fault_left
                    and self._n_alive(st) > 1):
                acts.append(("kill",))
            return acts
        if ph == "land":
            acts = [("land_ok",)]
            if self.cfg.fault == "handoff-fail" and st.fault_left:
                acts.append(("land_fail",))
            return acts
        return [("step",)]

    def pick_action(self, st: SchedState,
                    acts: Sequence[Action]) -> Optional[Action]:
        # every phase is deterministic except the two genuine fault
        # races (kill timing, handoff landing): a lone enabled action is
        # its own persistent set — no other action exists to commute
        # with — so POR replays exactly the fault-timing tree and the
        # naive DFS must agree (pinned by tests/test_sched.py)
        return acts[0] if len(acts) == 1 else None

    def apply(self, st: SchedState, act: Action) -> None:
        ph = st.phase[0]
        actor = st.phase[1] if ph == "engine" else 0
        st.trace = ((("node", actor, (ph,) + act + (st.tick,)),
                     st.trace))
        if ph == "boundary":
            if st.tick > self.max_ticks:
                raise ProtocolError(
                    "livelock",
                    f"tick bound {self.max_ticks} exceeded with "
                    "unfinished requests — the progress measure is not "
                    f"decreasing ({self._ctx()})")
            if act == ("kill",):
                st.fault_left -= 1
                self._kill(st, self._chaos_victim(st))
            st.phase = ("route",)
        elif ph == "route":
            self._route_arrivals(st)
        elif ph == "drain":
            self._drain_step(st)
        elif ph == "land":
            self._land(st, act)
        elif ph == "engine":
            self._engine_tick(st, st.phase[1])
        elif ph == "decode_drain":
            self._decode_drain(st)
        else:
            assert ph == "scaler", ph
            self._scaler(st)
        self._check_conservation(st)

    # -- shared helpers ------------------------------------------------------

    def _ctx(self) -> str:
        return ", ".join(f"{k}={v}" for k, v in self.meta.items())

    def _n_alive(self, st: SchedState) -> int:
        return sum(1 for r in st.reps if r[P_ALIVE])

    def _n_tokens(self, q: List[Any]) -> int:
        g = q[R_GEN]
        return self.cfg.prompt_len + (g - 1 if g else 0)

    def _target(self, q: List[Any]) -> int:
        return SCHED_RULES.committed_target(
            q[R_STATE], q[R_REPLAY], self._n_tokens(q))

    def _on(self, st: SchedState, k: int) -> List[int]:
        """Live (slot-holding) request ids on replica k."""
        return [rid for rid, q in enumerate(st.reqs)
                if q[R_REP] == k
                and q[R_STATE] in (SCHED_PREFILL, SCHED_DECODE)]

    def _load(self, st: SchedState, k: int) -> int:
        return len(self._on(st, k)) + len(st.reps[k][P_WAIT])

    def _alive_idx(self, st: SchedState,
                   role: Optional[str] = None) -> List[int]:
        out = [k for k, r in enumerate(st.reps) if r[P_ALIVE]]
        if role is not None:
            out = [k for k in out
                   if st.reps[k][P_ROLE] in (role, "both")]
        return out

    def _route_to_prefill(self, st: SchedState, rid: int,
                          front: bool) -> None:
        cands = self._alive_idx(st, "prefill")
        pos = SCHED_RULES.route_least_loaded(
            [(self._load(st, k), k) for k in cands])
        assert pos is not None, "no prefill-capable replica alive"
        wait = st.reps[cands[pos]][P_WAIT]
        if front:
            wait.insert(0, rid)
        else:
            wait.append(rid)

    def _replay_fallback(self, st: SchedState, rid: int) -> None:
        """The degraded tier: KV pages released (or lost with a dead
        pool), generated tokens kept, front-of-line re-prefill."""
        q = st.reqs[rid]
        k = q[R_REP]
        if k >= 0 and q[R_HELD]:
            st.reps[k][P_FREE] += q[R_HELD]
        q[R_HELD] = 0
        q[R_STATE] = SCHED_WAITING
        q[R_DONE] = 0
        q[R_REP] = -1
        q[R_REPLAY] = SCHED_RULES.replay_target(self._n_tokens(q))
        self._route_to_prefill(st, rid, front=True)

    # -- per-phase transitions ----------------------------------------------

    def _route_arrivals(self, st: SchedState) -> None:
        if not st.hold:
            while st.intake:
                self._route_to_prefill(st, st.intake.pop(0), front=False)
        st.phase = ("drain",)

    def _drain_step(self, st: SchedState) -> None:
        """One prefill->decode handoff attempt per apply (so the
        mid-handoff state is explorable); parks mark ``tried`` and the
        scan resumes next apply.  No candidate left -> engine phase."""
        for k in self._alive_idx(st):
            if st.reps[k][P_ROLE] != "prefill":
                continue                 # 'both' decodes locally
            done = sorted(
                (st.reqs[rid][R_SEQ], rid) for rid in self._on(st, k)
                if st.reqs[rid][R_STATE] == SCHED_DECODE)
            for _, rid in done:
                if rid in st.tried:
                    continue
                st.tried.append(rid)
                n = st.reqs[rid][R_HELD]
                dsts = [d for d in self._alive_idx(st, "decode")
                        if len(self._on(st, d)) < self.cfg.slots
                        and st.reps[d][P_FREE] >= n]
                pos = SCHED_RULES.route_least_loaded(
                    [(self._load(st, d), d) for d in dsts])
                if pos is None:
                    return               # parked: retry next tick
                if n == 0:
                    self._replay_fallback(st, rid)
                    return
                dst = dsts[pos]
                st.reps[dst][P_FREE] -= n       # dst reservation
                st.inflight = (rid, dst, n)
                st.phase = ("land",)
                return
        st.phase = ("engine", 0)

    def _land(self, st: SchedState, act: Action) -> None:
        assert st.inflight is not None
        rid, dst, n = st.inflight
        q = st.reqs[rid]
        if act == ("land_ok",):
            # src pages free, the dst reservation becomes resident; the
            # adopt bumps admit_seq (the real `ContinuousBatcher.adopt`)
            st.reps[q[R_REP]][P_FREE] += q[R_HELD]
            q[R_REP] = dst
            q[R_HELD] = n
            st.seq += 1
            q[R_SEQ] = st.seq
        else:
            # injected handoff fault: the reservation unwinds and the
            # request degrades to the replay tier (tokens kept)
            st.fault_left -= 1
            st.reps[dst][P_FREE] += n
            self._replay_fallback(st, rid)
        st.inflight = None
        st.phase = ("drain",)

    def _engine_tick(self, st: SchedState, k: int) -> None:
        rep = st.reps[k]
        if rep[P_ALIVE]:
            role = rep[P_ROLE]
            if role != "decode":
                self._admit(st, k)
            # decode page claims FIRST, then the prefill chunk — whose
            # demand may evict the newest selected decoder (the batch
            # is re-filtered below): ServeEngine._tick's exact order
            dec: List[int] = []
            if role != "prefill":
                cands = [rid for rid in self._on(st, k)
                         if st.reqs[rid][R_STATE] == SCHED_DECODE]
                for pos in SCHED_RULES.decode_order(
                        [st.reqs[rid][R_SEQ] for rid in cands]):
                    rid = cands[pos]
                    if st.reqs[rid][R_STATE] != SCHED_DECODE:
                        continue         # evicted by an older sibling
                    if self._ensure(st, k, rid,
                                    self._n_tokens(st.reqs[rid]) + 1):
                        dec.append(rid)
            pre: Optional[Tuple[int, int]] = None
            if role != "decode":
                cands = [rid for rid in self._on(st, k)
                         if st.reqs[rid][R_STATE] == SCHED_PREFILL]
                pos = SCHED_RULES.pick_oldest(
                    [st.reqs[rid][R_SEQ] for rid in cands])
                if pos is not None:
                    rid = cands[pos]
                    q = st.reqs[rid]
                    n_true = SCHED_RULES.prefill_chunk_len(
                        self.cfg.prefill_chunk, q[R_REPLAY], q[R_DONE])
                    if self._ensure(st, k, rid, q[R_DONE] + n_true):
                        pre = (rid, n_true)
            if pre is not None:
                rid, n_true = pre
                q = st.reqs[rid]
                q[R_DONE] += n_true
                if q[R_DONE] >= q[R_REPLAY]:
                    q[R_STATE] = SCHED_DECODE
                    if q[R_GEN] == 0:
                        # a fresh prefill's sample IS the first token
                        self._token(st, rid)
            for rid in dec:
                if st.reqs[rid][R_STATE] != SCHED_DECODE:
                    continue             # evicted by the prefill claim
                self._token(st, rid)
        nxt = st.phase[1] + 1
        st.phase = (("engine", nxt) if nxt < len(st.reps)
                    else ("decode_drain",))

    def _admit(self, st: SchedState, k: int) -> None:
        rep = st.reps[k]
        while rep[P_WAIT]:
            live = self._on(st, k)
            if len(live) >= self.cfg.slots:
                break
            rid = rep[P_WAIT][0]
            q = st.reqs[rid]
            need = SCHED_RULES.admission_need(q[R_REPLAY])
            committed = SCHED_RULES.committed_outstanding(
                [(self._target(st.reqs[r]), st.reqs[r][R_HELD])
                 for r in live])
            if (self.mutate != "drop_watermark"
                    and not SCHED_RULES.admit_ok(rep[P_FREE], committed,
                                                 need)):
                break
            rep[P_WAIT].pop(0)
            q[R_STATE] = SCHED_PREFILL
            q[R_REP] = k
            st.seq += 1
            q[R_SEQ] = st.seq
            # the INDEPENDENT watermark-safety invariant, algebraically
            # equivalent to admit_ok on a non-over-committed pool (see
            # docs/MODELCHECK.md): checked at the admission event itself
            total = sum(self._target(st.reqs[r])
                        for r in self._on(st, k))
            if total > self.cfg.pages:
                raise ProtocolError(
                    "watermark",
                    f"admission over-commit on replica {k}: committed "
                    f"targets sum to {total} pages > pool "
                    f"{self.cfg.pages} after admitting request {rid} "
                    f"({self._ctx()})")

    def _ensure(self, st: SchedState, k: int, rid: int,
                n_positions: int) -> bool:
        """Grow rid's page set to n_positions, LIFO-evicting while the
        pool is dry.  False: no evictable victim (cannot proceed)."""
        q = st.reqs[rid]
        rep = st.reps[k]
        while q[R_HELD] < n_positions:
            if rep[P_FREE] > 0:
                rep[P_FREE] -= 1
                q[R_HELD] += 1
                continue
            if self.mutate == "no_evict":
                return False
            victims = [r for r in self._on(st, k)
                       if r != rid and st.reqs[r][R_HELD] > 0]
            pos = SCHED_RULES.pick_victim(
                [st.reqs[r][R_SEQ] for r in victims])
            if pos is None:
                return False
            self._evict(st, k, victims[pos])
        return True

    def _evict(self, st: SchedState, k: int, vid: int) -> None:
        v = st.reqs[vid]
        back = v[R_HELD] - (1 if self.mutate == "leak_evict" else 0)
        st.reps[k][P_FREE] += back
        v[R_HELD] = 0
        v[R_STATE] = SCHED_WAITING
        v[R_DONE] = 0
        v[R_REP] = -1
        v[R_REPLAY] = SCHED_RULES.replay_target(self._n_tokens(v))
        st.reps[k][P_WAIT].insert(0, vid)   # evicted work has priority

    def _token(self, st: SchedState, rid: int) -> None:
        q = st.reqs[rid]
        q[R_GEN] += 1
        if q[R_GEN] >= self.cfg.max_new:
            k = q[R_REP]
            st.reps[k][P_FREE] += q[R_HELD]
            q[R_HELD] = 0
            q[R_REP] = -1
            q[R_STATE] = SCHED_FINISHED

    def _decode_drain(self, st: SchedState) -> None:
        for k in self._alive_idx(st):
            if st.reps[k][P_ROLE] != "decode":
                continue
            while st.reps[k][P_WAIT]:
                self._replay_fallback(st, st.reps[k][P_WAIT].pop(0))
        st.phase = ("scaler",)

    def _signals(self, st: SchedState) -> Dict[str, Any]:
        alive = self._alive_idx(st)
        queue = (sum(len(st.reps[k][P_WAIT]) for k in alive)
                 + len(st.intake))
        pure_p = [k for k in alive if st.reps[k][P_ROLE] == "prefill"]
        pure_d = [k for k in alive if st.reps[k][P_ROLE] == "decode"]
        rb = SCHED_RULES.route_least_loaded(
            [(self._load(st, k), k) for k in pure_p])
        si = SCHED_RULES.route_least_loaded(
            [(self._load(st, k), k) for k in pure_d])
        free = sum(st.reps[k][P_FREE] for k in alive)
        return {
            "queue_depth": float(queue),
            "n_decode": len(self._alive_idx(st, "decode")),
            "n_prefill_pure": len(pure_p),
            "n_decode_pure": len(pure_d),
            "rebalance_idx": pure_p[rb] if rb is not None else -1,
            "scale_in_idx": pure_d[si] if si is not None else -1,
            "free_frac": free / (max(1, len(alive)) * self.cfg.pages),
        }

    def _flap_check(self, st: SchedState, direction: str) -> None:
        if (st.last_dir and direction != st.last_dir
                and st.tick - st.last_tick <= self.cfg.cooldown_ticks):
            raise ProtocolError(
                "flap",
                f"opposite-direction scale actions inside the cooldown "
                f"window: {st.last_dir}@tick{st.last_tick} then "
                f"{direction}@tick{st.tick} (cooldown "
                f"{self.cfg.cooldown_ticks}) ({self._ctx()})")
        st.last_dir = direction
        st.last_tick = st.tick

    def _scaler(self, st: SchedState) -> None:
        cfg = self.cfg
        sig = self._signals(st)
        resid = SCHED_RULES.load_residual(
            sig["queue_depth"], cfg.target_per_decode,
            max(1, sig["n_decode"]))
        # the hysteresis-regression mutant disables BOTH halves of the
        # detector's damping (the re-arm cooldown and the drift slack)
        hyst_off = self.mutate == "drop_cooldown"
        st.pos, st.neg, st.cooldown, trip = SCHED_RULES.cusum_step(
            st.pos, st.neg, st.cooldown, resid,
            0.0 if hyst_off else cfg.drift, cfg.threshold,
            0 if hyst_off else cfg.cooldown_ticks)
        if trip is not None and trip[0] == "slow":
            if st.spares > 0:
                st.spares -= 1
                st.reps.append([1, "decode", cfg.pages, []])
                self._flap_check(st, "out")
            elif SCHED_RULES.scale_up_fallback(
                    sig["n_prefill_pure"],
                    sig["rebalance_idx"]) == "rebalance":
                st.reps[sig["rebalance_idx"]][P_ROLE] = "both"
                self._flap_check(st, "out")
        elif trip is not None:
            if SCHED_RULES.scale_down_ok(
                    sig["n_decode_pure"], cfg.min_decode,
                    sig["queue_depth"], sig["scale_in_idx"]):
                self._flap_check(st, "in")
                self._kill(st, sig["scale_in_idx"])
        shed = SCHED_RULES.shed_action(st.hold, sig["free_frac"],
                                       cfg.shed_lo, cfg.shed_hi)
        if shed == "shed_on":
            st.hold = True
        elif shed == "shed_off":
            st.hold = False
        # liveness bookkeeping: the progress measure is total generated
        # tokens — it must grow within STALL_LIMIT ticks while any
        # request is unfinished (the evict/readmit livelock class)
        total = sum(q[R_GEN] for q in st.reqs)
        unfinished = any(q[R_STATE] != SCHED_FINISHED for q in st.reqs)
        if unfinished and total == st.last_tokens:
            st.stall += 1
            if st.stall >= STALL_LIMIT:
                raise ProtocolError(
                    "livelock",
                    f"no token progress for {STALL_LIMIT} consecutive "
                    "ticks with unfinished requests — evict/readmit "
                    f"livelock ({self._ctx()})")
        else:
            st.stall = 0
        st.last_tokens = total
        st.tick += 1
        st.tried = []
        st.phase = ("boundary",)

    # -- membership change ---------------------------------------------------

    def _chaos_victim(self, st: SchedState) -> int:
        cands = (self._alive_idx(st, "decode")
                 or self._alive_idx(st))
        pos = SCHED_RULES.pick_kill_victim(
            [(self._load(st, k), k) for k in cands])
        assert pos is not None
        return cands[pos]

    def _kill(self, st: SchedState, victim: int) -> None:
        """`ServeFleet.kill_replica`: dead first, promote a survivor if
        a role was lost, then per live request (admission order) the
        migrate/reroute/replay trichotomy; the waiting queue reroutes.
        Pages on the dead pool die with it (excluded from conservation
        the moment alive drops)."""
        st.reps[victim][P_ALIVE] = 0
        self._promote_if_role_lost(st)
        live = sorted((st.reqs[rid][R_SEQ], rid)
                      for rid in self._on(st, victim))
        for _, rid in live:
            q = st.reqs[rid]
            act = SCHED_RULES.migration_action(
                q[R_STATE], q[R_HELD] > 0, True)
            if act == "migrate":
                n = q[R_HELD]
                role = ("decode" if q[R_STATE] == SCHED_DECODE
                        else "prefill")
                dsts = [d for d in self._alive_idx(st, role)
                        if len(self._on(st, d)) < self.cfg.slots
                        and st.reps[d][P_FREE] >= n]
                pos = SCHED_RULES.route_least_loaded(
                    [(self._load(st, d), d) for d in dsts])
                if pos is None:
                    self._replay_fallback(st, rid)
                    continue
                # the kill-path handoff is atomic here: the fault
                # budget is spent on the kill itself, so no handoff
                # fault can race it (one injection per run, like the
                # chaos plans the benches drive)
                dst = dsts[pos]
                st.reps[dst][P_FREE] -= n
                q[R_REP] = dst
                st.seq += 1
                q[R_SEQ] = st.seq
            elif act == "reroute":
                # admitted but no KV written: zero work lost, NOT a
                # replay — but the requeue resets the replay target
                # exactly like the real enqueue does
                q[R_STATE] = SCHED_WAITING
                q[R_DONE] = 0
                q[R_REP] = -1
                q[R_REPLAY] = SCHED_RULES.replay_target(
                    self._n_tokens(q))
                self._route_to_prefill(st, rid, front=True)
            else:
                self._replay_fallback(st, rid)
        while st.reps[victim][P_WAIT]:
            self._route_to_prefill(
                st, st.reps[victim][P_WAIT].pop(0), front=False)

    def _promote_if_role_lost(self, st: SchedState) -> None:
        for role in ("prefill", "decode"):
            if not self._alive_idx(st, role):
                cands = self._alive_idx(st)
                pos = SCHED_RULES.route_least_loaded(
                    [(self._load(st, k), k) for k in cands])
                assert pos is not None
                st.reps[cands[pos]][P_ROLE] = "both"

    # -- invariants ----------------------------------------------------------

    def shape_violations(self) -> List[str]:
        """The static pre-pass (`validate_shape`'s model analogue): the
        worst-case single-request footprint must fit one pool, or the
        liveness claim is forfeit before any exploration."""
        worst = self.cfg.prompt_len + self.cfg.max_new
        if worst > self.cfg.pages:
            return [f"worst-case footprint {worst} pages > pool "
                    f"{self.cfg.pages} — a lone request cannot finish"]
        return []

    def _check_conservation(self, st: SchedState) -> None:
        for k, rep in enumerate(st.reps):
            if not rep[P_ALIVE]:
                continue
            resident = sum(q[R_HELD] for q in st.reqs
                           if q[R_REP] == k)
            reserve = (st.inflight[2]
                       if st.inflight is not None
                       and st.inflight[1] == k else 0)
            free = rep[P_FREE]
            if free < 0 or free + resident + reserve != self.cfg.pages:
                promised = SCHED_RULES.committed_outstanding(
                    [(self._target(st.reqs[r]), st.reqs[r][R_HELD])
                     for r in self._on(st, k)])
                raise ProtocolError(
                    "conservation",
                    f"page ledger broken on replica {k}: uncommitted "
                    f"{free - promised} + promised {promised} + "
                    f"resident {resident} + in-flight {reserve} != "
                    f"pool {self.cfg.pages} — a page leaked or was "
                    f"double-freed ({self._ctx()})")


# ---------------------------------------------------------------------------
# envelope
# ---------------------------------------------------------------------------

def build_sched(n_reqs: int, pages: int, n_replicas: int, fault: str,
                mutate: Optional[str] = None) -> SchedModel:
    cfg = SchedConfig(n_reqs, pages, n_replicas, fault)
    meta: Dict[str, Any] = {"route": "sched", "R": n_reqs, "P": pages,
                            "K": n_replicas, "fault": fault}
    if mutate is not None:
        meta["mutation"] = mutate
    return SchedModel(cfg, meta, mutate=mutate)


def sched_cells() -> List[Tuple[int, int, int, str]]:
    """The exhaustive control-plane envelope: requests <= 4, pages <= 6,
    replicas <= 3, one fault injection from {none, kill, handoff-fail}
    — 180 cells (>= 150, the ISSUE-20 acceptance floor)."""
    return [(r, p, k, fault)
            for r in (1, 2, 3, 4)
            for p in (2, 3, 4, 5, 6)
            for k in (1, 2, 3)
            for fault in SCHED_FAULTS]
