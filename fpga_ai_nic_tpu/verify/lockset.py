"""H1 — the happens-before/lockset pass: R1 generalized from "stats
counters mutate under lock" to cross-thread ORDER.

R1 froze one instance of the PR-4 race class: the named counter fields
of CollectiveStats/RecoveryStats.  But the same machine runs three-plus
real threads — the trainer loop, the elastic watchdog worker (every
`watchdog.run(fn)` executes ``fn`` on a daemon thread), XLA host
callback threads (`pure_callback` taps), and any `threading.Thread`
target — and ANY instance attribute written from two of them without a
common lock is the same dropped-update bug wearing a different field
name.

The pass (heuristic, like every graftlint rule — docs/LINT.md):

  1. thread roots: callables registered as ``Thread(target=...)``,
     ``<watchdog>.run(fn, ...)``, ``<executor>.submit(fn, ...)`` and
     host-callback bodies (``pure_callback(fn, ...)`` et al.), plus
     defs nested inside them;
  2. a name-based call graph over the scoped modules (self.m -> the
     enclosing class's method, bare f -> module function, obj.m -> any
     scoped class defining m), giving each function its ROLE SET:
     "worker" if reachable from a thread root, "main" if reachable
     from a public entry point;
  3. acquired-lock sets: the ``with *lock:`` contexts enclosing a
     statement, plus the INTERSECTION of lock sets over all call paths
     into the enclosing function (a lock held on only one path does
     not order the other);
  4. every ``self.<attr>`` write (assign / augassign / mutating method
     call) outside construction is a write site; a (class, attr) with
     a worker-role write and a main-role write whose lock sets are
     DISJOINT is an H1 finding — the two threads' writes are unordered.

Reads are out of scope (single-writer publish patterns are legal and
common); construction (`__init__`/`__post_init__`) happens-before
thread start and is exempt.  Findings are suppressible with
``# graftlint: disable=H1 -- reason`` like any AST rule.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from ..lint.engine import ModuleCtx
from ..lint.findings import Finding
from ..lint.suppress import scan as scan_suppressions

# the cross-thread surface: every module where a second thread executes
# (watchdog workers, callback taps, the queue the worker drives) — plus
# the stats/event sinks they all write into
SCOPE = (
    "runtime/queue.py", "runtime/watchdog.py", "runtime/chaos.py",
    "runtime/staging.py", "parallel/elastic.py",
    "utils/observability.py", "obs/events.py", "obs/metrics.py",
)

_THREAD_CTORS = {"Thread"}
_SUBMIT_METHODS = {"submit"}
_CALLBACK_FUNCS = {"pure_callback", "io_callback"}
_CONSTRUCTORS = {"__init__", "__post_init__", "__new__", "_lock_field"}
_MUTATING_METHODS = {"append", "extend", "insert", "pop", "clear",
                     "update", "setdefault", "remove", "add", "discard"}

FnKey = Tuple[str, str, str]          # (path, class name or "", qualname)


@dataclass
class _Fn:
    key: FnKey
    node: ast.AST
    ctx: ModuleCtx
    cls: str                          # "" for module-level
    name: str
    nested_in: Optional[FnKey] = None


@dataclass
class _WriteSite:
    fn: FnKey
    cls: str
    attr: str
    line: int
    path: str
    locks: FrozenSet[str]             # with-locks at the statement


@dataclass
class _Graph:
    fns: Dict[FnKey, _Fn] = field(default_factory=dict)
    by_method: Dict[str, List[FnKey]] = field(default_factory=dict)
    by_class_method: Dict[Tuple[str, str], List[FnKey]] = \
        field(default_factory=dict)
    by_module_fn: Dict[Tuple[str, str], List[FnKey]] = \
        field(default_factory=dict)
    calls: Dict[FnKey, List[Tuple[FnKey, FrozenSet[str]]]] = \
        field(default_factory=dict)   # callee -> [(caller, site locks)]
    worker_roots: Set[FnKey] = field(default_factory=set)
    writes: List[_WriteSite] = field(default_factory=list)
    # (class, attr) -> class names assigned via `self.attr = Cls(...)`;
    # lets `self.queue.wait` resolve to CollectiveQueue.wait instead of
    # every scoped class with a `wait` method
    instance_types: Dict[Tuple[str, str], Set[str]] = \
        field(default_factory=dict)
    class_names: Set[str] = field(default_factory=set)
    by_node: Dict[int, FnKey] = field(default_factory=dict)  # id(def node)


# with-context names that count as acquired locks: Lock/RLock handles
# and Condition variables (a Condition acquires its underlying lock)
_LOCKISH_SUFFIXES = ("lock", "cv", "cond", "condition")


def _lock_names(ctx: ModuleCtx, node: ast.AST) -> FrozenSet[str]:
    """Locks held at ``node``: enclosing ``with X:`` items whose dotted
    name ends in a lock-ish suffix (self._lock, stats._lock, self._cv,
    ...), normalized without the leading 'self.'."""
    out: Set[str] = set()
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                d = ctx.dotted(item.context_expr)
                if d.lower().endswith(_LOCKISH_SUFFIXES):
                    out.add(d[5:] if d.startswith("self.") else d)
    return frozenset(out)


def _enclosing_class_name(ctx: ModuleCtx, node: ast.AST) -> str:
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc.name
    return ""


def _collect_fns(ctx: ModuleCtx, graph: _Graph) -> None:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            graph.class_names.add(node.name)
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = _enclosing_class_name(ctx, node)
        outer = None
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                outer = anc
                break
        qual = (f"{cls}." if cls else "") + node.name \
            + (f"@{outer.name}" if outer is not None else "")
        key: FnKey = (ctx.path, cls, qual)
        fn = _Fn(key=key, node=node, ctx=ctx, cls=cls, name=node.name)
        if outer is not None:
            ocls = _enclosing_class_name(ctx, outer)
            fn.nested_in = (ctx.path, ocls,
                            (f"{ocls}." if ocls else "") + outer.name)
        graph.fns[key] = fn
        graph.by_node[id(node)] = key
        graph.by_method.setdefault(node.name, []).append(key)
        if cls:
            graph.by_class_method.setdefault((cls, node.name),
                                             []).append(key)
        else:
            graph.by_module_fn.setdefault((ctx.path, node.name),
                                          []).append(key)


def _resolve_callable(ctx: ModuleCtx, graph: _Graph, expr: ast.AST,
                      at: ast.AST) -> List[FnKey]:
    """Function keys an expression may denote: self.m, bare f, obj.m
    (any scoped class with a method m), seen through
    functools.partial."""
    while isinstance(expr, ast.Call) \
            and ctx.dotted(expr.func).split(".")[-1] == "partial" \
            and expr.args:
        expr = expr.args[0]
    d = ctx.dotted(expr)
    if not d:
        return []
    parts = d.split(".")
    name = parts[-1]
    if parts[0] == "self" and len(parts) == 2:
        cls = _enclosing_class_name(ctx, at)
        return list(graph.by_class_method.get((cls, name), ())) \
            or list(graph.by_method.get(name, ()))
    if parts[0] == "self" and len(parts) == 3:
        # self.<attr>.<meth>: prefer the inferred instance type(s)
        cls = _enclosing_class_name(ctx, at)
        owners = graph.instance_types.get((cls, parts[1]))
        if owners:
            out: List[FnKey] = []
            for o in owners:
                out.extend(graph.by_class_method.get((o, name), ()))
            return out
        return list(graph.by_method.get(name, ()))
    if len(parts) == 1:
        local = graph.by_module_fn.get((ctx.path, name))
        if local:
            return list(local)
        return [k for k in graph.by_method.get(name, ())
                if graph.fns[k].nested_in is not None
                or not graph.fns[k].cls]
    if parts[0] in ctx.mod_aliases:      # module attr: out of scope
        return []
    return list(graph.by_method.get(name, ()))


def _collect_instance_types(ctx: ModuleCtx, graph: _Graph) -> None:
    """`self.attr = Cls(...)` / `Cls.sized(...)` assignments -> the
    attr's plausible classes (union over sites, any method)."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.value, ast.Call)):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            continue
        d = ctx.dotted(node.value.func)
        head = d.split(".")[0] if d else ""
        if head in graph.class_names:
            cls = _enclosing_class_name(ctx, node)
            graph.instance_types.setdefault((cls, t.attr),
                                            set()).add(head)


def _scan_module(ctx: ModuleCtx, graph: _Graph) -> None:
    # call graph + worker-root registrations + write sites, one walk
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn_node = ctx.enclosing_function(node)
        caller: Optional[FnKey] = None
        if fn_node is not None and not isinstance(fn_node, ast.Lambda):
            caller = graph.by_node.get(id(fn_node))
        d = ctx.dotted(node.func)
        last = d.split(".")[-1] if d else ""
        # worker-root registrations (the callable travels as DATA, not
        # as a call — it executes on another thread)
        target: Optional[ast.AST] = None
        if last in _THREAD_CTORS:
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
        elif last == "run" and "watchdog" in d.lower() and node.args:
            target = node.args[0]
        elif last in _SUBMIT_METHODS and node.args:
            target = node.args[0]
        elif (last in _CALLBACK_FUNCS or d.endswith("debug.callback")) \
                and node.args:
            target = node.args[0]
        if target is not None:
            graph.worker_roots.update(
                _resolve_callable(ctx, graph, target, node))
            continue
        # ordinary call edge
        if caller is None:
            continue
        locks = _lock_names(ctx, node)
        for callee in _resolve_callable(ctx, graph, node.func, node):
            graph.calls.setdefault(callee, []).append((caller, locks))

    # write sites
    for key, fn in graph.fns.items():
        if fn.ctx is not ctx or fn.name in _CONSTRUCTORS:
            continue
        for node in ast.walk(fn.node):
            inner = ctx.enclosing_function(node)
            if inner is not fn.node:
                continue               # nested defs are their own entry
            targets: List[ast.AST] = []
            if isinstance(node, ast.AugAssign):
                targets = [node.target]
            elif isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _MUTATING_METHODS:
                    targets = [f.value]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    t = t.value
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if t.attr.endswith("lock"):
                    continue
                graph.writes.append(_WriteSite(
                    fn=key, cls=fn.cls, attr=t.attr, line=node.lineno,
                    path=ctx.path, locks=_lock_names(ctx, node)))


def _reach(graph: _Graph, roots: Set[FnKey]) -> Set[FnKey]:
    """Forward closure over the call graph (callee -> callers is what we
    store, so build the forward map once), including defs nested inside
    reached functions (closures run on the reaching thread)."""
    fwd: Dict[FnKey, Set[FnKey]] = {}
    for callee, sites in graph.calls.items():
        for caller, _locks in sites:
            fwd.setdefault(caller, set()).add(callee)
    nested: Dict[FnKey, Set[FnKey]] = {}
    for key, fn in graph.fns.items():
        if fn.nested_in is not None:
            nested.setdefault(fn.nested_in, set()).add(key)
    seen: Set[FnKey] = set()
    frontier = set(roots)
    while frontier:
        k = frontier.pop()
        if k in seen:
            continue
        seen.add(k)
        frontier |= fwd.get(k, set()) - seen
        frontier |= nested.get(k, set()) - seen
    return seen


def _entry_locks(graph: _Graph, roots: Set[FnKey]
                 ) -> Dict[FnKey, FrozenSet[str]]:
    """Fixpoint: locks GUARANTEED held on entry — the intersection over
    all call paths (roots enter lock-free)."""
    top = frozenset({"<top>"})        # lattice top: unvisited
    entry: Dict[FnKey, FrozenSet[str]] = {
        k: (frozenset() if k in roots else top) for k in graph.fns}
    changed = True
    while changed:
        changed = False
        for callee, sites in graph.calls.items():
            acc: Optional[FrozenSet[str]] = None
            for caller, locks in sites:
                cal = entry.get(caller, top)
                if cal == top:
                    continue
                held = frozenset(cal | locks)
                acc = held if acc is None else frozenset(acc & held)
            if callee in roots:
                acc = frozenset() if acc is None else frozenset()
            if acc is None:
                continue
            if entry.get(callee, top) == top or entry[callee] != acc:
                if entry.get(callee) != acc:
                    entry[callee] = acc
                    changed = True
    return {k: (frozenset() if v == top else v)
            for k, v in entry.items()}


def default_scope(repo_root: str) -> List[str]:
    """The scoped module paths — a missing entry is an ERROR, never a
    silent shrink of the race-checked surface (a rename must update
    SCOPE, not quietly drop the module from the pass)."""
    base = os.path.join(repo_root, "fpga_ai_nic_tpu")
    paths = [os.path.join(base, p) for p in SCOPE]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(
            "H1 lockset scope entries missing (renamed/deleted module? "
            f"update verify.lockset.SCOPE): {missing}")
    return paths


def run_lockset(paths: Optional[Sequence[str]] = None,
                repo_root: Optional[str] = None) -> List[Finding]:
    """Run the H1 pass over ``paths`` (default: the cross-thread scope
    of this repo).  Returns findings, suppressed ones marked."""
    if paths is None:
        root = repo_root or os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        paths = default_scope(root)
    graph = _Graph()
    ctxs: List[ModuleCtx] = []
    sups = {}
    for p in paths:
        with open(p, "r", encoding="utf-8") as fh:
            text = fh.read()
        tree = ast.parse(text, filename=p)
        ctx = ModuleCtx(p, text, tree)
        ctxs.append(ctx)
        sups[p] = scan_suppressions(p, text)
    for ctx in ctxs:
        _collect_fns(ctx, graph)
    for ctx in ctxs:
        _collect_instance_types(ctx, graph)
    for ctx in ctxs:
        _scan_module(ctx, graph)

    worker = _reach(graph, graph.worker_roots)
    main_roots = {k for k, fn in graph.fns.items()
                  if k not in graph.worker_roots
                  and fn.nested_in is None
                  and not fn.name.startswith("_")}
    main = _reach(graph, main_roots)
    entry = _entry_locks(graph, graph.worker_roots | main_roots)

    by_attr: Dict[Tuple[str, str], List[_WriteSite]] = {}
    for w in graph.writes:
        if w.fn in worker or w.fn in main:
            by_attr.setdefault((w.cls, w.attr), []).append(w)

    findings: List[Finding] = []
    for (cls, attr), sites in sorted(by_attr.items()):
        w_sites = [s for s in sites if s.fn in worker]
        m_sites = [s for s in sites if s.fn in main]
        if not w_sites or not m_sites:
            continue                   # single-threaded attribute
        reported: Set[Tuple[str, int]] = set()
        for ws in w_sites:
            wl = ws.locks | entry.get(ws.fn, frozenset())
            for ms in m_sites:
                ml = ms.locks | entry.get(ms.fn, frozenset())
                if wl & ml:
                    continue           # a common lock orders them
                loc = (ws.path, ws.line)
                if loc in reported:
                    continue
                reported.add(loc)
                other = ("the same statement" if (ms.path, ms.line) == loc
                         else f"{os.path.basename(ms.path)}:{ms.line}")
                findings.append(Finding(
                    "H1", ws.path, ws.line,
                    f"'{(cls + '.') if cls else ''}{attr}' is written on "
                    f"a worker thread here and from the main-thread path "
                    f"at {other} with DISJOINT lock sets "
                    f"({sorted(wl) or 'none'} vs {sorted(ml) or 'none'})"
                    " — unordered cross-thread writes drop updates; "
                    "route both through one locked method (the R1 "
                    "record_* pattern)"))
    out: List[Finding] = []
    for f in findings:
        sup = sups.get(f.path)
        if sup is not None:
            hit, reason = sup.lookup("H1", f.line)
            if hit:
                f = Finding(f.code, f.path, f.line, f.message,
                            suppressed=True, suppress_reason=reason)
        out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line))
