"""graftmc core: exhaustive explicit-state model checking + the
randomized fuzz scheduler, over the shared op-stream models
(`verify.opstream`).

Checked properties, per cell of the (route x n x S x depth) grid:

  deadlock freedom   some action is enabled until every node's program
                     and every in-flight transfer has retired;
  slot overwrite     a landing never hits an undecoded frame, an encode
                     never overwrites an in-flight frame;
  decode ordering    every decode finds exactly the emission the
                     schedule expects; no payload is orphaned;
  credit safety      semaphore counts never exceed the window
                     (boundedness) and never leak at termination
                     (non-negativity is structural: waits block);
  termination        the exhaustive exploration itself is finite and
                     every maximal path ends in the final state;
  DMA discipline     (static, per node) single wait per DMA, no wait
                     before start, declared slot-reuse/RAW predecessors
                     waited, full drain at exit.

Exploration: depth-first over the interleaving graph with state hashing
at branch points and a persistent-set partial-order reduction: at each
state, one action that commutes with every other enabled action (wire
landings into distinct slots, local node steps) is executed alone;
branching happens only where genuinely dependent actions race (a
landing vs the decode of its slot, an encode vs the in-flight frame it
would overwrite).  Any action whose violation condition is already live
is explored immediately — the schedule freedom that fires it exists, so
that path IS the counterexample.  The interleaving graph is a DAG
(program counters and transfer sets strictly advance), so the classic
cycle proviso is vacuous; docs/MODELCHECK.md carries the full soundness
argument.  `check(por=False)` runs the naive full-DFS for the
POR-vs-naive state-count comparison the corpus reports.

The randomized mode (`run_random`) executes the SAME model under a
seeded scheduler — it is `ops.ring_pallas.simulate_rs_protocol`'s
backend, and the corpus uses it as the seed-sweep fuzz beyond the
exhaustive envelope (n = 8).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Set, Tuple)

from . import opstream
from .opstream import (GatherModel, PairModel, ProtocolError, RingModel,
                       reshard_owners)
from .sched import SchedModel, build_sched, sched_cells

# The exhaustive envelope (per route; ROADMAP acceptance): every cell
# with n <= N_MAX, S <= S_MAX, depth <= D_MAX is explored EXHAUSTIVELY
# by `make modelcheck`; beyond it the randomized fuzz sweeps seeds.
N_MAX = 6
S_MAX = 6
D_MAX = 4
FUZZ_N = 8
FUZZ_SEEDS = 3

# POR-vs-naive comparison cells (small enough for the naive full DFS):
# reported by the corpus, pinned >= 5x by tests/test_verify.py
COMPARE_CELLS: Tuple[Tuple[int, int, int], ...] = ((2, 2, 2), (3, 2, 1))

DEFAULT_MAX_STATES = 2_000_000


class Violation(AssertionError):
    """A protocol violation with its interleaving attached.  Subclasses
    AssertionError so `simulate_rs_protocol` callers keep their
    ``pytest.raises(AssertionError, match=...)`` contracts."""

    def __init__(self, kind: str, message: str,
                 trace: Tuple[Any, ...] = (),
                 meta: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(message)
        self.kind = kind
        self.message = message
        self.trace = trace
        self.meta = dict(meta or {})


@dataclass
class CheckResult:
    """Outcome of one exhaustive exploration."""

    ok: bool
    states: int                    # transitions applied
    branch_points: int             # states where dependent actions raced
    terminal_paths: int
    por: bool
    meta: Dict[str, Any] = field(default_factory=dict)
    violation: Optional[Violation] = None

    @property
    def inconclusive(self) -> bool:
        """True when the exploration hit the state budget: NOT a
        protocol verdict in either direction (still fails the corpus —
        an unverified cell cannot be claimed verified — but is reported
        as inconclusive, with no counterexample export)."""
        return self.violation is not None and self.violation.kind == "budget"


def _unroll_trace(trace: Optional[Tuple[Any, Any]]) -> Tuple[Any, ...]:
    out: List[Any] = []
    while trace is not None:
        entry, trace = trace
        out.append(entry)
    out.reverse()
    return tuple(out)


def check(model: Any, por: bool = True,
          max_states: int = DEFAULT_MAX_STATES) -> CheckResult:
    """Exhaustively explore every inequivalent interleaving of ``model``.
    Returns a CheckResult; a violation is returned (not raised) with its
    counterexample trace attached."""
    res = CheckResult(ok=True, states=0, branch_points=0,
                      terminal_paths=0, por=por,
                      meta=dict(getattr(model, "meta", {})))
    st = model.init_state()
    stack = [st]
    seen: Set[Tuple[Any, ...]] = set()
    cur = st          # the state being advanced — carries the violation
    try:              # trace (apply records the action before checking)
        while stack:
            st = stack.pop()
            cur = st
            while True:
                if model.finished(st):
                    model.check_terminal(st)
                    res.terminal_paths += 1
                    break
                acts = model.enabled(st)
                if not acts:
                    raise ProtocolError("deadlock",
                                        model.deadlock_message(st))
                act = model.pick_action(st, acts) if por else None
                if act is not None:
                    res.states += 1
                    if res.states > max_states:
                        raise ProtocolError(
                            "budget",
                            f"state budget exceeded ({max_states}) — "
                            "exploration INCONCLUSIVE, not a protocol "
                            "verdict; raise max_states")
                    model.apply(st, act)
                    continue
                key = st.key()
                if key in seen:
                    break
                seen.add(key)
                res.branch_points += 1
                for act in acts:
                    child = st.clone()
                    res.states += 1
                    if res.states > max_states:
                        raise ProtocolError(
                            "budget",
                            f"state budget exceeded ({max_states}) — "
                            "exploration INCONCLUSIVE, not a protocol "
                            "verdict; raise max_states")
                    cur = child
                    model.apply(child, act)
                    stack.append(child)
                cur = st
                break
    except ProtocolError as e:
        res.ok = False
        res.violation = Violation(e.kind, e.message,
                                  trace=_unroll_trace(cur.trace),
                                  meta=res.meta)
    return res


def run_random(model: Any, seed: int = 0,
               max_events: int = 2_000_000) -> int:
    """Randomized-scheduler execution of one interleaving — the fuzz
    backend (`simulate_rs_protocol` delegates here).  Raises Violation
    (an AssertionError) on any protocol failure; returns the number of
    scheduler events on success."""
    rng = random.Random(seed)
    st = model.init_state()
    events = 0
    while True:
        acts = model.enabled(st)
        if not acts:
            if model.finished(st):
                try:
                    model.check_terminal(st)
                except ProtocolError as e:
                    raise Violation(e.kind, e.message,
                                    trace=_unroll_trace(st.trace)) from None
                return events
            raise Violation("deadlock", model.deadlock_message(st),
                            trace=_unroll_trace(st.trace))
        events += 1
        if events > max_events:
            raise Violation("termination", "scheduler did not terminate",
                            trace=_unroll_trace(st.trace))
        act = acts[rng.randrange(len(acts))]
        try:
            model.apply(st, act)
        except ProtocolError as e:
            raise Violation(e.kind, e.message,
                            trace=_unroll_trace(st.trace)) from None


# ---------------------------------------------------------------------------
# route builders: one model per grid cell
# ---------------------------------------------------------------------------

def build_flat(n: int, S: int, depth: int,
               integrity: bool = False) -> RingModel:
    ops, n_slots = opstream.rs_op_stream(n, S, depth,
                                         integrity=integrity)
    return RingModel(n, ops, n_slots,
                     meta={"route": "flat", "n": n, "S": S, "depth": depth,
                           **({"integrity": True} if integrity else {})})


def build_streaming(n: int, S: int, depth: int,
                    opt_kind: Optional[str] = None,
                    integrity: bool = False) -> RingModel:
    ops, n_slots = opstream.rs_stream_op_stream(n, S, depth,
                                                opt_kind=opt_kind,
                                                integrity=integrity)
    return RingModel(n, ops, n_slots,
                     meta={"route": "streaming", "n": n, "S": S,
                           "depth": depth, "opt": opt_kind or "none",
                           **({"integrity": True} if integrity else {})})


def build_ag(n: int, S: int,
             phys_slots: Optional[int] = None) -> RingModel:
    """The streaming all-gather's interleaved emission schedule under
    the full wait/credit protocol.  ``phys_slots`` overrides the
    MODEL's slot window only (the protocol stream keeps its planned
    window) — the anti-vacuity shrink: one physical slot fewer than the
    plan must overwrite."""
    ops, n_slots = opstream.ag_op_stream(n, S)
    return RingModel(n, ops,
                     n_slots if phys_slots is None else phys_slots,
                     meta={"route": "ag", "n": n, "S": S,
                           **({"phys_slots": phys_slots}
                              if phys_slots is not None else {})})


def build_hier(n: int, ni: int, s_inter: int,
               integrity: bool = False) -> PairModel:
    streams = opstream.hier_op_stream(n, ni, s_inter,
                                      integrity=integrity)
    return PairModel(streams, meta={"route": "hier", "n": n, "ni": ni,
                                    "S": s_inter,
                                    **({"integrity": True}
                                       if integrity else {})})


def build_handoff(n_layers: int, integrity: bool = False) -> PairModel:
    streams = opstream.handoff_op_stream(n_layers, integrity=integrity)
    return PairModel(streams, meta={"route": "handoff",
                                    "n_layers": n_layers,
                                    "integrity": integrity})


def reshard_layout(live: int, n_src: int, n_tgt: int
                   ) -> Tuple[int, int, int]:
    """(chunk_src, chunk_tgt, n_union) of a grid cell under the default
    ceil-padding — a thin view over THE union arithmetic
    (`opstream.union_layout`, which `parallel.reshard.make_plan` also
    consumes: one definition)."""
    padded_src = -(-live // n_src) * n_src
    padded_tgt = -(-live // n_tgt) * n_tgt
    cs, ct, nu, _seed = opstream.union_layout(live, n_src, padded_src,
                                              n_tgt, padded_tgt)
    return cs, ct, nu


def build_reshard(live: int, n_src: int, n_tgt: int,
                  residual: bool = False,
                  integrity: bool = False,
                  n_flat_leaves: int = 1) -> PairModel:
    chunk_src, chunk_tgt, n_union = reshard_layout(live, n_src, n_tgt)
    owners = reshard_owners(n_src, n_tgt) if residual else None
    streams = opstream.reshard_op_stream(live, chunk_src, chunk_tgt,
                                         n_union, owners,
                                         n_flat_leaves=n_flat_leaves,
                                         integrity=integrity)
    return PairModel(streams, meta={"route": "reshard", "live": live,
                                    "n_src": n_src, "n_tgt": n_tgt,
                                    "residual": residual,
                                    **({"integrity": True}
                                       if integrity else {})})


def flat_cells() -> List[Tuple[int, int, int]]:
    return [(n, S, D) for n in range(2, N_MAX + 1)
            for S in range(1, S_MAX + 1) for D in range(1, D_MAX + 1)]


def ag_cells() -> List[Tuple[int, int]]:
    return [(n, S) for n in range(2, N_MAX + 1)
            for S in range(1, S_MAX + 1)]


def hier_cells() -> List[Tuple[int, int, int]]:
    return [(n, ni, s) for n in range(2, N_MAX + 1)
            for ni in range(1, n + 1) if n % ni == 0
            for s in (1, 2)]


def handoff_cells() -> List[Tuple[int, bool]]:
    # n_layers spans trivial -> multi-block; integrity adds the ledger
    # chk pairs + the verdict exchange (the route M2 audits)
    return [(L, integ) for L in (1, 2, 3) for integ in (False, True)]


def build_gather(n_pages: int, n_live: int, depth: int) -> GatherModel:
    """The paged gather-attend kernel's per-(request, kv-head) DMA
    schedule (`opstream.paged_attend_op_stream` — the one definition
    `ops.paged_attend_pallas` also lowers) as a single-node async-DMA
    model: `check` explores the landing interleavings for semaphore-slot
    aliasing, and `_static_violations` runs the exact live-page coverage
    pass (`opstream.check_gather_coverage`) plus the generic DMA
    discipline first."""
    ops = opstream.paged_attend_op_stream(n_pages, n_live, depth)
    return GatherModel(ops, depth,
                       meta={"route": "gather", "P": n_pages,
                             "n_live": n_live, "depth": depth})


def gather_cells() -> List[Tuple[int, int, int]]:
    # every occupancy of every table width up to N_MAX, per buffer
    # depth — n_live == 0 (all-dead row: an inactive slot's schedule)
    # and depth > P (the prologue clamp) are both in-envelope
    return [(P, nl, d) for P in range(1, N_MAX + 1)
            for nl in range(0, P + 1) for d in (1, 2, 3)]


def reshard_cells() -> List[Tuple[int, int, int, bool]]:
    # 48 divides evenly almost everywhere; 37 is prime — every chunk
    # boundary of either layout cuts (the nothing-divides-anything case)
    cells = []
    for live in (48, 37):
        for ns in range(2, N_MAX + 1):
            for nt in range(2, N_MAX + 1):
                if ns == nt:
                    continue
                for residual in (False, True):
                    cells.append((live, ns, nt, residual))
    return cells


# ---------------------------------------------------------------------------
# the corpus: everything `make modelcheck` runs (CPU-only, < 60 s)
# ---------------------------------------------------------------------------

@dataclass
class CellReport:
    route: str
    cell: Tuple[Any, ...]
    states: int
    branch_points: int
    ok: bool
    message: str = ""


@dataclass
class RouteStats:
    """One route's share of the corpus — the envelope artifact's rows
    (MC_ENVELOPE_r*.json), gated two-sided by obs-gate mc.* keys so a
    silent envelope shrink is a CI failure, not a diff nobody reads."""

    route: str
    cells: int = 0
    states: int = 0
    branch_points: int = 0
    wall_s: float = 0.0


@dataclass
class CorpusStats:
    cells: int = 0
    states: int = 0
    branch_points: int = 0
    fuzz_runs: int = 0
    routes: List[RouteStats] = field(default_factory=list)
    compare: List[Dict[str, Any]] = field(default_factory=list)
    failures: List[CellReport] = field(default_factory=list)
    wall_s: float = 0.0


def _mc_findings(route: str, cell: Tuple[Any, ...], message: str,
                 code: str = "M1") -> "Any":
    from ..lint.findings import Finding
    return Finding(code, f"<mc:{route}>", 0,
                   f"cell {cell}: {message}")


def _static_violations(model: Any) -> List[Tuple[str, str]]:
    """The static pre-passes over a model's streams, no interleaving
    needed: per-node DMA discipline (single wait, ordered hazards, full
    drain) on RingModel streams, and the M2 checksum-weight pass
    (oddness, 1:1 emit/arrive pairing, program-distinctness) on every
    stream.  Returns (kind, message) pairs."""
    out: List[Tuple[str, str]] = []
    if isinstance(model, RingModel):
        dma = opstream.check_dma_discipline(model.ops)
        if dma:
            out.append(("dma", "; ".join(dma)))
        m2 = opstream.check_weight_conservation(model.ops)
    elif isinstance(model, GatherModel):
        dma = opstream.check_dma_discipline(model.ops)
        cov = opstream.check_gather_coverage(
            model.ops, model.meta["P"], model.meta["n_live"])
        if dma or cov:
            out.append(("dma", "; ".join(dma + cov)))
        m2 = opstream.check_weight_conservation(model.ops)
    elif isinstance(model, SchedModel):
        # the control-plane family carries no op streams — its static
        # pre-pass is the validate_shape analogue (a lone request must
        # fit one pool, or the liveness claim is forfeit unexplored)
        shape = model.shape_violations()
        if shape:
            out.append(("shape", "; ".join(shape)))
        m2 = []
    else:
        m2 = opstream.check_weight_conservation(model.streams)
    if m2:
        out.append(("weights", "; ".join(m2)))
    return out


def run_cell(route: str, cell: Tuple[Any, ...],
             max_states: int = DEFAULT_MAX_STATES
             ) -> Tuple[CheckResult, Any]:
    """Build and exhaustively check one grid cell; returns the
    CheckResult and the model (for replay).  The static passes (DMA
    discipline, M2 weight conservation) run first — deterministic, no
    interleaving needed."""
    builder: Dict[str, Callable[..., Any]] = {
        "flat": build_flat, "streaming": build_streaming,
        "ag": build_ag, "hier": build_hier, "reshard": build_reshard,
        "handoff": build_handoff, "gather": build_gather,
        "sched": build_sched}
    model = builder[route](*cell)
    static = _static_violations(model)
    if static:
        res = CheckResult(ok=False, states=0, branch_points=0,
                          terminal_paths=0, por=True,
                          meta=dict(model.meta))
        res.violation = Violation(static[0][0],
                                  "; ".join(m for _, m in static))
        return res, model
    return check(model, por=True, max_states=max_states), model


def run_corpus(emit: Optional[Callable[[str], None]] = None,
               counterexample_dir: Optional[str] = None
               ) -> Tuple[List[Any], CorpusStats]:
    """The full bounded corpus: exhaustive over the envelope for all
    four routes, POR-vs-naive comparison on the reported cells, and the
    randomized seed-sweep fuzz beyond the envelope (n = 8).  Returns
    (findings, stats); findings non-empty => `make modelcheck` fails."""
    import time
    t_corpus = time.perf_counter()
    log = emit or (lambda s: None)
    findings: List[Any] = []
    stats = CorpusStats()

    def sweep(route: str, cells: Iterable[Tuple[Any, ...]]) -> None:
        t0 = time.perf_counter()
        rs = RouteStats(route=route)
        for cell in cells:
            res, model = run_cell(route, cell)
            rs.cells += 1
            rs.states += res.states
            rs.branch_points += res.branch_points
            stats.branch_points += res.branch_points
            if not res.ok:
                assert res.violation is not None
                msg = f"{res.violation.kind}: {res.violation.message}"
                code = "M2" if res.violation.kind == "weights" else "M1"
                stats.failures.append(CellReport(
                    route, cell, res.states, res.branch_points, False,
                    msg))
                findings.append(_mc_findings(route, cell, msg, code=code))
                if counterexample_dir is not None \
                        and not res.inconclusive \
                        and res.violation.trace:
                    from . import replay
                    replay.export_counterexample(
                        model, res.violation, counterexample_dir)
        rs.wall_s = time.perf_counter() - t0
        stats.routes.append(rs)
        stats.cells += rs.cells
        stats.states += rs.states
        log(f"[graftmc] route {route}: {rs.cells} cells exhaustive, "
            f"{rs.states} states, {rs.wall_s:.2f}s")

    # integrity variants ride every route whose lowering carries the
    # PR-12 checksum ops — the chk pairs join the explored streams and
    # the M2 static pass audits their weights per cell
    sweep("flat", [c + (integ,) for c in flat_cells()
                   for integ in (False, True)])
    sweep("streaming", [c + v for c in flat_cells()
                        for v in ((None, False), ("adamw", False),
                                  (None, True), ("adamw", True))])
    sweep("ag", ag_cells())
    sweep("hier", [c + (integ,) for c in hier_cells()
                   for integ in (False, True)])
    sweep("reshard", [c + (integ,) for c in reshard_cells()
                      for integ in (False, True)])
    sweep("handoff", handoff_cells())
    sweep("gather", gather_cells())
    sweep("sched", sched_cells())

    # POR-vs-naive comparison on the reported cells (flat route; the
    # naive full DFS is only tractable on small cells)
    for cell in COMPARE_CELLS:
        res_por, _ = run_cell("flat", cell)
        res_naive = check(build_flat(*cell), por=False)
        stats.compare.append({
            "cell": cell, "por_states": res_por.states,
            "naive_states": res_naive.states,
            "agree": res_por.ok == res_naive.ok,
            "reduction": (res_naive.states / max(1, res_por.states)),
        })
        if res_por.ok != res_naive.ok:
            findings.append(_mc_findings(
                "flat", cell,
                "POR and naive DFS disagree on the verdict — the "
                "reduction is unsound for this cell"))
        log(f"[graftmc] POR vs naive on flat{cell}: "
            f"{res_por.states} vs {res_naive.states} states "
            f"({res_naive.states / max(1, res_por.states):.1f}x)")

    # fuzz beyond the exhaustive envelope: n = 8 randomized seed sweep
    # (the old simulate_rs_protocol coverage, now on the shared model)
    for route, build in (("flat", build_flat),
                         ("streaming", build_streaming)):
        for S in (2, 4):
            for depth in (2, 4):
                for seed in range(FUZZ_SEEDS):
                    stats.fuzz_runs += 1
                    try:
                        run_random(build(FUZZ_N, S, depth), seed=seed)
                    except Violation as v:
                        findings.append(_mc_findings(
                            route, (FUZZ_N, S, depth, seed),
                            f"fuzz {v.kind}: {v.message}"))
    log(f"[graftmc] fuzz beyond envelope: {stats.fuzz_runs} runs at "
        f"n={FUZZ_N}")
    stats.wall_s = time.perf_counter() - t_corpus
    return findings, stats


def envelope_record(stats: CorpusStats) -> Dict[str, Any]:
    """The corpus as a bankable artifact (MC_ENVELOPE_r*.json): per-route
    cell counts / states / branch points / wall time, the POR-vs-naive
    comparison rows, fuzz count, totals.  tools/obs_gate.py extracts
    mc.* metrics from it — cells/states two-sided exact (a silent
    envelope shrink fails CI), wall time lower-is-better against the
    explosion budget."""
    return {
        "schema_version": 1,
        "routes": [{"route": r.route, "cells": r.cells,
                    "states": r.states,
                    "branch_points": r.branch_points,
                    "wall_s": round(r.wall_s, 3)}
                   for r in stats.routes],
        "compare": [{"cell": list(c["cell"]),
                     "por_states": c["por_states"],
                     "naive_states": c["naive_states"],
                     "reduction": round(c["reduction"], 2),
                     "agree": c["agree"]} for c in stats.compare],
        "fuzz_runs": stats.fuzz_runs,
        "total_cells": stats.cells,
        "total_states": stats.states,
        "total_branch_points": stats.branch_points,
        "failures": len(stats.failures),
        "wall_s": round(stats.wall_s, 3),
    }


def run_fixture(path: str,
                counterexample_dir: Optional[str] = None) -> List[Any]:
    """Load a fixture module (env hook GRAFTMC_FIXTURE — the J7-style
    anti-vacuity pattern): the module's ``build()`` returns a mutated
    model that MUST violate.  The violation surfaces as an M1 finding
    (M2 for the static weight pass — same pass order as `run_cell`:
    static first); a fixture that does NOT violate is itself a finding
    (the checker would be vacuous)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location("graftmc_fixture", path)
    assert spec is not None and spec.loader is not None, path
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    model = mod.build()
    static = _static_violations(model)
    if static:
        return [_mc_findings(
            "fixture", (path,), f"{kind}: {msg}",
            code="M2" if kind == "weights" else "M1")
            for kind, msg in static]
    res = check(model)
    if res.ok:
        return [_mc_findings(
            "fixture", (path,),
            "fixture model completed clean — the mutated protocol was "
            "expected to violate; the checker would be vacuous")]
    assert res.violation is not None
    if counterexample_dir is not None and res.violation.trace:
        from . import replay
        replay.export_counterexample(model, res.violation,
                                     counterexample_dir)
    return [_mc_findings("fixture", (path,),
                         f"{res.violation.kind}: {res.violation.message}")]
