"""graftmc — exhaustive protocol model checking for the collectives.

The repo now carries SIX hand-built wire protocols (the reference
carried one, hw/all_reduce.sv): the depth-D flat ring reduce-scatter,
the HBM-streaming variant with its slice-prefetch DMA windows and the
fused-optimizer w/m/v state window, the streaming all-gather's
interleaved emission schedule, the hierarchical intra x inter two-hop
schedule (ops.ring_hier), the reshard single-pair ppermute program
(parallel.reshard), and the serving KV-handoff pair program
(serve.handoff).  Until this package the strongest protocol evidence
was a *randomized* interleaving simulator
(`ops.ring_pallas.simulate_rs_protocol`) — a fuzzer, not a proof.

graftmc closes that gap in three layers (docs/MODELCHECK.md):

  opstream   ONE op-stream EMITTER per protocol — consumed by the real
             kernels/lowerings for their schedule AND by the checker
             for its stream, so transcription drift is structurally
             impossible — plus the small-step execution models
             (`RingModel`, `PairModel`), a static DMA single-wait/RAW
             discipline check, and the M2 static checksum-weight pass
             (paired odd program-distinct conservation weights: the
             PR-12 collision class as a tool).
  mc         an exhaustive explicit-state checker with state hashing
             and a persistent-set/sleep-style partial-order reduction
             over commuting wire-landing events; checks deadlock
             freedom, recv/send-slot overwrite, decode ordering, credit
             non-negativity/boundedness and termination across the
             (route x n x S x depth) grid — exhaustive for n<=6, S<=6,
             D<=4 per route (integrity variants included), randomized
             seed-sweep fuzz beyond.  The randomized mode IS
             `simulate_rs_protocol`'s backend now.  Every corpus run
             records its envelope (per-route cells/states/wall time)
             for MC_ENVELOPE_r*.json and the obs-gate mc.* keys.
  replay     a violating interleaving pretty-prints as a per-node op
             trace and exports through obs.timeline as Perfetto JSON.
  lockset    the happens-before/lockset AST pass (rule H1): watchdog vs
             trainer vs queue writes classified by acquired-lock sets
             over the call graph; unordered cross-thread writes are
             findings.

Entry points: ``tools/graftlint.py --mc`` / ``make modelcheck``.
No module IN this package imports jax or touches a device — the corpus
is plain-Python state exploration.  (Importing it still executes the
parent package's ``__init__``, which pulls jax; the CLI pins
``JAX_PLATFORMS=cpu`` / ``PALLAS_AXON_POOL_IPS=`` before any import —
the same guard as tests/conftest.py — so `make modelcheck` runs in
seconds even with a wedged TPU tunnel.)
"""

from .opstream import (
    RingModel, PairModel, ProtocolError, rs_plan, rs_op_stream,
    rs_stream_op_stream, ag_schedule, ag_op_stream, hier_program,
    hier_op_stream, reshard_op_stream, reshard_segments,
    handoff_program, handoff_op_stream, check_dma_discipline,
    check_weight_conservation, SchedEmitter, SCHED_RULES,
)
from .sched import SchedModel, build_sched, sched_cells
from .mc import Violation, CheckResult, check, run_random, run_corpus

__all__ = [
    "RingModel", "PairModel", "ProtocolError", "rs_plan", "rs_op_stream",
    "rs_stream_op_stream", "ag_schedule", "ag_op_stream", "hier_program",
    "hier_op_stream", "reshard_op_stream", "reshard_segments",
    "handoff_program", "handoff_op_stream", "check_dma_discipline",
    "check_weight_conservation", "SchedEmitter", "SCHED_RULES",
    "SchedModel", "build_sched", "sched_cells",
    "Violation", "CheckResult", "check", "run_random", "run_corpus",
]
