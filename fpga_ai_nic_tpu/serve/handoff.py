"""Live KV migration between serving replicas — the serving-plane reuse
of the reshard discipline (parallel/reshard.py, docs/RESHARD.md).

PR 7 proved that the fastest way to move *training* state off a dying
replica is a static collective transfer program whose wire bytes are
exactly accounted (rule J8).  The serving plane has the same problem
with different state: a preempted or scaled-down replica holds live
requests' KV pages, and the only recovery tier until now was
replay-from-prompt — every in-flight request's prefill work thrown
away.  This module expresses "move request r's page-pool pages from
replica A to replica B" the same way reshard expresses a mesh move:

  - a **HandoffPlan** is the static description: ``n_move`` pages (each
    ``[kv_local, page_size, hd]`` per layer per K/V) crossing from the
    pair's device 0 to device 1.  ``wire_bytes()`` is EXACTLY the pages'
    bytes — the number graftlint rule J11 holds the lowered program's
    ppermute operands to (page ids, table rows and the request's host
    tokens move host-side and are declared separately as
    ``host_bytes``, never smuggled into the wire accounting).
  - **lower_apply** lowers the plan to ONE jitted shard_map over a
    2-device "rep" pair mesh: gather the ``n_move`` pages out of the
    source shard (page ids are int32 *operands*, so which pages move is
    a VALUE — one trace serves every migration of the same size), one
    single-pair ``lax.ppermute`` per layer per K/V with the gathered
    block as the exact-length payload, scatter into the destination
    shard's freshly allocated page ids.  Every pool operand is DONATED
    (the reshard footprint rule: the transfer runs in ~one pool's
    memory, not two).
  - **apply_handoff** assembles the two replicas' single-device pools
    into the pair-sharded operands ZERO-COPY
    (``jax.make_array_from_single_device_arrays``) and hands the output
    shards back as each replica's new pool.

Because ``forward_paged`` is bitwise-invariant to page assignment
(docs/SERVING.md's parity theorem), a migrated request's continuation on
the destination replica is bitwise the continuation it would have
produced at home — the fleet's replica-kill chaos cell pins exactly
that.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama_decode
from ..models.llama import LlamaConfig
from .paged import ServeConfig

__all__ = ["HandoffPlan", "make_plan", "plan_for", "lower_apply",
           "abstract_operands", "apply_handoff", "pair_mesh"]

Pool = List[Dict[str, jax.Array]]

REP_AXIS = "rep"


class HandoffPlan(NamedTuple):
    """Static shape of one KV migration: ``n_move`` pool pages crossing
    the pair axis, per layer, per K and V.  Page IDS are operands, not
    plan fields — one plan (one trace) serves every migration of the
    same page count over the same pool geometry."""

    n_layers: int
    kv_local: int
    page_size: int
    head_dim: int
    n_pages: int                 # pool pages per replica (operand shape)
    n_move: int                  # pages crossing the wire (static)
    dtype: str = "float32"

    @property
    def itemsize(self) -> int:
        return int(jnp.dtype(self.dtype).itemsize)

    @property
    def page_bytes(self) -> int:
        """Bytes of ONE page of ONE layer's K or V."""
        return self.kv_local * self.page_size * self.head_dim \
            * self.itemsize

    def wire_bytes(self) -> int:
        """EXACTLY the bytes the ppermutes move (pages only — rule J11
        holds the lowered program to this, two-sided)."""
        return 2 * self.n_layers * self.n_move * self.page_bytes

    def host_bytes(self, n_tokens: int) -> int:
        """Bytes that move HOST-side per migrated request: the page-table
        row (int32) and the request's prompt+generated token ids —
        declared apart from the wire bytes, the seed_bytes honesty rule."""
        return self.n_move * 4 + int(n_tokens) * 4

    def describe(self) -> Dict[str, Any]:
        return {"n_layers": self.n_layers, "kv_local": self.kv_local,
                "page_size": self.page_size, "head_dim": self.head_dim,
                "n_pages": self.n_pages, "n_move": self.n_move,
                "dtype": self.dtype, "wire_bytes": self.wire_bytes()}


def make_plan(*, n_layers: int, kv_local: int, page_size: int,
              head_dim: int, n_pages: int, n_move: int,
              dtype: str = "float32") -> HandoffPlan:
    assert n_layers >= 1 and kv_local >= 1 and page_size >= 1
    assert 1 <= n_move < n_pages, (n_move, n_pages)
    return HandoffPlan(n_layers=n_layers, kv_local=kv_local,
                       page_size=page_size, head_dim=head_dim,
                       n_pages=n_pages, n_move=n_move,
                       dtype=str(jnp.dtype(dtype)))


def plan_for(cfg: LlamaConfig, scfg: ServeConfig, n_move: int, *,
             tp_size: int = 1, dtype: Optional[str] = None) -> HandoffPlan:
    """The plan for migrating ``n_move`` pages between two replicas of
    the given model/serve geometry (both sides MUST share it — the
    fleet constructs every replica from one (cfg, scfg) pair)."""
    return make_plan(
        n_layers=cfg.n_layers,
        kv_local=llama_decode.kv_local_heads(cfg, tp_size),
        page_size=scfg.page_size, head_dim=cfg.head_dim,
        n_pages=scfg.n_pages, n_move=n_move,
        dtype=str(jnp.dtype(dtype or cfg.dtype)))


# ---------------------------------------------------------------------------
# lowering: the plan as one jitted pair-ppermute program (donated pools)
# ---------------------------------------------------------------------------

def lower_apply(plan: HandoffPlan, mesh: Mesh, ax: str = REP_AXIS, *,
                donate: bool = True) -> Any:
    """The plan as ONE jitted transfer program over a 2-device pair mesh.

    Positional args: ``2 * n_layers`` stacked pools
    ``[2, n_pages, kv_local, page_size, hd]`` sharded ``P(ax)`` (layer
    order, K then V), then ``src_idx [n_move]`` / ``dst_idx [n_move]``
    int32 (replicated).  Returns the same pools with the gathered source
    pages landed at the destination's page ids; the source shard passes
    through untouched (its pages are freed host-side and recycled
    dirty).  Every pool operand is donated by default."""
    assert mesh.shape[ax] == 2, mesh.shape
    n_pool = 2 * plan.n_layers

    def body(*ops: jax.Array) -> Tuple[jax.Array, ...]:
        pools = ops[:n_pool]
        src_idx, dst_idx = ops[n_pool], ops[n_pool + 1]
        i = lax.axis_index(ax)
        outs = []
        for p in pools:
            # exact-length payload: ONLY the migrating pages cross —
            # [n_move, kv_local, page_size, hd] per layer per K/V
            payload = jnp.take(p[0], src_idx, axis=0)
            payload = lax.ppermute(payload, ax, [(0, 1)])
            landed = p.at[0, dst_idx].set(payload)
            outs.append(jnp.where(i == 1, landed, p))
        return tuple(outs)

    sm = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(ax),) * n_pool + (P(), P()),
                       out_specs=(P(ax),) * n_pool, check_vma=False)
    return jax.jit(sm, donate_argnums=(tuple(range(n_pool)) if donate
                                       else ()))


@functools.lru_cache(maxsize=64)
def _cached_apply(plan: HandoffPlan, mesh: Mesh, ax: str,
                  donate: bool) -> Any:
    """Memoized ``lower_apply``: migrations of the same page count over
    the same pair mesh hit the jit dispatch cache — the fleet's handoff
    trace count is bounded by distinct (n_move, pair) values, not by
    migration events."""
    return lower_apply(plan, mesh, ax, donate=donate)


def abstract_operands(plan: HandoffPlan
                      ) -> Tuple[jax.ShapeDtypeStruct, ...]:
    """ShapeDtypeStructs matching ``lower_apply``'s positional args —
    the zero-device-work handle the graftlint J11 sweep traces the
    program through."""
    pool_sds = jax.ShapeDtypeStruct(
        (2, plan.n_pages, plan.kv_local, plan.page_size, plan.head_dim),
        jnp.dtype(plan.dtype))
    idx = jax.ShapeDtypeStruct((plan.n_move,), jnp.int32)
    return (pool_sds,) * (2 * plan.n_layers) + (idx, idx)


# ---------------------------------------------------------------------------
# runtime: zero-copy pair assembly + shard disassembly
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def pair_mesh(dev_src: Any, dev_dst: Any) -> Mesh:
    """The 2-device transfer surface for one (src, dst) replica pair."""
    assert dev_src != dev_dst, "handoff needs two distinct devices"
    return Mesh(np.array([dev_src, dev_dst]), (REP_AXIS,))


def _stacked(a: jax.Array, b: jax.Array, sharding: NamedSharding
             ) -> jax.Array:
    """[n_pages, ...] on dev0 + [n_pages, ...] on dev1 -> global
    [2, n_pages, ...] sharded P(rep), zero cross-device copies."""
    return jax.make_array_from_single_device_arrays(
        (2,) + tuple(a.shape), sharding,
        [a.reshape((1,) + tuple(a.shape)),
         b.reshape((1,) + tuple(b.shape))])


def _unstack(out: jax.Array) -> Tuple[jax.Array, jax.Array]:
    shards = sorted(out.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    assert len(shards) == 2
    return shards[0].data[0], shards[1].data[0]


def apply_handoff(plan: HandoffPlan, mesh: Mesh, src_pool: Pool,
                  dst_pool: Pool, src_pages: Sequence[int],
                  dst_pages: Sequence[int], *, ax: str = REP_AXIS,
                  donate: bool = True) -> Tuple[Pool, Pool]:
    """Run the transfer: source pages ``src_pages`` of ``src_pool`` land
    at ``dst_pages`` of ``dst_pool``.  Returns (new_src_pool,
    new_dst_pool); with ``donate`` the stacked inputs are consumed.  The
    caller owns the host bookkeeping (allocator, table rows, request
    state) — this is ONLY the device move."""
    assert len(src_pages) == len(dst_pages) == plan.n_move
    sharding = NamedSharding(mesh, P(ax))
    ops = []
    for ls, ld in zip(src_pool, dst_pool):
        for key in ("k", "v"):
            ops.append(_stacked(ls[key], ld[key], sharding))
    run = _cached_apply(plan, mesh, ax, donate)
    outs = run(*ops, jnp.asarray(np.asarray(src_pages, np.int32)),
               jnp.asarray(np.asarray(dst_pages, np.int32)))
    jax.block_until_ready(outs)
    new_src: Pool = []
    new_dst: Pool = []
    it = iter(outs)
    for _ in range(plan.n_layers):
        sk, dk = _unstack(next(it))
        sv, dv = _unstack(next(it))
        new_src.append({"k": sk, "v": sv})
        new_dst.append({"k": dk, "v": dv})
    return new_src, new_dst
