"""Live KV migration between serving replicas — the serving-plane reuse
of the reshard discipline (parallel/reshard.py, docs/RESHARD.md).

PR 7 proved that the fastest way to move *training* state off a dying
replica is a static collective transfer program whose wire bytes are
exactly accounted (rule J8).  The serving plane has the same problem
with different state: a preempted or scaled-down replica holds live
requests' KV pages, and the only recovery tier until now was
replay-from-prompt — every in-flight request's prefill work thrown
away.  This module expresses "move request r's page-pool pages from
replica A to replica B" the same way reshard expresses a mesh move:

  - a **HandoffPlan** is the static description: ``n_move`` pages (each
    ``[kv_local, page_size, hd]`` per layer per K/V) crossing from the
    pair's device 0 to device 1.  ``wire_bytes()`` is EXACTLY the pages'
    bytes — the number graftlint rule J11 holds the lowered program's
    ppermute operands to (page ids, table rows and the request's host
    tokens move host-side and are declared separately as
    ``host_bytes``, never smuggled into the wire accounting).
  - **lower_apply** lowers the plan to ONE jitted shard_map over a
    2-device "rep" pair mesh: gather the ``n_move`` pages out of the
    source shard (page ids are int32 *operands*, so which pages move is
    a VALUE — one trace serves every migration of the same size), one
    single-pair ``lax.ppermute`` per layer per K/V with the gathered
    block as the exact-length payload, scatter into the destination
    shard's freshly allocated page ids.  Every pool operand is DONATED
    (the reshard footprint rule: the transfer runs in ~one pool's
    memory, not two).
  - **apply_handoff** assembles the two replicas' single-device pools
    into the pair-sharded operands ZERO-COPY
    (``jax.make_array_from_single_device_arrays``) and hands the output
    shards back as each replica's new pool.

Because ``forward_paged`` is bitwise-invariant to page assignment
(docs/SERVING.md's parity theorem), a migrated request's continuation on
the destination replica is bitwise the continuation it would have
produced at home — the fleet's replica-kill chaos cell pins exactly
that.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama_decode
from ..models.llama import LlamaConfig
from ..ops import integrity as integrity_lib
from ..ops import ring as ring_ops
# the shared protocol IR: the block order of a migration (and with it
# the per-block ledger-compare weights) is emitted once there and
# consumed both by the lowering below and by graftmc's checked handoff
# streams (verify.opstream.handoff_op_stream)
from ..verify import opstream as _opstream
from .paged import ServeConfig

# THE block order of one KV migration — tests pin the delegation by
# identity (a reorder would silently re-pair ledger weights: the M2
# class)
handoff_program = _opstream.handoff_program

__all__ = ["HandoffPlan", "make_plan", "plan_for", "lower_apply",
           "abstract_operands", "apply_handoff", "pair_mesh"]

Pool = List[Dict[str, jax.Array]]

REP_AXIS = "rep"


class HandoffPlan(NamedTuple):
    """Static shape of one KV migration: ``n_move`` pool pages crossing
    the pair axis, per layer, per K and V.  Page IDS are operands, not
    plan fields — one plan (one trace) serves every migration of the
    same page count over the same pool geometry."""

    n_layers: int
    kv_local: int
    page_size: int
    head_dim: int
    n_pages: int                 # pool pages per replica (operand shape)
    n_move: int                  # pages crossing the wire (static)
    dtype: str = "float32"

    @property
    def itemsize(self) -> int:
        return int(jnp.dtype(self.dtype).itemsize)

    @property
    def page_bytes(self) -> int:
        """Bytes of ONE page of ONE layer's K or V."""
        return self.kv_local * self.page_size * self.head_dim \
            * self.itemsize

    def wire_bytes(self) -> int:
        """EXACTLY the bytes the ppermutes move (pages only — rule J11
        holds the lowered program to this, two-sided)."""
        return 2 * self.n_layers * self.n_move * self.page_bytes

    def host_bytes(self, n_tokens: int) -> int:
        """Bytes that move HOST-side per migrated request: the page-table
        row (int32) and the request's prompt+generated token ids —
        declared apart from the wire bytes, the seed_bytes honesty rule."""
        return self.n_move * 4 + int(n_tokens) * 4

    def describe(self) -> Dict[str, Any]:
        return {"n_layers": self.n_layers, "kv_local": self.kv_local,
                "page_size": self.page_size, "head_dim": self.head_dim,
                "n_pages": self.n_pages, "n_move": self.n_move,
                "dtype": self.dtype, "wire_bytes": self.wire_bytes()}


def make_plan(*, n_layers: int, kv_local: int, page_size: int,
              head_dim: int, n_pages: int, n_move: int,
              dtype: str = "float32") -> HandoffPlan:
    assert n_layers >= 1 and kv_local >= 1 and page_size >= 1
    assert 1 <= n_move < n_pages, (n_move, n_pages)
    return HandoffPlan(n_layers=n_layers, kv_local=kv_local,
                       page_size=page_size, head_dim=head_dim,
                       n_pages=n_pages, n_move=n_move,
                       dtype=str(jnp.dtype(dtype)))


def plan_for(cfg: LlamaConfig, scfg: ServeConfig, n_move: int, *,
             tp_size: int = 1, dtype: Optional[str] = None) -> HandoffPlan:
    """The plan for migrating ``n_move`` pages between two replicas of
    the given model/serve geometry (both sides MUST share it — the
    fleet constructs every replica from one (cfg, scfg) pair)."""
    return make_plan(
        n_layers=cfg.n_layers,
        kv_local=llama_decode.kv_local_heads(cfg, tp_size),
        page_size=scfg.page_size, head_dim=cfg.head_dim,
        n_pages=scfg.n_pages, n_move=n_move,
        dtype=str(jnp.dtype(dtype or cfg.dtype)))


# ---------------------------------------------------------------------------
# lowering: the plan as one jitted pair-ppermute program (donated pools)
# ---------------------------------------------------------------------------

def lower_apply(plan: HandoffPlan, mesh: Mesh, ax: str = REP_AXIS, *,
                donate: bool = True, integrity: bool = False) -> Any:
    """The plan as ONE jitted transfer program over a 2-device pair mesh.

    Positional args: ``2 * n_layers`` stacked pools
    ``[2, n_pages, kv_local, page_size, hd]`` sharded ``P(ax)`` (layer
    order, K then V), then ``src_idx [n_move]`` / ``dst_idx [n_move]``
    int32 (replicated).  Returns the same pools with the gathered source
    pages landed at the destination's page ids; the source shard passes
    through untouched (its pages are freed host-side and recycled
    dirty).  Every pool operand is donated by default.

    ``integrity=True`` adds one replicated operand — ``expect [n_move]``
    uint32, the source replica's page-checksum ledger entries for the
    migrating pages (``ops.integrity.page_checksums``, recorded when the
    pages were last WRITTEN) — and two replicated outputs: ``landed
    [n_move]`` uint32 (the same exact checksum recomputed over the
    post-wire landed page blocks) and ``ok`` (landed == expect for every
    page).  A flipped bit anywhere between the source write and the
    destination land — including on the pair wire itself — fails ``ok``
    bit-exactly.  The page bytes moved and the J11 ppermute accounting
    are identical either way: the checksums are psum'd scalars, never
    wire payload."""
    assert mesh.shape[ax] == 2, mesh.shape
    n_pool = 2 * plan.n_layers

    def body(*ops: jax.Array) -> Tuple[jax.Array, ...]:
        pools = ops[:n_pool]
        src_idx, dst_idx = ops[n_pool], ops[n_pool + 1]
        i = lax.axis_index(ax)
        outs = []
        blocks = []
        # block order CONSUMED from the IR program: position == the
        # block's odd multiplier in gathered_page_checksums, so the
        # ledger weights here and in the checked stream are one fact
        for mv in handoff_program(plan.n_layers):
            p = pools[mv.pool]
            # exact-length payload: ONLY the migrating pages cross —
            # [n_move, kv_local, page_size, hd] per layer per K/V
            payload = jnp.take(p[0], src_idx, axis=0)
            payload = lax.ppermute(payload, ax, [(0, 1)])
            payload = ring_ops._tap_wire((payload,), "handoff.wire",
                                         consumed=i == 1)[0]
            blocks.append(payload)
            landed = p.at[0, dst_idx].set(payload)
            outs.append(jnp.where(i == 1, landed, p))
        if integrity:
            expect = ops[n_pool + 2]
            got = integrity_lib.gathered_page_checksums(blocks)
            # device 0 received zeros; replicate device 1's verdict (the
            # psum rides i32 — wraparound addition commutes with the
            # bitcast, and i32 all-reduce support is universal)
            landed_chk = lax.bitcast_convert_type(
                lax.psum(lax.bitcast_convert_type(
                    jnp.where(i == 1, got, jnp.zeros_like(got)),
                    jnp.int32), ax), jnp.uint32)
            bad = lax.psum(jnp.where(
                i == 1, jnp.sum((got != expect).astype(jnp.int32)), 0), ax)
            outs.extend([landed_chk, bad == 0])
        return tuple(outs)

    in_specs = (P(ax),) * n_pool + (P(), P()) + ((P(),) if integrity
                                                 else ())
    out_specs = (P(ax),) * n_pool + ((P(), P()) if integrity else ())
    sm = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
    return jax.jit(sm, donate_argnums=(tuple(range(n_pool)) if donate
                                       else ()))


@functools.lru_cache(maxsize=64)
def _cached_apply(plan: HandoffPlan, mesh: Mesh, ax: str,
                  donate: bool, integrity: bool = False) -> Any:
    """Memoized ``lower_apply``: migrations of the same page count over
    the same pair mesh hit the jit dispatch cache — the fleet's handoff
    trace count is bounded by distinct (n_move, pair) values, not by
    migration events."""
    return lower_apply(plan, mesh, ax, donate=donate, integrity=integrity)


def abstract_operands(plan: HandoffPlan, *, integrity: bool = False
                      ) -> Tuple[jax.ShapeDtypeStruct, ...]:
    """ShapeDtypeStructs matching ``lower_apply``'s positional args —
    the zero-device-work handle the graftlint J11/J12 sweeps trace the
    program through."""
    pool_sds = jax.ShapeDtypeStruct(
        (2, plan.n_pages, plan.kv_local, plan.page_size, plan.head_dim),
        jnp.dtype(plan.dtype))
    idx = jax.ShapeDtypeStruct((plan.n_move,), jnp.int32)
    ops = (pool_sds,) * (2 * plan.n_layers) + (idx, idx)
    if integrity:
        ops = ops + (jax.ShapeDtypeStruct((plan.n_move,), jnp.uint32),)
    return ops


# ---------------------------------------------------------------------------
# runtime: zero-copy pair assembly + shard disassembly
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def pair_mesh(dev_src: Any, dev_dst: Any) -> Mesh:
    """The 2-device transfer surface for one (src, dst) replica pair."""
    assert dev_src != dev_dst, "handoff needs two distinct devices"
    return Mesh(np.array([dev_src, dev_dst]), (REP_AXIS,))


def _stacked(a: jax.Array, b: jax.Array, sharding: NamedSharding
             ) -> jax.Array:
    """[n_pages, ...] on dev0 + [n_pages, ...] on dev1 -> global
    [2, n_pages, ...] sharded P(rep), zero cross-device copies."""
    return jax.make_array_from_single_device_arrays(
        (2,) + tuple(a.shape), sharding,
        [a.reshape((1,) + tuple(a.shape)),
         b.reshape((1,) + tuple(b.shape))])


def _unstack(out: jax.Array) -> Tuple[jax.Array, jax.Array]:
    shards = sorted(out.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    assert len(shards) == 2
    return shards[0].data[0], shards[1].data[0]


def apply_handoff(plan: HandoffPlan, mesh: Mesh, src_pool: Pool,
                  dst_pool: Pool, src_pages: Sequence[int],
                  dst_pages: Sequence[int], *, ax: str = REP_AXIS,
                  donate: bool = True,
                  expect: Optional[Any] = None) -> Any:
    """Run the transfer: source pages ``src_pages`` of ``src_pool`` land
    at ``dst_pages`` of ``dst_pool``.  Returns (new_src_pool,
    new_dst_pool); with ``donate`` the stacked inputs are consumed.  The
    caller owns the host bookkeeping (allocator, table rows, request
    state) — this is ONLY the device move.

    ``expect`` (uint32 [n_move], the source ledger's checksums for
    ``src_pages``) switches on the integrity-checked program: the return
    grows to ``(new_src, new_dst, ok, landed)`` where ``ok`` is the
    bit-exact landed-vs-written verdict and ``landed`` the recomputed
    per-page checksums (what the destination ledger must record for
    ``dst_pages`` — even on a tripped run, so the destination's dirty
    pages stay ledger-consistent)."""
    assert len(src_pages) == len(dst_pages) == plan.n_move
    integrity = expect is not None
    sharding = NamedSharding(mesh, P(ax))
    ops = []
    for ls, ld in zip(src_pool, dst_pool):
        for key in ("k", "v"):
            ops.append(_stacked(ls[key], ld[key], sharding))
    run = _cached_apply(plan, mesh, ax, donate, integrity)
    args = (jnp.asarray(np.asarray(src_pages, np.int32)),
            jnp.asarray(np.asarray(dst_pages, np.int32)))
    if integrity:
        args = args + (jnp.asarray(np.asarray(expect, np.uint32)),)
    outs = run(*ops, *args)
    jax.block_until_ready(outs)
    new_src: Pool = []
    new_dst: Pool = []
    it = iter(outs[:2 * plan.n_layers])
    for _ in range(plan.n_layers):
        sk, dk = _unstack(next(it))
        sv, dv = _unstack(next(it))
        new_src.append({"k": sk, "v": sv})
        new_dst.append({"k": dk, "v": dv})
    if not integrity:
        return new_src, new_dst
    landed, ok = outs[-2], outs[-1]
    return new_src, new_dst, bool(np.asarray(ok)), np.asarray(landed)
