"""Deterministic, replayable serving workload generator.

"Millions of users" is a traffic SHAPE — diurnal load cycles, bursts,
heavy-tailed prompt/output lengths, tenant mixes — not a bigger fixed
list.  This module generates that shape reproducibly: every draw comes
from a counter-based PRNG (a splitmix64-style hash of ``(seed, stream,
counter)``), so request i's attributes are a pure function of the seed
and i — no sequential RNG state, no numpy Generator whose draw ORDER
becomes part of the contract.  Same seed => byte-identical trace (the
determinism test pins the fingerprint); different seed => different
trace.  Replays are exact by construction, which is what lets the fleet
bench bank tick-exact ``fleet.slo.*`` metrics per scenario.

Arrivals are in the FLEET-TICK domain, not wall seconds: the bench's
drive loop submits a request when ``fleet.ticks`` reaches its
``arrival_tick``, so queue depth, pool pressure and the autoscaler's
decision sequence are machine-independent (CPU dryrun and TPU runs see
the SAME offered load per tick; only wall-clock latencies differ).

Distributions (all inverse-CDF on counter-PRNG uniforms):

  inter-arrival   exponential with tick-varying rate: base rate shaped
                  by a diurnal cosine cycle and additive burst windows
                  (spike / thundering-herd scenarios compose from the
                  same two knobs).
  prompt/output   bounded Pareto (heavy tail, hard clamp) — most
                  requests short, a fat tail of long ones, never past
                  the engine's static budget.
  tenant          weighted categorical over the configured mix.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["TrafficConfig", "TrafficRequest", "Workload", "Burst",
           "generate", "steady_config", "spike_config", "diurnal_config",
           "thundering_herd_config"]

_MASK64 = (1 << 64) - 1

# stream ids: every attribute of request i draws from its own stream so
# adding a field can never shift another field's value (replayability
# survives schema growth)
_S_ARRIVAL, _S_PROMPT, _S_OUTPUT, _S_TENANT, _S_TOKEN = range(5)


def _mix64(x: int) -> int:
    """splitmix64 finalizer — the counter-PRNG core."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def _u64(seed: int, stream: int, *counters: int) -> int:
    x = _mix64(seed & _MASK64) ^ _mix64((stream + 1) * 0x9E3779B97F4A7C15)
    for c in counters:
        x = _mix64((x ^ (c & _MASK64)))
    return x


def _uniform(seed: int, stream: int, *counters: int) -> float:
    """[0, 1) with 53 random bits — enough for every inverse CDF here."""
    return (_u64(seed, stream, *counters) >> 11) * (1.0 / (1 << 53))


def _bounded_pareto(u: float, lo: int, hi: int, alpha: float) -> int:
    """Inverse CDF of a Pareto truncated to [lo, hi], floored to int —
    the heavy-tailed length draw."""
    assert 0 < lo <= hi and alpha > 0
    if lo == hi:
        return lo
    la, ha = float(lo) ** -alpha, float(hi + 1) ** -alpha
    x = (la - u * (la - ha)) ** (-1.0 / alpha)
    return max(lo, min(hi, int(x)))


@dataclasses.dataclass(frozen=True)
class Burst:
    """An additive arrival burst: ``factor``x the base rate over
    ``[start_tick, start_tick + width_ticks)`` — the spike primitive
    (thundering herd = one huge narrow burst at t0)."""

    start_tick: int
    width_ticks: int
    factor: float

    def rate_mult(self, tick: float) -> float:
        if self.start_tick <= tick < self.start_tick + self.width_ticks:
            return self.factor
        return 1.0


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    """One scenario's traffic shape.  Everything is in ticks; lengths
    must respect the serving budget (prompt_hi + output_hi <= the
    engine's max_seq) — `generate` asserts nothing here, the engine's
    ``validate_shape`` is the real gate."""

    n_requests: int
    seed: int
    base_interval_ticks: float = 4.0     # mean inter-arrival at rate 1x
    prompt_lo: int = 4
    prompt_hi: int = 16
    prompt_alpha: float = 1.2            # heavy tail exponent
    output_lo: int = 2
    output_hi: int = 8
    output_alpha: float = 1.5
    diurnal_period_ticks: int = 0        # 0 = no diurnal cycle
    diurnal_amplitude: float = 0.0       # in [0, 1): rate swings 1 +/- a
    bursts: Tuple[Burst, ...] = ()
    tenants: Tuple[Tuple[str, float], ...] = (("default", 1.0),)

    def rate_mult(self, tick: float) -> float:
        m = 1.0
        if self.diurnal_period_ticks > 0 and self.diurnal_amplitude > 0:
            phase = 2.0 * math.pi * tick / self.diurnal_period_ticks
            m *= 1.0 + self.diurnal_amplitude * math.sin(phase)
        for b in self.bursts:
            m *= b.rate_mult(tick)
        return max(m, 1e-6)


@dataclasses.dataclass(frozen=True)
class TrafficRequest:
    """One generated request — the replayable trace row."""

    uid: int
    arrival_tick: int
    prompt_len: int
    max_new: int
    tenant: str

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class Workload:
    """A generated trace plus its provenance: the config, the canonical
    JSON trace, a content fingerprint (what the determinism test pins
    byte-for-byte), and on-demand prompt-token materialization from the
    same counter PRNG (request uid + position => token, independent of
    generation order)."""

    def __init__(self, cfg: TrafficConfig,
                 requests: List[TrafficRequest]) -> None:
        self.cfg = cfg
        self.requests = requests

    def __len__(self) -> int:
        return len(self.requests)

    def trace(self) -> List[Dict[str, Any]]:
        return [r.as_dict() for r in self.requests]

    def trace_bytes(self) -> bytes:
        """Canonical byte encoding of the trace — THE replay identity."""
        return json.dumps(self.trace(), sort_keys=True,
                          separators=(",", ":")).encode()

    def fingerprint(self) -> str:
        return hashlib.sha256(self.trace_bytes()).hexdigest()

    def prompt_tokens(self, uid: int, vocab: int) -> np.ndarray:
        """int32 [prompt_len] for request ``uid`` — tokens are a pure
        function of (seed, uid, position), so two runs (or two replicas
        replaying the trace) materialize identical prompts."""
        req = self.requests[uid - 1]
        assert req.uid == uid, "trace uids must be 1..n in order"
        return np.asarray(
            [_u64(self.cfg.seed, _S_TOKEN, uid, j) % vocab
             for j in range(req.prompt_len)], np.int32)

    def prompts(self, vocab: int) -> List[np.ndarray]:
        return [self.prompt_tokens(r.uid, vocab) for r in self.requests]

    def arrivals_by_tick(self) -> Dict[int, List[TrafficRequest]]:
        out: Dict[int, List[TrafficRequest]] = {}
        for r in self.requests:
            out.setdefault(r.arrival_tick, []).append(r)
        return out

    def summary(self) -> Dict[str, Any]:
        lens = [r.prompt_len for r in self.requests]
        outs = [r.max_new for r in self.requests]
        by_tenant: Dict[str, int] = {}
        for r in self.requests:
            by_tenant[r.tenant] = by_tenant.get(r.tenant, 0) + 1
        return {
            "n_requests": len(self.requests),
            "seed": self.cfg.seed,
            "fingerprint": self.fingerprint(),
            "first_tick": self.requests[0].arrival_tick
            if self.requests else None,
            "last_tick": self.requests[-1].arrival_tick
            if self.requests else None,
            "prompt_len_min": min(lens) if lens else None,
            "prompt_len_max": max(lens) if lens else None,
            "max_new_total": sum(outs),
            "tenants": by_tenant,
        }


def generate(cfg: TrafficConfig) -> Workload:
    """Materialize the trace.  Arrival times integrate an exponential
    inter-arrival process whose instantaneous rate is shaped by the
    diurnal cycle and burst windows (thinning-free: the mean gap is
    divided by the rate multiplier AT the current arrival's tick, which
    is deterministic and good enough for a bench scenario — this is a
    load generator, not a queueing-theory proof)."""
    assert cfg.n_requests >= 1
    # cumulative tenant weights for the inverse-CDF categorical draw
    total_w = sum(w for _, w in cfg.tenants)
    assert total_w > 0
    cum: List[Tuple[str, float]] = []
    acc = 0.0
    for name, w in cfg.tenants:
        acc += w / total_w
        cum.append((name, acc))

    out: List[TrafficRequest] = []
    t = 0.0
    for i in range(cfg.n_requests):
        u = _uniform(cfg.seed, _S_ARRIVAL, i)
        gap = -math.log(1.0 - u) * cfg.base_interval_ticks
        t += gap / cfg.rate_mult(t)
        up = _uniform(cfg.seed, _S_PROMPT, i)
        uo = _uniform(cfg.seed, _S_OUTPUT, i)
        ut = _uniform(cfg.seed, _S_TENANT, i)
        tenant = cum[-1][0]
        for name, edge in cum:
            if ut < edge:
                tenant = name
                break
        out.append(TrafficRequest(
            uid=i + 1,
            arrival_tick=int(t),
            prompt_len=_bounded_pareto(up, cfg.prompt_lo, cfg.prompt_hi,
                                       cfg.prompt_alpha),
            max_new=_bounded_pareto(uo, cfg.output_lo, cfg.output_hi,
                                    cfg.output_alpha),
            tenant=tenant))
    return Workload(cfg, out)


# ---------------------------------------------------------------------------
# scenario presets (the fleet bench's rows; tests pin their determinism)
# ---------------------------------------------------------------------------

_TENANT_MIX = (("interactive", 0.7), ("batch", 0.3))


def steady_config(n: int, seed: int, **over: Any) -> TrafficConfig:
    """Flat arrivals — the baseline every other scenario perturbs."""
    kw: Dict[str, Any] = dict(n_requests=n, seed=seed,
                              base_interval_ticks=3.0,
                              tenants=_TENANT_MIX)
    kw.update(over)
    return TrafficConfig(**kw)


def spike_config(n: int, seed: int, *, spike_tick: int = 12,
                 spike_width: int = 10, spike_factor: float = 8.0,
                 **over: Any) -> TrafficConfig:
    """Steady load with one sharp burst — the closed-loop autoscaler
    demo: the spike drives queue depth past the CUSUM threshold and the
    scale-out must restore windowed TTFT."""
    kw: Dict[str, Any] = dict(
        n_requests=n, seed=seed, base_interval_ticks=4.0,
        bursts=(Burst(spike_tick, spike_width, spike_factor),),
        tenants=_TENANT_MIX)
    kw.update(over)
    return TrafficConfig(**kw)


def diurnal_config(n: int, seed: int, *, period: int = 48,
                   amplitude: float = 0.8, **over: Any) -> TrafficConfig:
    """Sinusoidal day/night cycle — sustained swings, no step edges."""
    kw: Dict[str, Any] = dict(
        n_requests=n, seed=seed, base_interval_ticks=3.0,
        diurnal_period_ticks=period, diurnal_amplitude=amplitude,
        tenants=_TENANT_MIX)
    kw.update(over)
    return TrafficConfig(**kw)


def thundering_herd_config(n: int, seed: int, *, herd_width: int = 3,
                           **over: Any) -> TrafficConfig:
    """Everything arrives at once (a restart's reconnect stampede): one
    enormous burst at tick 0 — the admission-shedding scenario."""
    kw: Dict[str, Any] = dict(
        n_requests=n, seed=seed, base_interval_ticks=2.0,
        bursts=(Burst(0, herd_width, 50.0),),
        tenants=_TENANT_MIX)
    kw.update(over)
    return TrafficConfig(**kw)
