"""Elastic serving fleet — disaggregated prefill/decode replicas with
live KV migration (docs/SERVING.md "The fleet").

The single-engine serving plane (PR 10) has one recovery tier: replay
from prompt with a fresh pool.  The fleet adds the tier the training
side already has (parallel/elastic.py's reshard ladder): when a replica
is preempted or drained, its in-flight requests' KV pages MIGRATE to
survivors over the exact-accounted handoff program (serve/handoff.py),
so the fleet loses zero prefill work.  On top of the same machinery the
fleet splits roles:

  prefill workers   run ONLY the chunked-prefill program; a completed
                    prefill's KV pages hand off to a decode worker
                    (prefill -> KV-handoff -> decode, the disaggregated
                    pipeline — each replica compiles exactly one of the
                    two jitted programs, asserted by tests).
  decode workers    run ONLY the masked decode program; they receive
                    work exclusively via ``ContinuousBatcher.adopt``
                    (pages already resident — zero replay).

Scheduling is deterministic (least-loaded with stable ties), so a
seeded fleet run replays exactly — which is what makes the replica-kill
chaos verdict BYTE-level: every surviving request's token stream must
equal the fault-free fleet run's, because per-request chunk schedules
are position-aligned and `forward_paged` is bitwise page-assignment-
invariant.

Failure story (chaos sites):

  fleet.membership  a preemption here IS a replica kill signal.  The
                    victim's pool buffers are still alive (the signal
                    arrives at the tick boundary, before any dispatch —
                    the same `state_buffers_alive` gate the training
                    reshard tier uses), so every live request migrates:
                    DECODE requests to decode survivors, mid-PREFILL
                    requests (partial KV kept, prefill resumes at
                    ``prefill_done``) and WAITING requests to prefill
                    survivors.  MTTR = detection -> fleet serviceable.
  serve.handoff     a fault inside a migration degrades that ONE
                    request to the replay tier (generated tokens kept,
                    re-prefill on a survivor) — counted in
                    ``fleet_replays``, never lost.

If a role loses its last replica, a survivor is promoted to
``role="both"`` — the fleet degrades to the single-engine plane instead
of wedging.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..models.llama import LlamaConfig
from ..obs.metrics import RequestSpans
from ..obs.slo import SloAggregator
from ..runtime import chaos as chaos_lib
from ..runtime.requests import DECODE, FINISHED, PREFILL, Request
from ..utils.observability import Profiler
# ONE definition of every routing/kill/migration decision — exhaustively
# explored by verify.sched; delegation asserted by identity in
# tests/test_sched.py (the PR-14 emitter discipline)
from ..verify.opstream import SCHED_RULES as _RULES
from . import handoff as handoff_lib
from .engine import ServeEngine
from .paged import ServeConfig

__all__ = ["FleetConfig", "ServeFleet", "Replica"]


@dataclass(frozen=True)
class FleetConfig:
    """Fleet topology: how many replicas hold which role.  Every replica
    shares one (LlamaConfig, ServeConfig) pair — the handoff plan's
    geometry precondition."""

    n_prefill: int = 1
    n_decode: int = 2

    def __post_init__(self) -> None:
        if self.n_prefill < 1 or self.n_decode < 1:
            raise ValueError("need >= 1 prefill and >= 1 decode replica")

    @property
    def n_replicas(self) -> int:
        return self.n_prefill + self.n_decode


@dataclass
class Replica:
    """One fleet member: an engine pinned to its own device."""

    idx: int
    engine: ServeEngine
    device: Any
    alive: bool = True

    @property
    def role(self) -> str:
        return self.engine.role

    def load(self) -> int:
        b = self.engine.batcher
        return len(b.live) + len(b.waiting)


class ServeFleet:
    """The fleet scheduler: routes requests prefill -> KV-handoff ->
    decode and rebalances on membership change.  Single-threaded drive
    loop (one tick drives every alive replica once); the thread-safe
    seams stay in `runtime.requests`."""

    def __init__(self, params: Dict[str, Any], cfg: LlamaConfig,
                 scfg: ServeConfig, fcfg: Optional[FleetConfig] = None, *,
                 profiler: Optional[Profiler] = None,
                 chaos: Optional[chaos_lib.FaultPlan] = None,
                 dtype: Optional[str] = None,
                 devices: Optional[Sequence[Any]] = None) -> None:
        self.cfg = cfg
        self.scfg = scfg
        self.fcfg = fcfg or FleetConfig()
        self.dtype = dtype
        self.profiler = profiler or Profiler()
        # fleet-level chaos only: engine ticks stay chaos-free here (the
        # single-engine serve.step battery covers that surface) so a
        # fleet fault plan's step counter tracks FLEET ticks
        self.chaos = chaos
        if chaos is not None and chaos.events is None:
            chaos.events = self.profiler.events
        # the FULL device list is retained: devices beyond n_replicas are
        # spares the autoscaler's scale-out claims via `add_replica`
        # (default: every jax device, so an 8-device mesh gives a
        # 3-replica fleet 5 spare slots for free)
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < self.fcfg.n_replicas:
            raise ValueError(
                f"fleet needs {self.fcfg.n_replicas} devices, have "
                f"{len(devices)}")
        self._params = params
        self._spare_devices: List[Any] = devices[self.fcfg.n_replicas:]
        self.replicas: List[Replica] = []
        for i in range(self.fcfg.n_replicas):
            role = "prefill" if i < self.fcfg.n_prefill else "decode"
            eng = ServeEngine(params, cfg, scfg, profiler=self.profiler,
                              dtype=dtype, device=devices[i],
                              replica_id=i, role=role)
            self.replicas.append(Replica(idx=i, engine=eng,
                                         device=devices[i]))
        self.requests: List[Request] = []       # fleet submission order
        self._arrivals: List[Request] = []
        self._uid = 0
        self._t0 = time.perf_counter()
        self.ticks = 0
        self._wall_s = 0.0
        # live SLO observatory: windowed tick-domain latency series +
        # per-tick pressure gauges, mirrored onto the event stream
        self.slo = SloAggregator(events=self.profiler.events)
        # the autoscaler's admission valve: True defers arrival routing
        # (requests stay queued host-side — deferred, never dropped)
        self.hold_admissions = False
        self.grows = 0
        self.role_changes = 0
        self.handoffs = 0
        self.handoff_wire_bytes = 0
        self.handoff_host_bytes = 0
        self.fleet_replays = 0                   # replay-tier fallbacks
        self.kills = 0
        # exact-tier handoff verification (scfg.page_integrity): a
        # tripped landed-page checksum retries once before degrading to
        # replay — bounded-retry-then-replay, counted honestly
        self.handoff_retries = 1
        self.handoff_integrity_trips = 0

    # -- membership ----------------------------------------------------------

    def _alive(self, role: Optional[str] = None) -> List[Replica]:
        out = [r for r in self.replicas if r.alive]
        if role is not None:
            out = [r for r in out if r.role in (role, "both")]
        return out

    # -- intake --------------------------------------------------------------

    def submit(self, prompt: np.ndarray, max_new: int, *,
               eos_id: Optional[int] = None,
               not_before_s: float = 0.0,
               tenant: Optional[str] = None) -> Request:
        """Validate against the shared static budget, then queue for the
        fleet router (arrival shaping as in `runtime.requests`).  The
        submit is also tick-stamped: the SLO observatory's latency
        series live in the fleet-tick domain, where a seeded run is
        machine-independent."""
        p = np.asarray(prompt, np.int32).reshape(-1)
        self.replicas[0].engine.batcher.validate_shape(int(p.shape[0]),
                                                       int(max_new))
        self._uid += 1
        req = Request(uid=self._uid, prompt=p, max_new=int(max_new),
                      eos_id=eos_id, not_before_s=float(not_before_s),
                      t_submit=time.perf_counter(), tenant=tenant,
                      submit_tick=self.ticks)
        self._arrivals.append(req)
        self.requests.append(req)
        attrs: Dict[str, Any] = {"uid": req.uid,
                                 "prompt_len": req.prompt_len,
                                 "max_new": req.max_new}
        if tenant is not None:
            attrs["tenant"] = tenant
        self.profiler.events.instant("fleet.submit", **attrs)
        return req

    def _pop_arrived(self) -> List[Request]:
        now = time.perf_counter() - self._t0
        out = [r for r in self._arrivals if r.not_before_s <= now]
        self._arrivals = [r for r in self._arrivals
                          if r.not_before_s > now]
        return out

    def _route_to_prefill(self, req: Request, *, front: bool = False
                          ) -> None:
        """Deterministic least-loaded routing with stable ties (list
        order) — what makes a seeded fleet run replay exactly."""
        cands = self._alive("prefill")
        pos = _RULES.route_least_loaded([(r.load(), r.idx)
                                         for r in cands])
        assert pos is not None, "no prefill-capable replica alive"
        cands[pos].engine.batcher.enqueue(req, front=front)

    # -- KV handoff ----------------------------------------------------------

    def _pick_decode_target(self, n_pages: int) -> Optional[Replica]:
        cands = [r for r in self._alive("decode")
                 if r.engine.batcher.free_slots > 0
                 and r.engine.alloc.free >= n_pages]
        pos = _RULES.route_least_loaded([(r.load(), r.idx)
                                         for r in cands])
        return None if pos is None else cands[pos]

    def _handoff(self, src: Replica, dst: Replica, req: Request, *,
                 state: str) -> None:
        """Migrate one request's KV pages src -> dst over the lowered
        transfer program; on success the request continues on dst with
        ZERO replay.  Raises on an injected handoff fault BEFORE any
        state moved (the caller degrades that request to replay).

        With ``scfg.page_integrity`` the transfer runs the
        integrity-checked program: the landed page blocks are
        re-checksummed bit-exactly against the source ledger's
        write-time entries (``handoff.lower_apply(integrity=True)``),
        so migrated KV has end-to-end write-time -> land-time coverage.
        A tripped verdict gets ONE bounded retry (a transient wire fault
        must not cost replay); a second trip raises
        ``WireIntegrityError`` and the caller degrades the request to
        the replay tier — degraded, never lost, never silently wrong."""
        if self.chaos is not None:
            self.chaos.fire("serve.handoff")     # may sleep or raise
        src_eng, dst_eng = src.engine, dst.engine
        src_pages = src_eng.batcher.pages_of(req)
        n = len(src_pages)
        assert n >= 1, "handoff of a pageless request"
        dst_pages = dst_eng.alloc.alloc(n)
        assert dst_pages is not None, "target picked without capacity"
        plan = handoff_lib.plan_for(self.cfg, self.scfg, n,
                                    dtype=self.dtype)
        mesh = handoff_lib.pair_mesh(src.device, dst.device)
        integrity = bool(self.scfg.page_integrity)
        expect = src_eng.ledger_entries(src_pages) if integrity else None
        with self.profiler.events.span(
                "fleet.handoff", lane="serve", uid=req.uid, src=src.idx,
                dst=dst.idx, pages=n, wire_bytes=plan.wire_bytes(),
                integrity=integrity):
            ok = True
            for attempt in range(self.handoff_retries + 1):
                res = handoff_lib.apply_handoff(
                    plan, mesh, src_eng.pool, dst_eng.pool, src_pages,
                    dst_pages, expect=expect)
                if integrity:
                    new_src, new_dst, ok, landed = res
                    # ALWAYS record what actually landed — a rejected
                    # page stays free-and-dirty and dirty pages must be
                    # ledger-consistent (engine.record_landed_pages)
                    src_eng.pool, dst_eng.pool = new_src, new_dst
                    dst_eng.record_landed_pages(dst_pages, landed)
                    if ok:
                        break
                    self.handoff_integrity_trips += 1
                    self.profiler.events.instant(
                        "fleet.handoff_trip", uid=req.uid, src=src.idx,
                        dst=dst.idx, attempt=attempt)
                else:
                    new_src, new_dst = res
                    src_eng.pool, dst_eng.pool = new_src, new_dst
                    break
        if not ok:
            dst_eng.alloc.free_pages(dst_pages)
            raise chaos_lib.WireIntegrityError(
                f"KV handoff {src.idx}->{dst.idx} for request {req.uid} "
                f"failed its landed-page checksums "
                f"{self.handoff_retries + 1}x — degrading this request "
                "to the replay tier (KV discarded, tokens kept)")
        src_eng.batcher.release(req)
        slot = dst_eng.batcher.adopt(req, dst_pages, state=state)
        assert slot is not None, "target lost its free slot mid-handoff"
        src_eng.stats.record_handoff_out()
        dst_eng.stats.record_handoff_in()
        self.handoffs += 1
        self.handoff_wire_bytes += plan.wire_bytes()
        self.handoff_host_bytes += plan.host_bytes(
            req.prompt_len + len(req.generated))

    def _replay_fallback(self, src: Replica, req: Request) -> None:
        """The degraded tier: release the request's pages (KV lost) and
        requeue it front-of-line on a prefill survivor with its
        generated tokens kept — replay-from-prompt for THIS request
        only, counted honestly."""
        if req.slot >= 0:
            src.engine.batcher.release(req)
        self.fleet_replays += 1
        self.profiler.events.instant("fleet.replay", uid=req.uid,
                                     src=src.idx)
        self._route_to_prefill(req, front=True)

    def _migrate_or_replay(self, src: Replica, req: Request, *,
                           state: str, park_ok: bool = False) -> None:
        """``park_ok`` distinguishes the two callers: the per-tick
        prefill->decode drain may PARK a request on its (healthy)
        prefill worker when the decode fleet is transiently full — the
        handoff simply retries next tick, no prefill work is thrown
        away (backpressure, not replay).  The kill path cannot park
        (the source replica is dying) and degrades to replay instead."""
        role = "decode" if state == DECODE else "prefill"
        n = len(src.engine.batcher.pages_of(req))
        if role == "decode":
            dst = self._pick_decode_target(n)
        else:
            cands = [r for r in self._alive("prefill") if r is not src
                     and r.engine.batcher.free_slots > 0
                     and r.engine.alloc.free >= n]
            pos = _RULES.route_least_loaded([(r.load(), r.idx)
                                             for r in cands])
            dst = None if pos is None else cands[pos]
        if dst is None and park_ok:
            return                       # retry next tick; pages stay
        if dst is None or n == 0:
            self._replay_fallback(src, req)
            return
        try:
            self._handoff(src, dst, req, state=state)
        except (chaos_lib.InjectedFault,
                chaos_lib.WireIntegrityError) as err:
            kind = ("wire-corruption"
                    if isinstance(err, chaos_lib.WireIntegrityError)
                    else err.kind)
            ev = self.profiler.recovery.record_fault(
                kind, step=self.ticks, site="serve.handoff",
                error=repr(err))
            t0 = time.perf_counter()
            self._replay_fallback(src, req)
            self.profiler.recovery.record_recovery(
                time.perf_counter() - t0, event=ev)

    # -- membership change (the replica-kill tier) ---------------------------

    def _pick_victim(self) -> Optional[Replica]:
        """Deterministic kill target: the loaded-most decode replica
        (maximum blast radius — 'kill a replica mid-decode'), stable
        ties by index; any alive replica when no decode is left."""
        if len(self._alive()) <= 1:
            return None
        cands = self._alive("decode") or self._alive()
        pos = _RULES.pick_kill_victim([(r.load(), r.idx)
                                       for r in cands])
        return None if pos is None else cands[pos]

    def kill_replica(self, idx: int) -> None:
        """Planned scale-down / drain of one replica: migrate everything
        it holds to survivors, then remove it from membership.  The
        chaos preemption at ``fleet.membership`` routes here."""
        victim = self.replicas[idx]
        assert victim.alive, f"replica {idx} already dead"
        assert len(self._alive()) > 1, "cannot kill the last replica"
        ev = self.profiler.recovery.record_fault(
            "replica_kill", step=self.ticks, site="fleet.membership",
            error=f"replica {idx} preempted")
        t0 = time.perf_counter()
        victim.alive = False            # no further routing to it
        self.kills += 1
        self._promote_if_role_lost()
        eng = victim.engine
        migratable = chaos_lib.state_buffers_alive(eng.pool)
        live = sorted(eng.batcher.live, key=lambda r: r.admit_seq)
        for req in live:
            act = _RULES.migration_action(
                req.state, bool(eng.batcher.pages_of(req)), migratable)
            if act == "migrate":
                self._migrate_or_replay(victim, req, state=req.state)
            elif act == "reroute":
                # admitted but no KV written yet: re-routing loses zero
                # work — NOT a replay
                eng.batcher.release(req)
                self._route_to_prefill(req, front=True)
            else:
                self._replay_fallback(victim, req)
        while eng.batcher.waiting:
            self._route_to_prefill(eng.batcher.waiting.pop(0))
        self.profiler.recovery.record_recovery(
            time.perf_counter() - t0, event=ev)
        self.profiler.events.instant(
            "fleet.membership", tick=self.ticks, victim=idx,
            survivors=[r.idx for r in self._alive()],
            migrated=sum(1 for _ in live))

    def _promote_if_role_lost(self) -> None:
        """A role with zero survivors promotes the least-loaded survivor
        to role='both' — the fleet degrades to the single-engine plane
        instead of wedging (its missing program traces once, a bounded
        one-off)."""
        for role in ("prefill", "decode"):
            if not self._alive(role):
                cands = self._alive()
                pos = _RULES.route_least_loaded([(r.load(), r.idx)
                                                 for r in cands])
                assert pos is not None, "no survivor to promote"
                survivor = cands[pos]
                survivor.engine.role = "both"
                self.profiler.events.instant(
                    "fleet.promote", replica=survivor.idx,
                    lost_role=role)

    # -- membership growth + role rebalance (the autoscaler's levers) --------

    @property
    def spare_devices(self) -> int:
        return len(self._spare_devices)

    def add_replica(self, role: str = "decode") -> Optional[Replica]:
        """Scale-out: a spare device joins the fleet as a fresh replica.
        Returns None when no spare is left (the caller falls back to
        rebalance).  The new engine's two programs trace lazily on first
        use — exactly one trace each, so ``recompiles_steady`` (which
        counts traces BEYOND the first) stays 0 across a scale event:
        the no-flapping evidence the bench banks."""
        if not self._spare_devices:
            return None
        device = self._spare_devices.pop(0)
        idx = len(self.replicas)
        eng = ServeEngine(self._params, self.cfg, self.scfg,
                          profiler=self.profiler, dtype=self.dtype,
                          device=device, replica_id=idx, role=role)
        rep = Replica(idx=idx, engine=eng, device=device)
        self.replicas.append(rep)
        self.grows += 1
        self.profiler.events.instant(
            "fleet.membership", tick=self.ticks, joined=idx, role=role,
            survivors=[r.idx for r in self._alive()])
        return rep

    def set_role(self, idx: int, role: str) -> None:
        """Role rebalance (e.g. a surplus prefill worker promoted to
        role='both' when the decode pool is the bottleneck and no spare
        device remains).  Same bounded one-off trace note as
        `add_replica`: the newly-exercised program traces once."""
        rep = self.replicas[idx]
        assert rep.alive, f"replica {idx} is dead"
        if rep.engine.role == role:
            return
        old = rep.engine.role
        rep.engine.role = role
        self.role_changes += 1
        self.profiler.events.instant("fleet.rebalance", tick=self.ticks,
                                     replica=idx, from_role=old,
                                     to_role=role)

    def load_signals(self) -> Dict[str, float]:
        """The autoscaler's per-tick signal read — every value is a
        deterministic function of the tick-domain schedule (no wall
        clocks), so a seeded run produces the same signal sequence, and
        the same decision sequence, on any machine."""
        alive = self._alive()
        waiting = sum(len(r.engine.batcher.waiting) for r in alive)
        queue_depth = waiting + len(self._arrivals)
        usable = max(1, len(alive)) * self.scfg.usable_pages
        free = sum(r.engine.alloc.free for r in alive)
        in_use = sum(r.engine.alloc.in_use for r in alive)
        live = sum(len(r.engine.batcher.live) for r in alive)
        pure_prefill = [r for r in alive if r.role == "prefill"]
        pure_decode = [r for r in alive if r.role == "decode"]
        rb = _RULES.route_least_loaded([(r.load(), r.idx)
                                        for r in pure_prefill])
        si = _RULES.route_least_loaded([(r.load(), r.idx)
                                        for r in pure_decode])
        rebalance = None if rb is None else pure_prefill[rb]
        scale_in = None if si is None else pure_decode[si]
        return {
            "queue_depth": float(queue_depth),
            "live": float(live),
            "n_alive": float(len(alive)),
            "n_prefill": float(len(self._alive("prefill"))),
            "n_decode": float(len(self._alive("decode"))),
            "n_prefill_pure": float(len(pure_prefill)),
            "n_decode_pure": float(len(pure_decode)),
            "rebalance_idx": float(rebalance.idx
                                   if rebalance is not None else -1),
            "scale_in_idx": float(scale_in.idx
                                  if scale_in is not None else -1),
            "pages_in_use": float(in_use),
            "free_pages": float(free),
            "free_frac": float(free) / usable,
            "spare_devices": float(self.spare_devices),
        }

    # -- the drive loop ------------------------------------------------------

    def _observe_slo(self) -> None:
        """End-of-tick observatory feed: stamp tick-domain request
        milestones (admit / first token / done are detected by state,
        so the stamp lands on the tick the transition happened) and push
        the windows + pressure gauges.  O(n_requests) per tick — the
        fleet drive loop is host-side and n is bench-scale."""
        for r in self.requests:
            if r.admit_tick < 0 and not math.isnan(r.t_admit):
                r.admit_tick = self.ticks
                self.slo.observe("queue_wait",
                                 float(r.admit_tick - r.submit_tick))
            if r.first_tick < 0 and r.generated:
                r.first_tick = self.ticks
                self.slo.observe("ttft",
                                 float(r.first_tick - r.submit_tick))
            if r.done_tick < 0 and r.state == FINISHED:
                r.done_tick = self.ticks
                n = len(r.generated)
                self.slo.observe("tpot",
                                 (r.done_tick - r.first_tick) / (n - 1)
                                 if n > 1 else 0.0)
        sig = self.load_signals()
        self.slo.gauge("queue_depth", sig["queue_depth"])
        self.slo.gauge("pages_in_use", sig["pages_in_use"])
        self.slo.gauge("free_pages", sig["free_pages"])
        for rep in self._alive():
            self.slo.gauge("batch_occupancy",
                           len(rep.engine.batcher.live)
                           / self.scfg.max_reqs, replica=rep.idx)

    def tick(self) -> bool:
        """One fleet tick: membership chaos, routing, prefill->decode
        handoffs, one engine tick per alive replica, decode-side replay
        drain.  Returns False when nothing progressed (idle)."""
        if self.chaos is not None:
            self.chaos.begin_step(self.ticks)
            try:
                self.chaos.fire("fleet.membership")
            except chaos_lib.InjectedPreemption:
                victim = self._pick_victim()
                if victim is not None:
                    self.kill_replica(victim.idx)
            except chaos_lib.InjectedFault as err:
                # a transient membership-plane error: note and continue
                self.profiler.events.instant(
                    "fleet.membership_error", tick=self.ticks,
                    error=repr(err)[:120])
        if not self.hold_admissions:
            # the autoscaler's shed valve: while held, arrivals stay in
            # the host-side queue (deferred, never dropped) and the pool
            # drains toward the resume watermark
            for req in self._pop_arrived():
                self._route_to_prefill(req)
        # completed prefills hand off BEFORE the next engine tick, so a
        # prefill-role replica never decodes
        for rep in list(self._alive("prefill")):
            if rep.role == "both":
                continue                 # degraded mode decodes locally
            for req in [r for r in rep.engine.batcher.live
                        if r.state == DECODE]:
                self._migrate_or_replay(rep, req, state=DECODE,
                                        park_ok=True)
        progressed = False
        for rep in self._alive():
            progressed = rep.engine.tick() or progressed
        # an eviction on a decode replica lands in ITS waiting list but
        # must replay through a prefill worker
        for rep in self._alive():
            if rep.role != "decode":
                continue
            while rep.engine.batcher.waiting:
                req = rep.engine.batcher.waiting.pop(0)
                self._replay_fallback(rep, req)
        self._observe_slo()
        self.ticks += 1
        return progressed

    def run(self, *, max_ticks: int = 1_000_000) -> Dict[str, Any]:
        """Serve until every submitted request finishes; returns
        `summary()`."""
        t0 = time.perf_counter()
        while (self._arrivals
               or any(r.state != FINISHED for r in self.requests)):
            if self.ticks >= max_ticks:
                raise RuntimeError(
                    f"fleet loop exceeded max_ticks={max_ticks} with "
                    f"{sum(1 for r in self.requests if r.state != FINISHED)}"
                    " unfinished requests")
            if not self.tick():
                time.sleep(0.001)
        self._wall_s += time.perf_counter() - t0
        return self.summary()

    # -- introspection -------------------------------------------------------

    def request_summary(self) -> Dict[str, Any]:
        """Fleet-level latency percentiles computed from the request
        timestamps themselves (TTFT spans replica boundaries and the
        kill event — a migrated request's clock never resets)."""
        spans = RequestSpans()
        for r in self.requests:
            if r.state == FINISHED and not math.isnan(r.t_done):
                spans.record(r.uid, t_submit=r.t_submit,
                             t_admit=r.t_admit, t_first=r.t_first,
                             t_done=r.t_done, n_tokens=len(r.generated))
        return spans.summary()

    def obs_static_metrics(self) -> Dict[str, Any]:
        return {"fleet": {
            "n_prefill": self.fcfg.n_prefill,
            "n_decode": self.fcfg.n_decode,
            "n_replicas": self.fcfg.n_replicas,
        }}

    def summary(self) -> Dict[str, Any]:
        per_replica = []
        agg: Dict[str, int] = {}
        recompiles = 0
        for rep in self.replicas:
            s = rep.engine.stats.as_dict()
            for k, v in s.items():
                agg[k] = agg.get(k, 0) + v
            recompiles += rep.engine.recompiles_steady()
            per_replica.append({
                "replica": rep.idx, "role": rep.role,
                "alive": rep.alive, "ticks": rep.engine.ticks,
                "evictions": rep.engine.batcher.evictions,
                "pages_in_use_peak": rep.engine.alloc.peak_in_use,
                "trace_counts": rep.engine.trace_counts(), **s})
        rec = self.profiler.recovery.as_dict()
        wall = self._wall_s
        return {
            "ticks": self.ticks,
            "wall_s": round(wall, 4),
            "n_requests": len(self.requests),
            "completed": agg.get("completed", 0),
            "tokens_out": agg.get("tokens_out", 0),
            "throughput_tok_s": (round(agg.get("tokens_out", 0) / wall, 2)
                                 if wall > 0 else None),
            "handoffs": self.handoffs,
            "handoff_wire_bytes": self.handoff_wire_bytes,
            "handoff_host_bytes": self.handoff_host_bytes,
            "handoff_integrity_trips": self.handoff_integrity_trips,
            "page_trips": sum(r.engine.page_trips for r in self.replicas),
            "logit_trips": sum(r.engine.logit_trips
                               for r in self.replicas),
            "fleet_replays": self.fleet_replays,
            "kills": self.kills,
            "grows": self.grows,
            "role_changes": self.role_changes,
            "spare_devices": self.spare_devices,
            "serve_recoveries": agg.get("serve_recoveries", 0),
            "evictions": sum(r.engine.batcher.evictions
                             for r in self.replicas),
            "recompiles_steady": recompiles,
            "replicas": per_replica,
            "requests": self.request_summary(),
            "slo": self.slo.snapshot(),
            "recovery": {"faults": rec["faults"],
                         "recoveries": rec["recoveries"],
                         "mttr_mean_s": rec["mttr_mean_s"]},
            **self.obs_static_metrics(),
        }
