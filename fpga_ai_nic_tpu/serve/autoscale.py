"""Closed-loop fleet autoscaler: windowed SLO metrics -> fleet actions.

The first real CONSUMER of the serving observatory (`obs.slo`): every
fleet tick the controller reads the live signals (queue depth, pool
pressure, windowed TTFT percentiles) and decides — with the PR-13
discipline, not a bare threshold — whether the fleet should change
shape.  Detection is the same two-sided CUSUM `tune.adapt.DriftDetector`
the drift observatory uses (sustained shifts accumulate, one-tick spikes
drain; hysteresis cooldown after every trip prevents flapping), applied
to LOAD instead of plan drift:

    residual = queue_depth / (target_queue_per_decode * n_decode) - 1

  "slow" trip (sustained overload)   scale OUT: a spare device joins as
                                     a decode replica; with no spare
                                     left, REBALANCE: a surplus prefill
                                     replica is promoted to role="both"
                                     so it decodes too.
  "fast" trip (sustained idle)       scale IN: the least-loaded pure
                                     decode replica drains via
                                     ``kill_replica`` (live work
                                     migrates over the KV handoff —
                                     zero token loss by construction).

Admission shedding is a separate hysteresis band on the free-page
fraction (the pool watermark): below ``shed_free_frac_lo`` the fleet
HOLDS new admissions (arrivals queue host-side — deferred, never
dropped, zero token loss); above ``shed_free_frac_hi`` intake resumes.
The lo < hi gap is what keeps the valve from chattering at the
boundary.

Every gated-through action lands as a ``scale.decision`` instant on the
event stream carrying its full evidence window (tick, CUSUM statistic,
residual, queue depth, free-page fraction, windowed p99 TTFT), so the
Perfetto timeline shows WHY the fleet scaled — the `adapt.switch`
contract applied to serving.  Trips that gate NO action (no spare
device, at min_decode) emit ``scale.suppressed`` and are NOT counted as
decisions: the banked per-seed decision counts stay exact.

Everything here is tick-deterministic host Python: a seeded scenario
replays the same decision sequence on any machine, which is what lets
obs-gate pin `fleet.slo.*` decision counts two-sided-exact.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Protocol

from ..obs.slo import SloAggregator
from ..tune.adapt import DriftDetector
# ONE definition of the residual/gate/valve rules — exhaustively
# explored by verify.sched (the no-flap invariant rides these exact
# functions); delegation asserted by identity in tests/test_sched.py
from ..verify.opstream import SCHED_RULES as _RULES

__all__ = ["AutoscaleConfig", "ScaleDecision", "Autoscaler",
           "FleetActions"]


class FleetActions(Protocol):
    """The fleet surface the controller drives — `serve.fleet.ServeFleet`
    implements it; tests substitute a recording fake (the controller
    logic is pure host Python and must be testable without compiling a
    single engine)."""

    hold_admissions: bool

    def load_signals(self) -> Dict[str, float]: ...

    def add_replica(self, role: str = "decode") -> Optional[Any]: ...

    def kill_replica(self, idx: int) -> None: ...

    def set_role(self, idx: int, role: str) -> None: ...


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Controller knobs.  CUSUM defaults mirror `tune.adapt` (drift
    slack 0.75, threshold 3.0) with the cooldown in fleet ticks."""

    target_queue_per_decode: float = 2.0   # queued reqs a decode absorbs
    drift_rel: float = 0.75
    threshold: float = 3.0
    cooldown_ticks: int = 8
    min_decode: int = 1
    shed_free_frac_lo: float = 0.10        # hold admissions below
    shed_free_frac_hi: float = 0.30        # resume above (hysteresis)

    def __post_init__(self) -> None:
        if not 0.0 <= self.shed_free_frac_lo < self.shed_free_frac_hi:
            raise ValueError(
                "need 0 <= shed_free_frac_lo < shed_free_frac_hi "
                f"(got {self.shed_free_frac_lo}, {self.shed_free_frac_hi})")
        if self.min_decode < 1:
            raise ValueError("min_decode must be >= 1")


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One gated-through fleet action plus its evidence window — exactly
    what the ``scale.decision`` event (and the bench's ``slo`` row)
    records.  The `tune.adapt.SwitchDecision` pattern applied to
    serving."""

    action: str                 # scale_out | scale_in | rebalance |
    #                             shed_on | shed_off
    tick: int
    evidence: Dict[str, Any]


class Autoscaler:
    """The per-fleet controller: call ``observe_tick()`` once per fleet
    tick AFTER ``fleet.tick()`` (signals then reflect the tick's
    routing/admissions).  Single-threaded by contract, like the fleet
    drive loop itself."""

    def __init__(self, fleet: FleetActions, slo: SloAggregator, *,
                 cfg: Optional[AutoscaleConfig] = None,
                 events: Optional[Any] = None) -> None:
        self.fleet = fleet
        self.slo = slo
        self.cfg = cfg or AutoscaleConfig()
        self.events = events
        self.detector = DriftDetector(
            drift_rel=self.cfg.drift_rel, threshold=self.cfg.threshold,
            cooldown_steps=self.cfg.cooldown_ticks)
        self.ticks = 0
        self.decisions: List[ScaleDecision] = []
        self.scale_outs = 0
        self.scale_ins = 0
        self.rebalances = 0
        self.sheds = 0              # shed_on events (holds opened)
        self.suppressed = 0         # trips that gated no action

    # -- decision plumbing --------------------------------------------------

    def _decide(self, action: str, evidence: Dict[str, Any]
                ) -> ScaleDecision:
        dec = ScaleDecision(action=action, tick=self.ticks,
                            evidence=dict(evidence))
        self.decisions.append(dec)
        if self.events is not None:
            self.events.instant("scale.decision", action=action,
                                tick=self.ticks, **dec.evidence)
        return dec

    def _suppress(self, evidence: Dict[str, Any]) -> None:
        # evidence already carries the trip direction (merged at trip
        # time), so the instant spreads it without duplication
        self.suppressed += 1
        if self.events is not None:
            self.events.instant("scale.suppressed", tick=self.ticks,
                                **evidence)

    # -- the per-tick loop closure ------------------------------------------

    def observe_tick(self) -> List[ScaleDecision]:
        """Read signals, update the detector, gate actions.  Returns the
        decisions taken THIS tick (usually none)."""
        cfg = self.cfg
        sig = self.fleet.load_signals()
        n_decode = max(1, int(sig["n_decode"]))
        queue_depth = float(sig["queue_depth"])
        residual = _RULES.load_residual(
            queue_depth, cfg.target_queue_per_decode, n_decode)
        p99 = self.slo.window_stat("ttft", "p99")
        evidence: Dict[str, Any] = {
            "residual": round(residual, 4),
            "queue_depth": queue_depth,
            "n_decode": n_decode,
            "free_frac": round(float(sig["free_frac"]), 4),
            "ttft_p99_window": p99,
            "window": self.slo.window,
        }
        out: List[ScaleDecision] = []
        trip = self.detector.update(residual)
        if trip is not None:
            direction, stat = trip
            evidence = {**evidence, "cusum_stat": round(stat, 4),
                        "direction": direction}
            if direction == "slow":
                out.extend(self._scale_up(evidence, sig))
            else:
                out.extend(self._scale_down(evidence, sig))
        out.extend(self._shed_valve(evidence, sig))
        self.ticks += 1
        return out

    def _scale_up(self, evidence: Dict[str, Any],
                  sig: Dict[str, float]) -> List[ScaleDecision]:
        if self.fleet.add_replica("decode") is not None:
            self.scale_outs += 1
            return [self._decide("scale_out", evidence)]
        # no spare device: rebalance a surplus prefill worker into the
        # decode pool instead (role="both" — it keeps prefilling)
        if _RULES.scale_up_fallback(
                int(sig["n_prefill_pure"]),
                int(sig["rebalance_idx"])) == "rebalance":
            self.fleet.set_role(int(sig["rebalance_idx"]), "both")
            self.rebalances += 1
            return [self._decide("rebalance", evidence)]
        self._suppress(evidence)
        return []

    def _scale_down(self, evidence: Dict[str, Any],
                    sig: Dict[str, float]) -> List[ScaleDecision]:
        idx = int(sig["scale_in_idx"])
        if _RULES.scale_down_ok(int(sig["n_decode_pure"]),
                                self.cfg.min_decode,
                                float(sig["queue_depth"]), idx):
            self.fleet.kill_replica(idx)
            self.scale_ins += 1
            return [self._decide("scale_in", evidence)]
        self._suppress(evidence)
        return []

    def _shed_valve(self, evidence: Dict[str, Any],
                    sig: Dict[str, float]) -> List[ScaleDecision]:
        free_frac = float(sig["free_frac"])
        shed = _RULES.shed_action(self.fleet.hold_admissions, free_frac,
                                  self.cfg.shed_free_frac_lo,
                                  self.cfg.shed_free_frac_hi)
        if shed == "shed_on":
            self.fleet.hold_admissions = True
            self.sheds += 1
            return [self._decide("shed_on", evidence)]
        if shed == "shed_off":
            self.fleet.hold_admissions = False
            return [self._decide("shed_off", evidence)]
        return []

    # -- introspection ------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """Exact per-seed decision accounting — the bench's ``slo`` row
        feedstock (every value deterministic in the tick domain)."""
        return {
            "decisions": len(self.decisions),
            "scale_outs": self.scale_outs,
            "scale_ins": self.scale_ins,
            "rebalances": self.rebalances,
            "sheds": self.sheds,
            "suppressed": self.suppressed,
            "detector_trips": self.detector.trips,
            "first_scale_out_tick": next(
                (d.tick for d in self.decisions
                 if d.action == "scale_out"), -1),
        }
