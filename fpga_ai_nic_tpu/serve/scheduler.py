"""Continuous-batching scheduler — host policy over the paged pool.

Per engine tick the batcher decides WHICH requests occupy the static
decode slots and WHERE their KV pages live; the jitted step then runs
with those decisions as plain array values.  Policies (all deterministic,
so a seeded serving run replays exactly):

  - **Admission**: FIFO from the waiting queue whenever a slot is free
    and the free-page watermark covers the request's current replay
    length + 1 (enough to prefill and take the first decode step without
    immediately thrashing).  Page allocation itself is LAZY — pages are
    claimed as positions advance, so a short completion hands capacity
    to the next request mid-prefill.
  - **Chunked prefill**: one static-width chunk per tick (oldest PREFILL
    request first), interleaved with the decode batch — a long prompt
    never stalls every decoding request for its whole prefill, only by
    one chunk's latency (the Sarathi/vLLM discipline).
  - **Eviction**: when a page is needed and the pool is dry, the
    NEWEST-admitted other live request is evicted — pages freed, request
    requeued at the FRONT of the waiting queue with its generated tokens
    kept host-side.  Re-admission replays prompt + generated[:-1] as a
    prefill (greedy decode is deterministic, so the continuation is
    token-identical; pinned by tests/test_serve.py).  LIFO victims bound
    eviction cascades: the oldest request monotonically progresses, so
    any workload whose single worst request fits the pool terminates.

Submission validates that a request's WORST-CASE footprint
(prompt + max_new) fits both one page-table row and the usable pool, so
a lone request can always run to completion — the no-deadlock base case
the eviction policy leans on.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from ..runtime.requests import (DECODE, FINISHED, PREFILL, WAITING,
                                Request)
# ONE definition of every discrete scheduling decision — the graftsched
# model (verify.sched) explores these exact rules exhaustively;
# tests/test_sched.py asserts the delegation by identity (the PR-14
# emitter discipline: no hand transcription may survive here)
from ..verify.opstream import SCHED_RULES as _RULES
from .paged import NULL_PAGE, PageAllocator, ServeConfig

__all__ = ["ContinuousBatcher"]


class ContinuousBatcher:
    """Slot/page bookkeeping + the admit/evict/interleave policy.

    Single-threaded by contract (the engine loop); the cross-thread
    intake is `runtime.requests.RequestQueue`."""

    def __init__(self, scfg: ServeConfig, alloc: PageAllocator,
                 stats: Optional[Any] = None) -> None:
        self.scfg = scfg
        self.alloc = alloc
        self._stats = stats          # runtime.requests.ServeStats or None
        # the static page table the device step consumes (int32, shape
        # [max_reqs, max_pages_per_seq]); NULL_PAGE marks unallocated
        self.table = np.full((scfg.max_reqs, scfg.max_pages_per_seq),
                             NULL_PAGE, np.int32)
        self._pages: List[List[int]] = [[] for _ in range(scfg.max_reqs)]
        self.slots: List[Optional[Request]] = [None] * scfg.max_reqs
        self.waiting: List[Request] = []
        self.evictions = 0
        self._admit_seq = 0

    # -- intake --------------------------------------------------------------

    def validate_shape(self, prompt_len: int, max_new: int) -> None:
        """Reject requests that could never run alone (the eviction
        policy's termination argument needs every accepted request to fit
        the pool by itself)."""
        worst = prompt_len + max_new
        if worst > self.scfg.max_seq:
            raise ValueError(
                f"prompt {prompt_len} + max_new {max_new} = {worst} "
                f"exceeds max_seq {self.scfg.max_seq} "
                "(= max_pages_per_seq * page_size)")
        if self.scfg.pages_for(worst) > self.scfg.usable_pages:
            raise ValueError(
                f"worst case needs {self.scfg.pages_for(worst)} pages "
                f"but the pool holds {self.scfg.usable_pages} usable "
                "pages")

    def enqueue(self, req: Request, *, front: bool = False) -> None:
        self.validate_shape(req.prompt_len, req.max_new)
        req.state = WAITING
        req.slot = -1
        req.prefill_done = 0
        # replay target: every position the cache must hold before decode
        # can resume (prompt + all generated but the newest, whose K/V
        # the resuming decode step writes itself)
        req.replay_len = _RULES.replay_target(req.n_tokens)
        if front:
            self.waiting.insert(0, req)
        else:
            self.waiting.append(req)

    # -- admission -----------------------------------------------------------

    def _committed_outstanding(self) -> int:
        """Pages already PROMISED to live requests but not yet allocated
        (allocation is lazy): a prefilling request will claim up to
        replay_len + 1 positions' worth, a decoding one its next
        position.  The admission watermark subtracts this so a newly
        admitted request cannot immediately force an eviction storm."""
        return _RULES.committed_outstanding(
            [(self.scfg.pages_for(
                _RULES.committed_target(r.state, r.replay_len,
                                        r.n_tokens)),
              len(self._pages[r.slot]))
             for r in self.slots if r is not None])

    def admit(self) -> List[Request]:
        """Admit waiting requests into free slots while the free-page
        watermark holds; returns the newly admitted set (telemetry)."""
        out: List[Request] = []
        while self.waiting:
            slot = next((i for i, r in enumerate(self.slots) if r is None),
                        None)
            if slot is None:
                break
            req = self.waiting[0]
            need = self.scfg.pages_for(
                _RULES.admission_need(req.replay_len))
            if not _RULES.admit_ok(self.alloc.free,
                                   self._committed_outstanding(), need):
                break                     # watermark: avoid admit-thrash
            self.waiting.pop(0)
            req.slot = slot
            req.state = PREFILL
            self._admit_seq += 1
            req.admit_seq = self._admit_seq
            self.slots[slot] = req
            out.append(req)
        return out

    # -- pages ---------------------------------------------------------------

    def ensure_pages(self, req: Request, n_positions: int) -> bool:
        """Grow ``req``'s page set to cover ``n_positions``, evicting
        newer requests if the pool is dry.  False = cannot proceed this
        tick (every evictable victim is older, or req is alone)."""
        slot = req.slot
        need = self.scfg.pages_for(n_positions)
        if need > self.scfg.max_pages_per_seq:
            raise ValueError(
                f"request {req.uid} needs {need} pages > table width "
                f"{self.scfg.max_pages_per_seq}")
        while len(self._pages[slot]) < need:
            got = self.alloc.alloc(1)
            if got is None:
                victim = self._eviction_victim(req)
                if victim is None:
                    return False
                self.evict(victim)
                continue
            self.table[slot, len(self._pages[slot])] = got[0]
            self._pages[slot].append(got[0])
        return True

    def _eviction_victim(self, protect: Request) -> Optional[Request]:
        """Newest-admitted live request other than ``protect`` that holds
        at least one reclaimable page."""
        live = [r for r in self.slots
                if r is not None and r is not protect
                and self._pages[r.slot]]
        pos = _RULES.pick_victim([r.admit_seq for r in live])
        return None if pos is None else live[pos]

    def evict(self, req: Request) -> None:
        """Free the request's pages and requeue it (front — evicted work
        has priority) with its generated tokens kept for replay."""
        self._release_slot(req)
        req.evictions += 1
        self.evictions += 1
        if self._stats is not None:
            self._stats.record_evicted()
        self.enqueue(req, front=True)

    def _release_slot(self, req: Request) -> None:
        slot = req.slot
        if self._pages[slot]:
            self.alloc.free_pages(self._pages[slot])
            self._pages[slot] = []
        self.table[slot, :] = NULL_PAGE
        self.slots[slot] = None
        req.slot = -1

    def finish(self, req: Request) -> None:
        self._release_slot(req)
        req.state = FINISHED

    # -- KV handoff (serve.fleet) --------------------------------------------

    @property
    def free_slots(self) -> int:
        return sum(1 for r in self.slots if r is None)

    def pages_of(self, req: Request) -> List[int]:
        """The page ids a live request currently owns (copy) — the
        fleet's migration set.  Empty for a slotless request."""
        if req.slot < 0:
            return []
        return list(self._pages[req.slot])

    def adopt(self, req: Request, pages: List[int], *,
              state: str = DECODE) -> Optional[int]:
        """Install a request DIRECTLY into a free slot with its KV pages
        already resident — the fleet KV-handoff path: the pages were
        migrated from another replica's pool (same values, new page
        ids), so the request continues with ZERO replay.  ``pages`` must
        have been allocated from THIS batcher's allocator by the caller
        (accounting stays exact) and must cover every position the
        request's cache holds.  Returns the slot, or None with no free
        slot (caller keeps the request where it is)."""
        if len(pages) > self.scfg.max_pages_per_seq:
            raise ValueError(
                f"adopting {len(pages)} pages > table width "
                f"{self.scfg.max_pages_per_seq}")
        slot = next((i for i, r in enumerate(self.slots) if r is None),
                    None)
        if slot is None:
            return None
        self.table[slot, :] = NULL_PAGE
        self.table[slot, :len(pages)] = np.asarray(pages, np.int32)
        self._pages[slot] = list(pages)
        self.slots[slot] = req
        req.slot = slot
        req.state = state
        self._admit_seq += 1
        req.admit_seq = self._admit_seq
        return slot

    def release(self, req: Request) -> None:
        """Free the slot + pages WITHOUT requeueing — the handoff SOURCE
        side: the page bytes were already copied out by the transfer
        program, and dirty recycling makes the freed pages immediately
        reusable here."""
        self._release_slot(req)

    # -- per-tick work selection ---------------------------------------------

    def prefill_work(self) -> Optional[Tuple[Request, int, int]]:
        """(request, start, n_true) for this tick's prefill chunk — the
        oldest PREFILL request, one static-width chunk (n_true <= chunk
        is the unpadded token count).  None: nothing to prefill, or the
        pool is starved for it this tick."""
        cands = [r for r in self.slots
                 if r is not None and r.state == PREFILL]
        pos = _RULES.pick_oldest([r.admit_seq for r in cands])
        if pos is None:
            return None
        req = cands[pos]
        start = req.prefill_done
        n_true = _RULES.prefill_chunk_len(self.scfg.prefill_chunk,
                                          req.replay_len, start)
        if not self.ensure_pages(req, start + n_true):
            return None
        return req, start, n_true

    def decode_batch(self) -> List[Request]:
        """DECODE requests that can take a step this tick (oldest first;
        each needs one more position's page — may evict newer ones)."""
        out: List[Request] = []
        cands = [r for r in self.slots
                 if r is not None and r.state == DECODE]
        for pos in _RULES.decode_order([r.admit_seq for r in cands]):
            req = cands[pos]
            if req.state != DECODE:
                continue              # evicted by an older sibling above
            if self.ensure_pages(req, _RULES.committed_target(
                    req.state, req.replay_len, req.n_tokens)):
                out.append(req)
        return [r for r in out if r.state == DECODE]

    # -- recovery ------------------------------------------------------------

    def release_all(self) -> List[Request]:
        """Preemption recovery: every live request loses its slot/pages
        and requeues (submit order) for replay; returns the released
        set.  The allocator is expected to be REPLACED by the caller —
        pages freed here are never reused."""
        live = [r for r in self.slots if r is not None]
        for req in sorted(live, key=lambda r: r.uid):
            self._release_slot(req)
            self.enqueue(req)
        self.waiting.sort(key=lambda r: r.uid)
        return live

    def rebind(self, alloc: PageAllocator) -> None:
        """Point at a fresh allocator (post-preemption pool rebuild)."""
        self.alloc = alloc

    # -- introspection -------------------------------------------------------

    @property
    def live(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def pages_in_use(self) -> int:
        return sum(len(p) for p in self._pages)
