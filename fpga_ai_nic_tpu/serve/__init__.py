"""Serving plane: continuous batching + paged KV cache on the llama
decode path (docs/SERVING.md).

  - `paged` — the shared page pool, allocator and exact byte accounting
  - `scheduler` — admit / evict / prefill-decode interleave policy
  - `engine` — the tick loop: two trace-stable jitted programs, request
    telemetry, chaos/watchdog recovery
  - `handoff` — live KV migration between replicas as a J11-accounted
    pair-ppermute transfer program (the reshard discipline applied to
    serving state)
  - `fleet` — the elastic fleet: disaggregated prefill/decode replicas,
    replica-kill recovery by KV handoff instead of replay
  - `traffic` — deterministic replayable workload generator (diurnal
    cycles, bursts, heavy-tailed lengths, tenant mixes on a
    counter-based PRNG)
  - `autoscale` — the closed-loop controller: windowed SLO metrics
    (obs.slo) -> CUSUM/hysteresis -> gated fleet actions (scale out/in,
    role rebalance, admission shedding)

The device-side paged forward itself lives with the model
(`models.llama_decode.forward_paged`), bit-parity-pinned against the
contiguous cache.
"""

from .autoscale import AutoscaleConfig, Autoscaler, ScaleDecision
from .engine import ServeEngine, counted_jit
from .fleet import FleetConfig, Replica, ServeFleet
from .handoff import HandoffPlan, apply_handoff
from .paged import (NULL_PAGE, PageAllocator, ServeConfig,
                    contiguous_cache_bytes, init_pool, page_table_bytes,
                    pool_bytes)
from .scheduler import ContinuousBatcher
from .traffic import (TrafficConfig, TrafficRequest, Workload,
                      diurnal_config, generate, spike_config,
                      steady_config, thundering_herd_config)

__all__ = [
    "ServeEngine", "counted_jit",
    "NULL_PAGE", "PageAllocator", "ServeConfig", "init_pool",
    "pool_bytes", "contiguous_cache_bytes", "page_table_bytes",
    "ContinuousBatcher",
    "FleetConfig", "Replica", "ServeFleet",
    "HandoffPlan", "apply_handoff",
    "AutoscaleConfig", "Autoscaler", "ScaleDecision",
    "TrafficConfig", "TrafficRequest", "Workload", "generate",
    "steady_config", "spike_config", "diurnal_config",
    "thundering_herd_config",
]
