"""Serving engine — continuous batching over the paged decode path.

A genuinely different execution model from the trainers: request-level
async over the step-level substrate.  The host loop runs discrete TICKS;
each tick the scheduler (`scheduler.ContinuousBatcher`) decides which
requests occupy the static decode slots and where their KV pages live,
then AT MOST TWO jitted programs run — one prefill chunk
(``[1, prefill_chunk]`` tokens, oldest prefilling request) interleaved
with one decode step (``[max_reqs, 1]`` tokens, every decoding slot,
empty slots masked).  Both programs' jaxprs are invariant to which
requests occupy which slots: admissions, evictions, page re-assignments
and position churn all change operand VALUES only, never shapes — traced
once at warmup, never again (counted by `counted_jit`, frozen as
graftlint J10, asserted by the serve bench's ``recompiles_steady == 0``).

Failure story (the chaos serving cell): each tick's device work runs
under the `runtime.watchdog` bound when ``step_timeout_s`` is set, with
`runtime.chaos` firing at the ``serve.step`` site.  Recovery is
replay-tier: the pool is rebuilt, every live request loses its pages and
re-queues with its generated tokens kept host-side, and re-admission
replays prompt + generated[:-1] as ordinary prefill chunks — greedy
decode is deterministic, so the post-recovery token stream is identical
to the fault-free one (the request-level SLO `tools/chaos_bench.py`
gates).  MTTR (detection -> engine serviceable) lands in the same
`RecoveryStats` the elastic trainer reports through.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama, llama_decode
from ..models.llama import LlamaConfig
from ..obs.metrics import RequestSpans
from ..ops import integrity as integrity_lib
from ..runtime import chaos as chaos_lib
from ..runtime.requests import DECODE, Request, RequestQueue, ServeStats
from ..runtime.watchdog import DeviceHangError, Watchdog
from ..utils.observability import Profiler
from .paged import (PageAllocator, ServeConfig, contiguous_cache_bytes,
                    init_pool, page_table_bytes, pool_bytes)
from .scheduler import ContinuousBatcher

__all__ = ["ServeEngine", "counted_jit"]

Pool = List[Dict[str, jax.Array]]
PrefillWork = Tuple[Request, int, int]


def counted_jit(fn: Callable[..., Any], **jit_kwargs: Any
                ) -> Tuple[Any, Callable[[], int]]:
    """``jax.jit(fn)`` plus a trace counter: the wrapped Python body runs
    once per TRACE (cache miss), so the counter reads exactly the
    recompiles J10 and the serve bench hold at zero in steady state."""
    count = {"n": 0}

    def wrapped(*args: Any, **kwargs: Any) -> Any:
        count["n"] += 1
        return fn(*args, **kwargs)

    return jax.jit(wrapped, **jit_kwargs), lambda: count["n"]


class ServeEngine:
    """Continuous-batching inference engine over `forward_paged`.

    Greedy (argmax) sampling — determinism is what makes eviction replay
    and preemption recovery token-exact, and what the chaos cell's SLO
    verdict pins.  Single-threaded host loop; `runtime.requests` holds
    the thread-safe seams (intake queue, stats)."""

    def __init__(self, params: Dict[str, Any], cfg: LlamaConfig,
                 scfg: ServeConfig, *,
                 profiler: Optional[Profiler] = None,
                 chaos: Optional[chaos_lib.FaultPlan] = None,
                 dtype: Optional[str] = None,
                 device: Optional[Any] = None,
                 replica_id: int = 0,
                 role: str = "both",
                 tp_mesh: Optional[Any] = None,
                 tp_axis: str = "tp",
                 attend_impl: str = "reference") -> None:
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode: {role!r}")
        if attend_impl not in ("reference", "pallas"):
            raise ValueError("attend_impl must be reference|pallas: "
                             f"{attend_impl!r}")
        # tp_mesh: a jax.sharding.Mesh whose ``tp_axis`` dimension this
        # ONE replica spans — the tick programs are shard_map'd over it
        # (pool + params sharded, host-visible operands replicated), so
        # admissions/evictions/page churn still change VALUES only and
        # the J10 counted-trace discipline is unchanged: exactly one
        # trace per program for any schedule.
        self.tp_mesh = tp_mesh
        self.tp_axis = tp_axis
        self.attend_impl = attend_impl
        self.tp_size = (int(tp_mesh.shape[tp_axis])
                        if tp_mesh is not None else 1)
        # the axis name the tick programs hand to forward_paged: a real
        # mesh axis only inside the shard_map'd body
        self._impl_tp_axis = tp_axis if tp_mesh is not None else None
        if tp_mesh is not None and scfg.page_integrity:
            # the checksum ledger is defined over the GLOBAL pool; a
            # tp-sharded tick sees only its kv shard, and stitching
            # per-rank partial checksums back into the global ledger
            # would need a cross-rank reduction the integrity tier does
            # not model — run the integrity cells on single-shard
            # replicas
            raise ValueError(
                "page_integrity is not supported with a tp-sharded tick "
                "(the page-checksum ledger is global; shards see only "
                "their kv slice)")
        # device pins THIS replica's pool + params (the fleet places each
        # replica on its own device so the KV handoff is a real
        # cross-device ppermute); None keeps the default placement
        self.device = device
        self.replica_id = int(replica_id)
        self.role = role
        self.params = (jax.device_put(params, device)
                       if device is not None else params)
        self.cfg = cfg
        self.scfg = scfg
        self.dtype = dtype
        self.profiler = profiler or Profiler()
        self.stats = ServeStats()
        self.queue = RequestQueue(events=self.profiler.events,
                                  stats=self.stats)
        self.spans = RequestSpans(self.profiler.events)
        self.chaos = chaos
        self.watchdog = (Watchdog(scfg.step_timeout_s)
                         if scfg.step_timeout_s is not None else None)
        self.alloc = PageAllocator(scfg.n_pages)
        self.batcher = ContinuousBatcher(scfg, self.alloc,
                                         stats=self.stats)
        self.pool: Pool = self._fresh_pool()
        # exact per-page KV checksum ledger (scfg.page_integrity): what
        # the last ledger-maintaining program computed over its OUTPUT
        # pool; the next tick verifies its input pool against it.  A
        # zero-filled pool checksums to all-zeros by construction
        # (ops.integrity.page_checksums), so a fresh ledger is zeros.
        self.ledger = self._fresh_ledger()
        self.ticks = 0
        self._wall_s = 0.0
        self._consec_failures = 0
        self._pages_peak = 0         # survives allocator rebuilds
        self.page_trips = 0          # exact-tier (wire/page checksum) trips
        self.logit_trips = 0         # magnitude-tier (logit guard) trips
        if tp_mesh is None:
            self._decode_fn, self._decode_traces = counted_jit(
                self._decode_impl, donate_argnums=(0,))
            self._prefill_fn, self._prefill_traces = counted_jit(
                self._prefill_impl, donate_argnums=(0,))
        else:
            self._decode_fn, self._decode_traces = self._tp_tick_fn(
                self._decode_impl)
            self._prefill_fn, self._prefill_traces = self._tp_tick_fn(
                self._prefill_impl)

    def _tp_tick_fn(self, impl: Callable[..., Any]
                    ) -> Tuple[Any, Callable[[], int]]:
        """shard_map one tick program over the tp mesh and count its
        traces.  Pool shards on the kv-heads axis, params by
        `llama.param_specs`; tokens/table/pos and the emitted
        tokens/guard are replicated (forward_paged all-gathers logits
        over ``tp_axis``, so every rank argmaxes identical rows).  The
        trailing ledger arg of the unsharded call signature is dropped
        here — tp + page_integrity is rejected at construction, so it
        is always None."""
        P = jax.sharding.PartitionSpec
        ax = self.tp_axis
        pool_spec = [{"k": P(None, ax), "v": P(None, ax)}
                     for _ in range(self.cfg.n_layers)]
        pspecs = llama.param_specs(self.cfg, tp_axis=ax,
                                   tp_size=self.tp_size)

        def body(pool: Pool, params: Dict[str, Any], *rest: Any) -> Any:
            return impl(pool, params, *rest)

        sharded = jax.shard_map(
            body, mesh=self.tp_mesh,
            in_specs=(pool_spec, pspecs, P(), P(), P(), P()),
            out_specs=(P(), P(), pool_spec), check_vma=False)
        jitted, traces = counted_jit(sharded, donate_argnums=(0,))

        def call(pool: Pool, params: Dict[str, Any],
                 *rest_and_ledger: Any) -> Any:
            *rest, _ledger = rest_and_ledger
            return jitted(pool, params, *rest)

        return call, traces

    def _fresh_pool(self) -> Pool:
        pool = init_pool(self.cfg, self.scfg, dtype=self.dtype)
        if self.tp_size > 1:
            # the GLOBAL pool the shard_map'd tick shards on its kv
            # axis: kv_local * tp — equal to n_kv_heads except under
            # kv replication (n_kv_heads < tp), where every rank holds
            # its own replicated-head slice
            kv_global = llama_decode.kv_local_heads(
                self.cfg, self.tp_size) * self.tp_size
            if kv_global != pool[0]["k"].shape[1]:
                shape = (self.scfg.n_pages, kv_global,
                         self.scfg.page_size, self.cfg.head_dim)
                dt = pool[0]["k"].dtype
                pool = [{"k": jnp.zeros(shape, dt),
                         "v": jnp.zeros(shape, dt)}
                        for _ in range(self.cfg.n_layers)]
        if self.device is not None:
            pool = jax.device_put(pool, self.device)
        return pool

    def _fresh_ledger(self) -> Optional[jax.Array]:
        if not self.scfg.page_integrity:
            return None
        ledger = jnp.zeros((self.scfg.n_pages,), jnp.uint32)
        if self.device is not None:
            ledger = jax.device_put(ledger, self.device)
        return ledger

    def record_landed_pages(self, pages: Sequence[int],
                            checksums: Any) -> None:
        """Ledger update for pages mutated OUTSIDE the tick programs —
        the fleet's KV handoff lands page blocks directly into the pool,
        and the destination must record their (verified) checksums or
        the next tick's input check would trip on its own migration.
        Called on FAILED migrations too: the landed-but-rejected pages
        stay free-and-dirty, and dirty pages must still be
        ledger-consistent (dirty content is harmless by the mask-parity
        design; a ledger mismatch is corruption by definition)."""
        if self.ledger is None:
            return
        idx = jnp.asarray(np.asarray(pages, np.int32))
        self.ledger = self.ledger.at[idx].set(
            jnp.asarray(np.asarray(checksums, np.uint32)))

    def ledger_entries(self, pages: Sequence[int]) -> np.ndarray:
        """uint32 [len(pages)] — the ledger's write-time checksums for
        ``pages`` (what a migration's landed bytes must still hash to)."""
        assert self.ledger is not None, "page_integrity is off"
        return np.asarray(jax.device_get(self.ledger))[
            np.asarray(pages, np.int64)]

    # -- the two jitted programs (shapes fixed by ServeConfig) ---------------

    def _logit_guard(self, logits: jax.Array) -> jax.Array:
        """In-graph corrupted-tick tripwire: True when this tick's
        logits are non-finite or past the garbage magnitude bound — the
        host then GATES the tick (IntegrityError -> replay-tier
        recovery) instead of emitting poisoned tokens to a stream."""
        bad = ~jnp.isfinite(logits).all()
        if self.scfg.logit_guard_abs is not None:
            bad = bad | (jnp.max(jnp.abs(logits))
                         > jnp.float32(self.scfg.logit_guard_abs))
        return bad

    def _page_check(self, pool: Pool,
                    ledger: Optional[jax.Array]) -> jax.Array:
        """First-tier input verify: # of pool pages whose exact checksum
        differs from the write-time ledger — any nonzero count means a
        page's BYTES changed outside the ledger-maintaining programs (a
        finite wrong-value corruption the logit guard cannot see)."""
        if ledger is None:
            return jnp.int32(0)
        got = integrity_lib.page_checksums(pool)
        return jnp.sum((got != ledger).astype(jnp.int32))

    def _decode_impl(self, pool: Pool, params: Dict[str, Any],
                     tokens: jax.Array, table: jax.Array, pos: jax.Array,
                     active: jax.Array,
                     ledger: Optional[jax.Array] = None
                     ) -> Tuple[jax.Array, ...]:
        bad_pages = self._page_check(pool, ledger)
        logits, pool = llama_decode.forward_paged(
            params, tokens, pool, table, pos, self.cfg,
            page_size=self.scfg.page_size, active=active,
            tp_axis=self._impl_tp_axis, attend_impl=self.attend_impl)
        toks = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        if ledger is None:
            return toks, self._logit_guard(logits), pool
        return (toks, self._logit_guard(logits), bad_pages,
                integrity_lib.page_checksums(pool), pool)

    def _prefill_impl(self, pool: Pool, params: Dict[str, Any],
                      tokens: jax.Array, row: jax.Array, pos0: jax.Array,
                      last: jax.Array,
                      ledger: Optional[jax.Array] = None
                      ) -> Tuple[jax.Array, ...]:
        bad_pages = self._page_check(pool, ledger)
        logits, pool = llama_decode.forward_paged(
            params, tokens, pool, row, pos0, self.cfg,
            page_size=self.scfg.page_size,
            tp_axis=self._impl_tp_axis, attend_impl=self.attend_impl)
        # the sampled continuation at the chunk's last TRUE token — only
        # consumed when this chunk completes a FRESH prefill
        nxt = jnp.argmax(logits[0, last], axis=-1).astype(jnp.int32)
        if ledger is None:
            return nxt, self._logit_guard(logits), pool
        return (nxt, self._logit_guard(logits), bad_pages,
                integrity_lib.page_checksums(pool), pool)

    # -- intake --------------------------------------------------------------

    def submit(self, prompt: np.ndarray, max_new: int, *,
               eos_id: Optional[int] = None,
               not_before_s: float = 0.0) -> Request:
        """Validate against the static budget, then queue (thread-safe)."""
        p = np.asarray(prompt, np.int32).reshape(-1)
        self.batcher.validate_shape(int(p.shape[0]), int(max_new))
        return self.queue.submit(p, max_new, eos_id=eos_id,
                                 not_before_s=not_before_s)

    # -- the loop ------------------------------------------------------------

    def run(self, *, max_ticks: int = 1_000_000) -> Dict[str, Any]:
        """Serve until every submitted request finishes; returns
        `summary()`."""
        t0 = time.perf_counter()
        while (self.queue.pending or self.batcher.waiting
               or self.batcher.live):
            if self.ticks >= max_ticks:
                raise RuntimeError(
                    f"serve loop exceeded max_ticks={max_ticks} with "
                    f"{len(self.batcher.live)} live / "
                    f"{len(self.batcher.waiting)} waiting requests")
            if not self._tick():
                wait = self.queue.next_arrival_in()
                time.sleep(min(0.01, wait if wait is not None else 0.001))
        self._wall_s += time.perf_counter() - t0
        return self.summary()

    def tick(self) -> bool:
        """One public engine tick — the fleet scheduler's drive handle
        (run() loops this for the standalone engine)."""
        return self._tick()

    def _tick(self) -> bool:
        for req in self.queue.pop_arrived():
            self.batcher.enqueue(req)
        now = time.perf_counter()
        if self.role != "decode":
            # decode-role replicas receive work ONLY via the fleet's KV
            # handoff (batcher.adopt) — their waiting list is a replay
            # surface the fleet drains back to prefill workers
            for req in self.batcher.admit():
                self.stats.record_admitted()
                if math.isnan(req.t_admit):
                    req.t_admit = now
        # decode first, then prefill: prefill's page demand may evict the
        # NEWEST decoder, so the batch is re-filtered before dispatch
        dec = self.batcher.decode_batch() if self.role != "prefill" else []
        pre = (self.batcher.prefill_work()
               if self.role != "decode" else None)
        dec = [r for r in dec if r.state == DECODE and r.slot >= 0]
        if pre is None and not dec:
            return False
        with self.profiler.events.span("serve.tick", lane="serve",
                                       replica=self.replica_id,
                                       n_decode=len(dec),
                                       prefill=pre is not None):
            try:
                pool, out = self._device_tick(pre, dec)
            except Exception as err:  # noqa: BLE001 — the recovery boundary
                self._recover(err)
                return True
        self.pool = pool
        if self.ledger is not None and out.get("ledger") is not None:
            self.ledger = out["ledger"]
        self._consec_failures = 0
        self._apply(pre, dec, out)
        # pool pressure as a TIME SERIES, not just the peak scalar the
        # summary keeps: one counter event per tick, so the Perfetto
        # timeline (and any window over the stream) shows pages_in_use
        # rising toward the watermark instead of a single max
        self.profiler.events.counter("serve.pages_in_use",
                                     self.alloc.in_use,
                                     replica=self.replica_id)
        self.ticks += 1
        return True

    def _device_tick(self, pre: Optional[PrefillWork], dec: List[Request]
                     ) -> Tuple[Pool, Dict[str, Any]]:
        """All device work of one tick as a closure the watchdog can
        bound.  NO engine-state read OR mutation inside the closure: the
        pool/table are snapshotted HERE, on the engine thread, before the
        watchdog worker starts — a timed-out zombie that wakes after
        recovery must dispatch against the ABANDONED pool (harmless; its
        donated buffers are never touched again), never against the
        rebuilt one it would otherwise read off ``self.pool`` and
        consume."""
        scfg = self.scfg
        table = self.batcher.table.copy()
        pool_in = self.pool
        # EVERYTHING the closure needs is snapshotted here, on the engine
        # thread — including slot/last, which _recover() rewrites on the
        # live Request (a zombie reading req.slot == -1 post-recovery
        # would slice an empty table row and retrace a fresh shape)
        pre_snap: Optional[Tuple[np.ndarray, int, int, int]] = None
        if pre is not None:
            req, start, n_true = pre
            full = np.concatenate(
                [req.prompt, np.asarray(req.generated, np.int32)])
            pre_tokens = np.zeros((1, scfg.prefill_chunk), np.int32)
            pre_tokens[0, :n_true] = full[start:start + n_true]
            final = start + n_true >= req.replay_len
            last = (req.replay_len - 1 - start) if final else 0
            pre_snap = (pre_tokens, req.slot, start, last)
        dec_snap = [(r.slot, r.generated[-1], r.n_tokens) for r in dec]

        ledger_in = self.ledger

        def work() -> Tuple[Pool, Dict[str, Any]]:
            pool = pool_in
            ledger = ledger_in
            if self.chaos is not None:
                self.chaos.begin_step(self.ticks)
                self.chaos.fire("serve.step")      # may sleep or raise
                # a corruption spec damages the tick's KV payload — the
                # page-checksum tier (finite damage) or the logit guard
                # (NaN/scale) must catch it BEFORE any token reaches a
                # stream (zero copies when nothing is pending)
                pool = self.chaos.corrupt("serve.step", pool)
            out: Dict[str, Any] = {}
            corrupted = False
            bad_pages = 0
            if pre_snap is not None:
                pre_tokens, slot, start, last = pre_snap
                res = self._prefill_fn(
                    pool, self.params, jnp.asarray(pre_tokens),
                    jnp.asarray(table[slot:slot + 1]),
                    jnp.asarray([start], jnp.int32),
                    jnp.asarray(last, jnp.int32), ledger)
                if ledger is None:
                    tok, bad, pool = res
                else:
                    tok, bad, nbad, ledger, pool = res
                    bad_pages += int(nbad)                 # blocks
                out["prefill_tok"] = int(tok)              # blocks
                corrupted |= bool(bad)
            if dec_snap:
                R = scfg.max_reqs
                toks = np.zeros((R, 1), np.int32)
                pos = np.zeros((R,), np.int32)
                act = np.zeros((R,), bool)
                for slot, tok_in, n_tok in dec_snap:
                    toks[slot, 0] = tok_in
                    pos[slot] = n_tok
                    act[slot] = True
                res = self._decode_fn(
                    pool, self.params, jnp.asarray(toks),
                    jnp.asarray(table), jnp.asarray(pos),
                    jnp.asarray(act), ledger)
                if ledger is None:
                    ntok, bad, pool = res
                else:
                    ntok, bad, nbad, ledger, pool = res
                    bad_pages += int(nbad)                 # blocks
                out["decode_toks"] = np.asarray(ntok)      # blocks
                corrupted |= bool(bad)
            if bad_pages:
                # the EXACT tier tripped first: some page's bytes changed
                # outside the ledger-maintaining programs — finite,
                # plausible, invisible to the logit guard; gated out
                # BEFORE _apply, so no poisoned token was emitted
                raise chaos_lib.WireIntegrityError(
                    f"serve tick {self.ticks}: {bad_pages} KV pool "
                    "page(s) failed their exact checksum against the "
                    "write-time ledger — wrong-value corruption gated "
                    "before emission (recovery rebuilds pool + ledger "
                    "and replays)")
            if corrupted:
                # gated out BEFORE _apply: no poisoned token was emitted
                raise chaos_lib.IntegrityError(
                    f"serve tick {self.ticks} produced non-finite/"
                    "garbage logits — corrupted decode tick gated before "
                    "emission (recovery will rebuild the pool and "
                    "replay)")
            out["ledger"] = ledger
            return pool, out

        if self.watchdog is not None:
            result: Tuple[Pool, Dict[str, Any]] = self.watchdog.run(work)
            return result
        return work()

    def _apply(self, pre: Optional[PrefillWork], dec: List[Request],
               out: Dict[str, Any]) -> None:
        now = time.perf_counter()
        if pre is not None:
            req, start, n_true = pre
            req.prefill_done = start + n_true
            if req.prefill_done >= req.replay_len:
                req.state = DECODE
                if not req.generated:
                    # fresh prefill: the chunk's sample IS the first new
                    # token; a replay re-derives generated[-1] instead
                    # (greedy determinism) and the host copy wins
                    self._append_token(req, int(out["prefill_tok"]), now)
        if dec:
            toks = out["decode_toks"]
            for r in dec:
                self._append_token(r, int(toks[r.slot]), now)

    def _append_token(self, req: Request, tok: int, now: float) -> None:
        req.generated.append(tok)
        if math.isnan(req.t_first):
            req.t_first = now
            self.profiler.events.instant("serve.first_token", uid=req.uid)
        if (len(req.generated) >= req.max_new
                or (req.eos_id is not None and tok == req.eos_id)):
            req.t_done = now
            self.batcher.finish(req)
            self.stats.record_completed(len(req.generated))
            self.spans.record(req.uid, t_submit=req.t_submit,
                              t_admit=req.t_admit, t_first=req.t_first,
                              t_done=req.t_done,
                              n_tokens=len(req.generated))

    # -- recovery ------------------------------------------------------------

    def _recover(self, err: Exception) -> None:
        """Replay-tier recovery: fresh pool + allocator, every live
        request requeued with generated tokens kept.  MTTR = detection ->
        engine serviceable (the replayed prefills are ordinary serving
        work and land in request latency, not MTTR)."""
        self._consec_failures += 1
        if self._consec_failures > self.scfg.max_retries:
            raise err
        if isinstance(err, chaos_lib.InjectedPreemption):
            kind = "preemption"
        elif isinstance(err, DeviceHangError):
            kind = "hang"
        elif isinstance(err, chaos_lib.WireIntegrityError):
            # the EXACT tier (page checksums) — counted apart from the
            # logit guard so a chaos cell can prove WHICH tier caught a
            # finite corruption
            kind = "wire-corruption"
            self.page_trips += 1
        elif isinstance(err, chaos_lib.IntegrityError):
            kind = "corruption"
            self.logit_trips += 1
        else:
            kind = getattr(err, "kind", type(err).__name__)
        ev = self.profiler.recovery.record_fault(
            kind, step=self.ticks, site="serve.step", error=repr(err))
        t0 = time.perf_counter()
        self._pages_peak = max(self._pages_peak, self.alloc.peak_in_use)
        self.batcher.release_all()
        self.alloc = PageAllocator(self.scfg.n_pages)
        self.batcher.rebind(self.alloc)
        self.pool = self._fresh_pool()
        # fresh zero pool -> all-zero checksums, so the ledger resets
        # with it (the zero-pool invariant of ops.integrity)
        self.ledger = self._fresh_ledger()
        jax.block_until_ready(self.pool)
        self.profiler.recovery.record_recovery(
            time.perf_counter() - t0, event=ev)
        self.stats.record_recovery()
        self.profiler.events.instant("serve.recovered", tick=self.ticks,
                                     kind=kind)
        time.sleep(self.scfg.backoff_s * (2 ** (self._consec_failures - 1)))

    # -- introspection -------------------------------------------------------

    def trace_counts(self) -> Dict[str, int]:
        """Traces per jitted program — each must be exactly 1 after
        warmup, for ANY admit/evict schedule (graftlint J10)."""
        return {"prefill": self._prefill_traces(),
                "decode": self._decode_traces()}

    def recompiles_steady(self) -> int:
        return sum(max(0, n - 1) for n in self.trace_counts().values())

    def obs_static_metrics(self) -> Dict[str, Any]:
        """Trace-time-constant serving facts for the obs gate — byte
        accounting is EXACT (two-sided in tools/obs_gate.py), the same
        honesty rule as the collective wire bytes."""
        scfg = self.scfg
        return {"serve": {
            "max_reqs": scfg.max_reqs,
            "page_size": scfg.page_size,
            "n_pages": scfg.n_pages,
            "max_pages_per_seq": scfg.max_pages_per_seq,
            "prefill_chunk": scfg.prefill_chunk,
            "page_table_bytes": page_table_bytes(scfg),
            "pool_bytes": pool_bytes(self.cfg, scfg, dtype=self.dtype),
            "contiguous_cache_bytes": contiguous_cache_bytes(
                self.cfg, scfg.max_reqs, scfg.max_seq, dtype=self.dtype),
        }}

    def summary(self) -> Dict[str, Any]:
        rec = self.profiler.recovery.as_dict()
        stats = self.stats.as_dict()
        wall = self._wall_s
        usable = self.scfg.usable_pages
        return {
            "replica_id": self.replica_id,
            "role": self.role,
            "tp_size": self.tp_size,
            "attend_impl": self.attend_impl,
            "ticks": self.ticks,
            "wall_s": round(wall, 4),
            **stats,
            "evictions": self.batcher.evictions,
            "pages_in_use_peak": max(self._pages_peak,
                                     self.alloc.peak_in_use),
            "page_util_peak": round(
                max(self._pages_peak, self.alloc.peak_in_use) / usable, 4),
            "throughput_tok_s": (round(stats["tokens_out"] / wall, 2)
                                 if wall > 0 else None),
            "trace_counts": self.trace_counts(),
            "recompiles_steady": self.recompiles_steady(),
            "page_integrity": bool(self.scfg.page_integrity),
            "page_trips": self.page_trips,
            "logit_trips": self.logit_trips,
            "requests": self.spans.summary(),
            "recovery": {"faults": rec["faults"],
                         "recoveries": rec["recoveries"],
                         "mttr_mean_s": rec["mttr_mean_s"]},
            **self.obs_static_metrics(),
        }
