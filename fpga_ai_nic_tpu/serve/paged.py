"""Paged KV-cache pool + page allocator — HBM sharing for the serving plane.

`models.llama_decode.init_cache` allocates (and zero-fills) the FULL
``[B, kv_local, max_seq, hd]`` extent per layer, per K and V, up front:
a batch of short sequences pays for ``max_seq`` anyway, and no byte is
ever shared between sequences.  The serving plane replaces it with the
vLLM-style paged layout:

  - ONE preallocated pool per layer: ``[n_pages, kv_local, page_size,
    hd]`` (page 0 reserved as the null page — the write target of empty
    slots and the gather target of unallocated table entries; its
    contents are never visible through the attention mask).
  - a static-shape ``[max_reqs, max_pages_per_seq]`` int32 page table:
    sequences own arbitrary page sets, fragmentation-free, and a page
    re-assignment changes table VALUES only — the jitted decode step
    never retraces (graftlint J10).
  - recycled pages are dirty BY DESIGN: `forward_paged`'s mask makes
    paged decode bitwise-identical to the contiguous cache regardless of
    what a page held before (pinned by tests/test_serve.py), so freeing
    is O(1) list surgery with no zero-fill pass.

Byte accounting here is exact (`pool_bytes` == the sum of the actual
device array sizes, tested) because the obs gate holds the serving
artifacts to it two-sided — the same honesty rule as the wire-byte
accounting on the collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..models import llama_decode
from ..models.llama import LlamaConfig

__all__ = ["NULL_PAGE", "ServeConfig", "PageAllocator", "init_pool",
           "pool_bytes", "contiguous_cache_bytes", "page_table_bytes"]

NULL_PAGE = 0


@dataclass(frozen=True)
class ServeConfig:
    """Static shape/budget knobs of the serving plane.  Everything here
    is baked into the jitted step's shapes — requests, pages and slots
    move WITHIN these bounds without retracing."""

    max_reqs: int = 8                # decode slots (R)
    page_size: int = 16              # positions per KV page
    n_pages: int = 64                # pool pages INCLUDING null page 0
    max_pages_per_seq: int = 8       # page-table width (P)
    prefill_chunk: int = 16          # tokens per prefill call (static T)
    # fault handling (chaos serving cell): watchdog bound over each
    # tick's device work; None disables detection
    step_timeout_s: Optional[float] = None
    max_retries: int = 4
    backoff_s: float = 0.01
    # corrupted-tick guard, SECOND tier: a decode/prefill tick whose
    # logits are non-finite OR exceed this magnitude is GATED
    # (IntegrityError -> replay-tier recovery) before any token reaches
    # a stream.  Healthy logits are O(10); a NaN'd or scale-corrupted KV
    # pool lands far past this.  This tier is a magnitude guard ONLY —
    # it is provably blind to finite wrong-value damage (a flipped
    # mantissa bit in a KV page yields wrong-but-normal-magnitude
    # logits).  That class is owned by the FIRST tier, the exact
    # per-page checksum ledger below (``page_integrity``); the logit
    # guard remains as the backstop for damage classes that bypass the
    # pool (activation corruption, a poisoned weight replica).  None
    # disables the magnitude half (non-finite always trips).
    logit_guard_abs: Optional[float] = 1e6
    # corrupted-tick guard, FIRST tier: exact per-page checksums over
    # the KV pool (ops.integrity.page_checksums).  Every tick's program
    # verifies its INPUT pool bit-for-bit against the ledger the
    # previous program's output recorded, and emits the new ledger —
    # so any byte of any page changed OUTSIDE the ledger-maintaining
    # programs (host corruption, a wrong-KEY write, a failed migration)
    # trips BEFORE the tick emits a token, closing the finite
    # wrong-value class the logit guard cannot see (the honest boundary
    # docs/SERVING.md carried until PR 12).  The ledger is values-only:
    # shapes/trace counts are unchanged (J10 holds either way).
    page_integrity: bool = True

    def __post_init__(self) -> None:
        if self.max_reqs < 1 or self.page_size < 1:
            raise ValueError("max_reqs and page_size must be >= 1")
        if self.logit_guard_abs is not None and self.logit_guard_abs <= 0:
            raise ValueError("logit_guard_abs must be positive (or None)")
        if self.n_pages < 2:
            raise ValueError("n_pages must be >= 2 (page 0 is reserved)")
        if self.max_pages_per_seq < 1:
            raise ValueError("max_pages_per_seq must be >= 1")
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")

    @property
    def max_seq(self) -> int:
        """Longest sequence a single page-table row can address."""
        return self.max_pages_per_seq * self.page_size

    @property
    def usable_pages(self) -> int:
        return self.n_pages - 1          # page 0 is the null page

    def pages_for(self, n_positions: int) -> int:
        """Pages needed to hold ``n_positions`` KV entries."""
        return max(0, -(-int(n_positions) // self.page_size))


class PageAllocator:
    """Free-list allocator over pool pages ``1..n_pages-1``.

    Single-threaded by contract — only the engine loop allocates (the
    cross-thread surfaces are RequestQueue/ServeStats).  Freed pages are
    recycled LIFO and handed out dirty; `forward_paged`'s mask-parity
    makes that safe (module docstring)."""

    def __init__(self, n_pages: int) -> None:
        if n_pages < 2:
            raise ValueError("n_pages must be >= 2 (page 0 is reserved)")
        self.n_pages = int(n_pages)
        self._free: List[int] = list(range(self.n_pages - 1, 0, -1))
        self.in_use = 0
        self.peak_in_use = 0

    @property
    def free(self) -> int:
        return len(self._free)

    def alloc(self, n: int = 1) -> Optional[List[int]]:
        """n pages, or None (caller evicts and retries) — never partial."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self.in_use += n
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return pages

    def free_pages(self, pages: List[int]) -> None:
        for p in pages:
            if not 1 <= p < self.n_pages:
                raise ValueError(f"page {p} outside pool (1..{self.n_pages - 1})")
        self._free.extend(pages)
        self.in_use -= len(pages)
        if self.in_use < 0 or len(self._free) > self.n_pages - 1:
            raise RuntimeError("page double-free detected")


def init_pool(cfg: LlamaConfig, scfg: ServeConfig, *, tp_size: int = 1,
              dtype: Optional[str] = None) -> List[Dict[str, jax.Array]]:
    """Per-layer paged K/V pools ``[n_pages, kv_local, page_size, hd]``,
    zero-filled once at engine construction — the ONLY full-pool
    zero-fill the serving plane ever performs."""
    kv_local = llama_decode.kv_local_heads(cfg, tp_size)
    dt = jnp.dtype(dtype or cfg.dtype)
    shape = (scfg.n_pages, kv_local, scfg.page_size, cfg.head_dim)
    return [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            for _ in range(cfg.n_layers)]


def pool_bytes(cfg: LlamaConfig, scfg: ServeConfig, *, tp_size: int = 1,
               dtype: Optional[str] = None) -> int:
    """Exact bytes of the paged pool (all layers, K and V)."""
    kv_local = llama_decode.kv_local_heads(cfg, tp_size)
    dt = jnp.dtype(dtype or cfg.dtype)
    per_layer = 2 * scfg.n_pages * kv_local * scfg.page_size \
        * cfg.head_dim * dt.itemsize
    return cfg.n_layers * per_layer


def contiguous_cache_bytes(cfg: LlamaConfig, batch: int, max_seq: int, *,
                           tp_size: int = 1,
                           dtype: Optional[str] = None) -> int:
    """Exact bytes `init_cache` would allocate for the same concurrency —
    the HBM cost the paged pool is measured against (docs/PERF.md)."""
    kv_local = llama_decode.kv_local_heads(cfg, tp_size)
    dt = jnp.dtype(dtype or cfg.dtype)
    return cfg.n_layers * 2 * batch * kv_local * max_seq \
        * cfg.head_dim * dt.itemsize


def page_table_bytes(scfg: ServeConfig) -> int:
    """Exact bytes of the static int32 page table."""
    return scfg.max_reqs * scfg.max_pages_per_seq * 4
