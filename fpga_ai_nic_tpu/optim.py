"""Fused optimizers operating on flat parameter shards.

The reference fuses exactly one optimizer into the collective: SGD with a
hard-wired lr = 0.1 (FFMA constant a = 0xBDCCCCCD = -0.1,
hw/weight_update.sv:439-452; the lrate CSR plumbing is commented out,
hw/all_reduce.sv:616,638-642).  We keep the same fusion point — the update
runs on the *owned shard* between reduce-scatter and all-gather — but make
the optimizer pluggable (sgd / momentum / adamw) and the hyperparameters
configuration, and keep master weights + state in f32 regardless of the
compute dtype (ZeRO-1 style, per BASELINE.json config 5).

State layout: a dict of flat f32 arrays with the same length as the owned
shard, so the whole thing shards trivially over the dp axis.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .utils.config import OptimizerConfig, OptimizerSpec

OptState = Dict[str, jax.Array]

# ---------------------------------------------------------------------------
# fused-update hyperparameter vector
# ---------------------------------------------------------------------------
# Layout of the f32[HYPER_LEN] scalar vector the fused paths consume: the
# Pallas ring kernels read it from SMEM (ops.ring_pallas fused-opt
# variants), the jnp fused path reads it as a traced array, and the numpy
# golden twins take the identical values — one definition so a
# hyperparameter (lr schedule step, weight decay, bias correction) can
# NEVER recompile a kernel or drift between implementations.  Bias
# corrections ride as RECIPROCALS (rc1 = 1/(1-b1^t)): the fused adam
# update multiplies instead of dividing so the kernel has exactly ONE
# elementwise division (num/den) — XLA's (a/b)/c -> a/(b*c) rewrite would
# otherwise re-associate a second division and break golden bit-parity.
H_LR, H_WD, H_MOM, H_B2, H_EPS, H_RC1, H_RC2 = 0, 1, 2, 3, 4, 5, 6
HYPER_LEN = 8


def fused_hyperparams(cfg: OptimizerConfig, step=None) -> jax.Array:
    """The f32[HYPER_LEN] scalar vector for one fused update at ``step``
    (traced; scheduled lr and adam bias corrections are plain traced
    expressions, so changing them never recompiles the kernel)."""
    if step is None:
        assert cfg.schedule == "constant" and cfg.warmup_steps == 0, (
            "lr schedules need the step count")
        lr = jnp.float32(cfg.learning_rate)
    else:
        lr = learning_rate_at(cfg, step)
    if cfg.kind == "adamw":
        assert step is not None, "adamw needs the (replicated) step count"
        t = (jnp.asarray(step) + 1).astype(jnp.float32)
        b1, b2 = jnp.float32(cfg.b1), jnp.float32(cfg.b2)
        rc1 = jnp.float32(1.0) / (jnp.float32(1.0) - b1 ** t)
        rc2 = jnp.float32(1.0) / (jnp.float32(1.0) - b2 ** t)
    else:
        rc1 = rc2 = jnp.float32(1.0)
    mom = jnp.float32(cfg.momentum if cfg.kind == "momentum" else cfg.b1)
    return jnp.stack([lr.astype(jnp.float32), jnp.float32(cfg.weight_decay),
                      mom, jnp.float32(cfg.b2), jnp.float32(cfg.eps),
                      rc1, rc2, jnp.float32(0.0)])


def fused_apply_blocks(kind: str, w, g, state: Tuple, h: Callable):
    """THE fused-update formula — shared verbatim by the Pallas ring
    kernels (operating on VMEM sub-slice blocks, scalars read from the
    SMEM hyper vector) and the jnp fused path (flat arrays, traced hyper
    vector).  ``h(i)`` reads hyper scalar i (optim.H_*); ``state`` is the
    positional tuple per OptimizerSpec.state_keys.  Returns
    ``(w_new, new_state)``.

    BIT CONTRACT (tests/test_fused_optimizer.py): every expression here
    is shaped so each add/sub has at most ONE inexact multiply operand —
    an unambiguous FMA contraction site.  XLA:CPU (and LLVM generally)
    contracts exactly those into fused multiply-adds, which
    ``golden_fused_apply`` mirrors with explicit emulated fmaf, so kernel
    and twin agree bit for bit on this container.  Adam uses the
    EMA-increment form m + (1-b1)*(g-m) (not b1*m + (1-b1)*g, whose
    two-product add contracts ambiguously) and reciprocal bias
    corrections (see the hyper-layout comment).  On a backend that does
    not contract at all the bits would differ from the twin by final-ulp
    rounding only — the parity tests pin THIS container's backend."""
    one = jnp.float32(1.0)
    lr, wd = h(H_LR), h(H_WD)
    if kind == "sgd":
        return w - lr * (g + wd * w), ()
    if kind == "momentum":
        # DECOUPLED weight decay (SGDW): wd rides its own final term
        # instead of folding into the accumulator.  Not (only) a
        # semantics choice — `mom*m + (g + wd*w)` chains two contraction
        # candidates through an add's ADDEND slot, and XLA's fusion
        # boundaries split that chain differently per context (measured:
        # the Pallas-kernel route contracted only the outer site while
        # the flat route contracted both), so no single twin could match
        # both routes.  Each site below has raw operands beside its one
        # mul; single-step math is identical to the coupled form.
        (m,) = state
        m2 = h(H_MOM) * m + g
        t1 = w - lr * m2
        return t1 - (lr * wd) * w, (m2,)
    if kind == "adamw":
        m, v = state
        m2 = m + (one - h(H_MOM)) * (g - m)
        v2 = v + (one - h(H_B2)) * (g * g - v)
        num = h(H_RC1) * m2
        den = jnp.sqrt(h(H_RC2) * v2) + h(H_EPS)
        upd = num / den + wd * w
        return w - lr * upd, (m2, v2)
    raise ValueError(kind)


def fused_apply_flat(spec: OptimizerSpec, w: jax.Array, g_sum: jax.Array,
                     state: OptState, hyper: jax.Array,
                     n: int) -> Tuple[jax.Array, OptState]:
    """The fused update on a flat owned shard OUTSIDE the Pallas kernel —
    the routing target for fused_optimizer mode off the fused-kernel path
    (XLA psum_scatter / separate-op ring / n == 1), numerically identical
    to the in-kernel update: same formula, same hyper vector, same
    golden twin.  ``g_sum`` is the reduce-scattered gradient SUM; the /n
    mean happens here, matching the kernel."""
    w = w.astype(jnp.float32)
    g = g_sum.astype(jnp.float32) / jnp.float32(n)
    st = tuple(state[k] for k in spec.state_keys)
    w2, st2 = fused_apply_blocks(spec.kind, w, g, st,
                                 lambda i: hyper[i])
    return w2, dict(zip(spec.state_keys, st2))


# ---------------------------------------------------------------------------
# numpy golden twins (the bit spec of the fused update)
# ---------------------------------------------------------------------------

def _np_fmaf(a, b, c):
    """Exact float32 fused multiply-add via float64: the f32xf32 product
    is exact in f64 (<= 48 significand bits) and 53 >= 2*24 + 2 makes the
    double rounding innocuous, so this equals fmaf(a, b, c) bit for bit
    on every input."""
    import numpy as np
    return (np.asarray(a, np.float64) * np.asarray(b, np.float64)
            + np.asarray(c, np.float64)).astype(np.float32)


def golden_fused_apply(kind: str, w, g_sum, state: Dict, hyper,
                       n: int) -> Tuple:
    """Numpy golden twin of ``fused_apply_blocks`` composed with the /n
    gradient mean — the bit-level SPEC of the fused optimizer, mirroring
    the FMA contraction XLA:CPU applies to the jnp formula (each fmaf
    below is one contraction site; the rest round separately).  Composed
    with compress.golden's codec-generic ring golden it specifies the
    whole fused decode+update path per codec (tests/test_fused_optimizer).

    Returns ``(w_new, new_state_dict)`` in float32.  ``hyper`` is the
    (materialized) fused_hyperparams vector — pass the SAME values the
    kernel saw; recomputing lr/bias corrections host-side would compare
    two pow implementations, not the update."""
    import numpy as np
    spec = OptimizerSpec(kind=kind)
    w = np.asarray(w, np.float32)
    g = np.asarray(g_sum, np.float32) / np.float32(n)
    h = np.asarray(hyper, np.float32)
    lr, wd = h[H_LR], h[H_WD]
    one = np.float32(1.0)
    if kind == "sgd":
        w2 = _np_fmaf(-lr, _np_fmaf(wd, w, g), w)
        return w2, {}
    if kind == "momentum":
        m = np.asarray(state["m"], np.float32)
        m2 = _np_fmaf(h[H_MOM], m, g)
        t1 = _np_fmaf(-lr, m2, w)
        return _np_fmaf(-(lr * wd), w, t1), {"m": m2}
    if kind == "adamw":
        m = np.asarray(state["m"], np.float32)
        v = np.asarray(state["v"], np.float32)
        m2 = _np_fmaf(one - h[H_MOM], g - m, m)
        v2 = _np_fmaf(one - h[H_B2], _np_fmaf(g, g, -v), v)
        num = h[H_RC1] * m2
        den = (np.sqrt(h[H_RC2] * v2) + h[H_EPS]).astype(np.float32)
        upd = _np_fmaf(wd, w, num / den)
        return _np_fmaf(-lr, upd, w), {"m": m2, "v": v2}
    raise ValueError(spec.kind)


def init_state(cfg: OptimizerConfig, shard_len: int) -> OptState:
    z = lambda: jnp.zeros((shard_len,), jnp.float32)
    if cfg.kind == "sgd":
        return {}
    if cfg.kind == "momentum":
        return {"m": z()}
    if cfg.kind == "adamw":
        # the step count lives in TrainState.step (replicated), not here,
        # so every state leaf is a flat shard and shards uniformly
        return {"m": z(), "v": z()}
    raise ValueError(cfg.kind)


def learning_rate_at(cfg: OptimizerConfig, step) -> jax.Array:
    """Scheduled lr at a (traced) step count: linear warmup then constant /
    cosine / linear decay to min_lr_ratio * lr.  The reference's lr is a
    synthesis-time FFMA constant (hw/weight_update.sv:439-446) — schedules
    are impossible there; here they are one traced expression."""
    base = jnp.float32(cfg.learning_rate)
    if cfg.schedule == "constant" and cfg.warmup_steps == 0:
        return base
    t = jnp.asarray(step, jnp.float32)
    warm = (jnp.minimum(1.0, (t + 1.0) / cfg.warmup_steps)
            if cfg.warmup_steps > 0 else jnp.float32(1.0))
    if cfg.schedule == "constant":
        return base * warm
    horizon = max(cfg.decay_steps - cfg.warmup_steps, 1)
    frac = jnp.clip((t - cfg.warmup_steps) / horizon, 0.0, 1.0)
    decay = (0.5 * (1.0 + jnp.cos(jnp.pi * frac))
             if cfg.schedule == "cosine" else 1.0 - frac)
    floor = jnp.float32(cfg.min_lr_ratio)
    return base * warm * (floor + (1.0 - floor) * decay)


def clip_by_global_norm(cfg: OptimizerConfig, g: jax.Array,
                        axes=(), weights=None) -> jax.Array:
    """Scale a (possibly sharded) flat gradient so its GLOBAL L2 norm is at
    most cfg.clip_norm.  ``axes``: the mesh axes the flat vector is sharded
    over (psum of the local sum-of-squares — called inside shard_map); ()
    when g is the full vector.  ``weights``: optional per-element norm
    weights for layouts where some segments are REPLICATED across ``axes``
    (tp/pp-replicated leaves in the sharded master layout) — weight
    1/replication makes the psum count each parameter exactly once.
    No-op when clip_norm is None.

    Runs on the owned shard between reduce-scatter and the optimizer — the
    same fusion point as the update itself (the reference's FFMA array has
    no such guard; hw/weight_update.sv applies raw gradients)."""
    if cfg.clip_norm is None:
        return g
    sq_el = jnp.square(g.astype(jnp.float32))
    if weights is not None:
        sq_el = sq_el * weights
    sq = jnp.sum(sq_el)
    if axes:
        sq = lax.psum(sq, tuple(axes))
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, jnp.float32(cfg.clip_norm)
                        / jnp.maximum(norm, 1e-12))
    return (g.astype(jnp.float32) * scale).astype(g.dtype)


def apply(cfg: OptimizerConfig, w: jax.Array, g: jax.Array,
          state: OptState, step=None) -> Tuple[jax.Array, OptState]:
    """w_new = step(w, g); w, g are flat f32 shards (ref semantics:
    w_new = -lr*g + w, hw/weight_update.sv:441-452)."""
    w = w.astype(jnp.float32)
    g = g.astype(jnp.float32)
    if step is None:
        assert cfg.schedule == "constant" and cfg.warmup_steps == 0, (
            "lr schedules need the step count")
        lr = jnp.float32(cfg.learning_rate)
    else:
        lr = learning_rate_at(cfg, step)
    if cfg.kind == "sgd":
        if cfg.weight_decay:
            g = g + jnp.float32(cfg.weight_decay) * w
        return w - lr * g, state
    if cfg.kind == "momentum":
        if cfg.weight_decay:
            g = g + jnp.float32(cfg.weight_decay) * w
        m = jnp.float32(cfg.momentum) * state["m"] + g
        return w - lr * m, {"m": m}
    if cfg.kind == "adamw":
        assert step is not None, "adamw needs the (replicated) step count"
        t = (step + 1).astype(jnp.float32)
        b1, b2 = jnp.float32(cfg.b1), jnp.float32(cfg.b2)
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + jnp.float32(cfg.eps))
        if cfg.weight_decay:
            upd = upd + jnp.float32(cfg.weight_decay) * w
        return w - lr * upd, {"m": m, "v": v}
    raise ValueError(cfg.kind)
