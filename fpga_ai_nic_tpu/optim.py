"""Fused optimizers operating on flat parameter shards.

The reference fuses exactly one optimizer into the collective: SGD with a
hard-wired lr = 0.1 (FFMA constant a = 0xBDCCCCCD = -0.1,
hw/weight_update.sv:439-452; the lrate CSR plumbing is commented out,
hw/all_reduce.sv:616,638-642).  We keep the same fusion point — the update
runs on the *owned shard* between reduce-scatter and all-gather — but make
the optimizer pluggable (sgd / momentum / adamw) and the hyperparameters
configuration, and keep master weights + state in f32 regardless of the
compute dtype (ZeRO-1 style, per BASELINE.json config 5).

State layout: a dict of flat f32 arrays with the same length as the owned
shard, so the whole thing shards trivially over the dp axis.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .utils.config import OptimizerConfig

OptState = Dict[str, jax.Array]


def init_state(cfg: OptimizerConfig, shard_len: int) -> OptState:
    z = lambda: jnp.zeros((shard_len,), jnp.float32)
    if cfg.kind == "sgd":
        return {}
    if cfg.kind == "momentum":
        return {"m": z()}
    if cfg.kind == "adamw":
        # the step count lives in TrainState.step (replicated), not here,
        # so every state leaf is a flat shard and shards uniformly
        return {"m": z(), "v": z()}
    raise ValueError(cfg.kind)


def learning_rate_at(cfg: OptimizerConfig, step) -> jax.Array:
    """Scheduled lr at a (traced) step count: linear warmup then constant /
    cosine / linear decay to min_lr_ratio * lr.  The reference's lr is a
    synthesis-time FFMA constant (hw/weight_update.sv:439-446) — schedules
    are impossible there; here they are one traced expression."""
    base = jnp.float32(cfg.learning_rate)
    if cfg.schedule == "constant" and cfg.warmup_steps == 0:
        return base
    t = jnp.asarray(step, jnp.float32)
    warm = (jnp.minimum(1.0, (t + 1.0) / cfg.warmup_steps)
            if cfg.warmup_steps > 0 else jnp.float32(1.0))
    if cfg.schedule == "constant":
        return base * warm
    horizon = max(cfg.decay_steps - cfg.warmup_steps, 1)
    frac = jnp.clip((t - cfg.warmup_steps) / horizon, 0.0, 1.0)
    decay = (0.5 * (1.0 + jnp.cos(jnp.pi * frac))
             if cfg.schedule == "cosine" else 1.0 - frac)
    floor = jnp.float32(cfg.min_lr_ratio)
    return base * warm * (floor + (1.0 - floor) * decay)


def clip_by_global_norm(cfg: OptimizerConfig, g: jax.Array,
                        axes=(), weights=None) -> jax.Array:
    """Scale a (possibly sharded) flat gradient so its GLOBAL L2 norm is at
    most cfg.clip_norm.  ``axes``: the mesh axes the flat vector is sharded
    over (psum of the local sum-of-squares — called inside shard_map); ()
    when g is the full vector.  ``weights``: optional per-element norm
    weights for layouts where some segments are REPLICATED across ``axes``
    (tp/pp-replicated leaves in the sharded master layout) — weight
    1/replication makes the psum count each parameter exactly once.
    No-op when clip_norm is None.

    Runs on the owned shard between reduce-scatter and the optimizer — the
    same fusion point as the update itself (the reference's FFMA array has
    no such guard; hw/weight_update.sv applies raw gradients)."""
    if cfg.clip_norm is None:
        return g
    sq_el = jnp.square(g.astype(jnp.float32))
    if weights is not None:
        sq_el = sq_el * weights
    sq = jnp.sum(sq_el)
    if axes:
        sq = lax.psum(sq, tuple(axes))
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, jnp.float32(cfg.clip_norm)
                        / jnp.maximum(norm, 1e-12))
    return (g.astype(jnp.float32) * scale).astype(g.dtype)


def apply(cfg: OptimizerConfig, w: jax.Array, g: jax.Array,
          state: OptState, step=None) -> Tuple[jax.Array, OptState]:
    """w_new = step(w, g); w, g are flat f32 shards (ref semantics:
    w_new = -lr*g + w, hw/weight_update.sv:441-452)."""
    w = w.astype(jnp.float32)
    g = g.astype(jnp.float32)
    if step is None:
        assert cfg.schedule == "constant" and cfg.warmup_steps == 0, (
            "lr schedules need the step count")
        lr = jnp.float32(cfg.learning_rate)
    else:
        lr = learning_rate_at(cfg, step)
    if cfg.kind == "sgd":
        if cfg.weight_decay:
            g = g + jnp.float32(cfg.weight_decay) * w
        return w - lr * g, state
    if cfg.kind == "momentum":
        if cfg.weight_decay:
            g = g + jnp.float32(cfg.weight_decay) * w
        m = jnp.float32(cfg.momentum) * state["m"] + g
        return w - lr * m, {"m": m}
    if cfg.kind == "adamw":
        assert step is not None, "adamw needs the (replicated) step count"
        t = (step + 1).astype(jnp.float32)
        b1, b2 = jnp.float32(cfg.b1), jnp.float32(cfg.b2)
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + jnp.float32(cfg.eps))
        if cfg.weight_decay:
            upd = upd + jnp.float32(cfg.weight_decay) * w
        return w - lr * upd, {"m": m, "v": v}
    raise ValueError(cfg.kind)
