"""ctypes loader for the native host codec (csrc/bfp_codec.cpp).

The reference's host runtime is C++ (sw/mlp_mpi_example_f32.cpp + OPAE
wrapper); our host-native piece is the BFP codec used for checkpoint
compression and as an independent parity implementation.  Loading degrades
gracefully: ``lib()`` returns None when the .so is absent and cannot be
built, and callers fall back to the numpy golden model.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "csrc")
_lib = None
_tried = False


def load_native(so_name: str) -> Optional[ctypes.CDLL]:
    """Shared build-on-first-use loader for every csrc library: runs
    `make -C csrc` when the .so is absent, returns None on any failure so
    callers degrade to their Python fallbacks."""
    so_path = os.path.join(_DIR, so_name)
    if not os.path.exists(so_path):
        try:
            # build the specific .so (rules are named after the files), so
            # non-default artifacts like libstaging_tsan.so build too
            # instead of silently falling back to the Python path
            subprocess.run(["make", "-C", _DIR, so_name], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        return ctypes.CDLL(so_path)
    except OSError:
        return None


def lib() -> Optional[ctypes.CDLL]:
    """Load (building on first use if needed) the native codec library."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    l = load_native("libbfp_codec.so")
    if l is None:
        return None
    l.bfp_encode_f32.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.POINTER(ctypes.c_int8),
        ctypes.POINTER(ctypes.c_int8)]
    l.bfp_decode_f32.argtypes = [
        ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(ctypes.c_int8),
        ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_float)]
    _lib = l
    return _lib


def available() -> bool:
    return lib() is not None


def bfp_encode(x: np.ndarray, block_size: int = 16, mantissa_bits: int = 8,
               rounding: str = "nearest") -> Tuple[np.ndarray, np.ndarray]:
    l = lib()
    assert l is not None, "native codec unavailable (csrc build failed)"
    x = np.ascontiguousarray(x, np.float32)
    if x.shape[-1] % block_size != 0:
        # same blocking rule as the golden model: blocks never span rows
        raise ValueError(
            f"last dim {x.shape[-1]} not a multiple of block {block_size}")
    n = x.size
    mant = np.empty(n, np.int8)
    scale = np.empty(n // block_size, np.int8)
    l.bfp_encode_f32(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n, block_size,
        mantissa_bits, 0 if rounding == "nearest" else 1,
        mant.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        scale.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)))
    return mant.reshape(x.shape), scale


def bfp_decode(mant: np.ndarray, scale: np.ndarray,
               block_size: int = 16) -> np.ndarray:
    l = lib()
    assert l is not None, "native codec unavailable (csrc build failed)"
    mant = np.ascontiguousarray(mant, np.int8)
    scale = np.ascontiguousarray(scale, np.int8)
    out = np.empty(mant.size, np.float32)
    l.bfp_decode_f32(
        mant.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        scale.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        mant.size, block_size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return out.reshape(mant.shape)
