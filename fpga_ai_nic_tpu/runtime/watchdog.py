"""Failure detection and recovery — the subsystem the reference lacks.

The reference documents a nondeterministic infinite hang (OPAE reads/writes
to on-board memory that never complete, hw/README:3-5) and ships no recovery:
its `kill_syn_e0` CSR is declared but never used (hw/all_reduce.sv:83) and
the only remedy is a full shell reset (`iko areset`/`reset`,
sw/mlp_mpi_example_f32.cpp:54-57).  SURVEY.md §5 calls this out as a gap to
fill, not replicate.  Here:

- ``Watchdog.run`` bounds any device-touching call with a wall-clock
  timeout; a wedged dispatch/tunnel raises ``DeviceHangError`` instead of
  spinning forever the way the reference's ``wait()`` poll loop does
  (sw/mlp_mpi_example_f32.cpp:157-180).
- ``Heartbeat`` is the training-loop liveness probe: steps beat it, a
  monitor (or the loop itself) checks staleness.
- ``run_with_recovery`` retries a step from the last known-good state with
  exponential backoff — elastic recovery for transient failures
  (preempted chip, flaky tunnel), composing with utils.checkpoint for
  cross-process restarts.

A hung XLA dispatch cannot be cancelled from Python (the thread leaks until
the runtime returns) — same physics as the FPGA: detection and restart is
the recovery model, matching how production TPU jobs handle preemption.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import jax


class DeviceHangError(RuntimeError):
    """A device-touching call exceeded its watchdog timeout."""


class Watchdog:
    """Run device-touching callables under a wall-clock timeout.

    One DAEMON thread per call: a wedged call must not keep the interpreter
    alive at exit (concurrent.futures workers are non-daemon and its atexit
    hook joins them — a hung dispatch would then hang process shutdown too,
    turning a detected failure back into the reference's undetected one).
    """

    def __init__(self, timeout_s: float = 300.0):
        self.timeout_s = timeout_s

    def run(self, fn: Callable, *args, timeout_s: Optional[float] = None,
            **kwargs) -> Any:
        result: dict = {}
        done = threading.Event()

        def target():
            try:
                result["value"] = fn(*args, **kwargs)
            except BaseException as e:      # noqa: BLE001 — re-raised below
                result["error"] = e
            finally:
                done.set()

        limit = timeout_s if timeout_s is not None else self.timeout_s
        threading.Thread(target=target, daemon=True,
                         name="watchdog").start()
        if not done.wait(limit):
            raise DeviceHangError(
                f"{getattr(fn, '__name__', fn)!r} exceeded "
                f"{limit:.1f}s — device or tunnel "
                "presumed hung (reference analogue: hw/README:3 hang with "
                "no kill path)")
        if "error" in result:
            raise result["error"]
        return result["value"]


@dataclass
class Heartbeat:
    """Liveness probe for a training loop: the loop calls ``beat()`` every
    step; anyone may call ``stalled()``/``assert_alive()``."""

    stall_after_s: float = 600.0

    def __post_init__(self):
        self._last = time.monotonic()
        self._beats = 0
        self._lock = threading.Lock()

    def beat(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._beats += 1

    @property
    def beats(self) -> int:
        return self._beats

    def age_s(self) -> float:
        with self._lock:
            return time.monotonic() - self._last

    def stalled(self) -> bool:
        return self.age_s() > self.stall_after_s

    def assert_alive(self) -> None:
        age = self.age_s()
        if age > self.stall_after_s:
            raise DeviceHangError(
                f"no heartbeat for {age:.1f}s (> {self.stall_after_s:.1f}s)")


def run_with_recovery(step_fn: Callable[[Any, Any], Tuple[Any, Any]],
                      state: Any, batch: Any, *,
                      max_retries: int = 2,
                      backoff_s: float = 1.0,
                      watchdog: Optional[Watchdog] = None,
                      restore_fn: Optional[Callable[[], Any]] = None,
                      on_failure: Optional[Callable[[Exception], None]] = None,
                      ) -> Tuple[Any, Any]:
    """Run one training step with retries from known-good state.

    On failure (including DeviceHangError from the watchdog), restores
    state via restore_fn (e.g. a checkpoint load; defaults to reusing the
    pre-step state, valid for non-donating steps because they are
    functional) and retries with exponential backoff.  Raises the last
    error after max_retries.

    Donation caveat: the framework's trainers jit their step with
    ``donate_argnums=(0,)``, so a dispatched-then-failed attempt may have
    consumed the input state's buffers — retrying with the same pytree
    would crash on deleted arrays.  Pass restore_fn (checkpoint restore)
    for those; the retry loop checks and raises a clear error otherwise.
    """

    def _deleted(tree) -> bool:
        return any(getattr(l, "is_deleted", lambda: False)()
                   for l in jax.tree_util.tree_leaves(tree))

    err: Optional[Exception] = None
    for attempt in range(max_retries + 1):
        src = state if restore_fn is None or attempt == 0 else restore_fn()
        if attempt > 0 and restore_fn is None and _deleted(src):
            raise RuntimeError(
                "cannot retry: the failed step donated the state buffers "
                "(trainer steps use donate_argnums); pass restore_fn="
                "<checkpoint restore> to run_with_recovery") from err
        try:
            if watchdog is not None:
                return watchdog.run(step_fn, src, batch)
            return step_fn(src, batch)
        except Exception as e:      # noqa: BLE001 — retry boundary
            err = e
            if on_failure is not None:
                on_failure(e)
            if attempt < max_retries:
                time.sleep(backoff_s * (2 ** attempt))
    raise err
