from . import chaos, native, staging  # noqa: F401
from .queue import CollectiveQueue, Ticket
from .requests import Request, RequestQueue, ServeStats
from .watchdog import DeviceHangError, Heartbeat, Watchdog, run_with_recovery

__all__ = ["CollectiveQueue", "Ticket", "native", "staging", "Watchdog",
           "Heartbeat", "DeviceHangError", "run_with_recovery", "chaos",
           "Request", "RequestQueue", "ServeStats"]
