from .queue import CollectiveQueue, Ticket
from . import native  # noqa: F401

__all__ = ["CollectiveQueue", "Ticket", "native"]
