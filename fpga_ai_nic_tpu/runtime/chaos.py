"""Deterministic fault injection + collective integrity checking.

The reference's defining failure mode is a nondeterministic infinite hang
with no recovery path: OPAE reads/writes to on-board memory never complete
(hw/README:3-5), the `kill_syn_e0` kill CSR is declared but never wired
(hw/all_reduce.sv:83), and the only remedy is a full shell reset
(sw/mlp_mpi_example_f32.cpp:54-57).  `runtime.watchdog` ships the
*detection* half; this module ships the half that makes detection
testable: a seeded, deterministic fault plan that can provoke every
failure class on demand, at the three device-touching boundaries —

  - ``queue.issue`` / ``queue.wait``  (runtime/queue.py host issue loop)
  - ``staging``                       (runtime/staging.py host batch gather)
  - ``collective``                    (the explicit-ring reduce-scatter AND
                                       all-gather in ops/ring.py, via a
                                       pure_callback tap that executes
                                       INSIDE the jitted program; the
                                       TPU-only fused ring_pallas kernel
                                       path is NOT tapped — off-TPU it
                                       falls back onto the tapped ring)

plus the collective-integrity layer the compressed wire path needs:
per-chunk checksums across the all-reduce (input contribution sums vs the
reduced output), a NaN/inf guard, and a host-side gradient-norm drift
guard — BFP quantization is *bounded* error, so anything outside the bound
is corruption, caught before the optimizer consumes it.

Fault classes (``FAULT_KINDS``):

  hang        sleep far past the watchdog limit — the reference's OPAE
              poll-forever, provoked on purpose.
  slowdown    sleep below the limit — a straggler hop/host; must be
              survived WITHOUT recovery.
  exception   raise InjectedFault — a transient driver/tunnel error.
  corruption  silently damage the payload (NaN / high-bit flip / scale) —
              the failure a compressed wire adds and checksums must catch.
  preemption  raise InjectedPreemption — the process lost its device slice
              (TPU preemption); recovery must re-init + restore.

Sites are host boundaries except ``collective``, whose faults run inside
the compiled step via `jax.pure_callback` (sleep or corrupt only — raising
inside an XLA callback aborts the runtime rather than unwinding the step,
so transient-exception faults belong to the host sites).

Everything is deterministic under a fixed seed: the plan's spec list, the
corrupted indices, and the flipped bits all derive from
``numpy.random.default_rng(seed)`` — a failing chaos run replays exactly.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "FAULT_KINDS", "DURABILITY_KINDS", "SITES", "TRAIN_SITES",
    "SERVE_SITES", "WIRE_SITES", "CKPT_SITES",
    "CORRUPTION_MODES",
    "InjectedFault", "InjectedPreemption", "IntegrityError",
    "WireIntegrityError",
    "FaultSpec", "FaultPlan", "NormDriftGuard",
    "chunk_checksums", "collective_integrity", "integrity_tol",
    "check_step_diag", "install_collective_tap", "uninstall_collective_tap",
    "install_wire_tap", "uninstall_wire_tap",
    "activate", "state_buffers_alive",
]

FAULT_KINDS = ("hang", "slowdown", "exception", "corruption", "preemption")
# "serve.step" is the serving plane's tick boundary (serve.engine): a
# host site like queue.*, fired once per engine tick inside the
# watchdog-bounded device work.  "serve.handoff" fires at the fleet's
# KV-migration boundary (serve.fleet._handoff — an exception there must
# degrade to replay, never lose the request) and "fleet.membership" at
# the fleet tick boundary (a preemption there IS a replica kill: the
# victim's in-flight requests must migrate to survivors).  The TRAINING
# matrix/soak in tools/chaos_bench.py iterates TRAIN_SITES — a serving
# spec never fires in a training run.
#
# "reshard.transfer" is the live-reshard transfer program's WIRE (the
# per-segment ppermute payloads of parallel/reshard.lower_apply): like
# "collective" it executes inside an XLA callback (corruption only), via
# the ENCODED-payload wire tap below — the boundary the exact frame
# checksums (ops.integrity) guard.  It is not in TRAIN_SITES: it can
# only fire while a reshard transfer is actually running, so the
# generic matrix/soak would plan unfireable specs; the dedicated
# integrity corruption cells in tools/chaos_bench.py own it.
# chaos FIRE point (the code boundary that actually calls
# ``FaultPlan.fire`` / arms a tap) -> the chaos SITE its specs target.
# The exported ``*_SITES`` tuples are DERIVED from these maps — never
# hand-written — so a new fire point that lands here is automatically
# part of the matrix/soak sweep, and one that doesn't is a one-line
# review catch.  This is the PR-12 drift class ("serve.handoff" missing
# from WIRE_SITES, caught by review) frozen structurally; graftlint R6
# fails any module-level ``*_SITES`` tuple built from string literals
# instead of a derivation like the ones below.
_TRAIN_POINT_SITES = {
    "runtime.queue.TicketQueue.issue": "queue.issue",
    "runtime.queue.TicketQueue.wait": "queue.wait",
    "runtime.staging.StagingPipeline.put": "staging",
    "runtime.chaos.collective_tap": "collective",   # XLA callback tap
}
_SERVE_POINT_SITES = {
    "serve.engine.ServeEngine.tick": "serve.step",
    "serve.fleet.ServeFleet._handoff": "serve.handoff",
    "serve.fleet.ServeFleet.tick": "fleet.membership",
}
TRAIN_SITES = tuple(dict.fromkeys(_TRAIN_POINT_SITES.values()))
SERVE_SITES = tuple(dict.fromkeys(_SERVE_POINT_SITES.values()))
# "ckpt.save" / "ckpt.restore" are the DURABILITY sites
# (utils.checkpoint): the save file-op sequence and the restore audit
# boundary.  Their fault kinds model what disks and processes actually
# do to checkpoints — kill-during-save (the op stream truncated at a
# planned prefix), disk-full (ENOSPC mid-sequence), file bit-flip at
# rest (corruption mode="wirebit" through damage_checkpoint) and a
# stale manifest (mode="stale_manifest": a previous step's manifest
# copied over the new one).  Not in TRAIN_SITES: they can only fire
# while a Checkpointer armed with the plan is saving/restoring, so the
# generic matrix/soak would plan unfireable specs; the dedicated
# durability cells in tools/chaos_bench.py own them.
_CKPT_POINT_SITES = {
    "utils.checkpoint.Checkpointer.save": "ckpt.save",
    "utils.checkpoint.Checkpointer.restore": "ckpt.restore",
}
CKPT_SITES = tuple(dict.fromkeys(_CKPT_POINT_SITES.values()))
SITES = TRAIN_SITES + SERVE_SITES + ("reshard.transfer",) + CKPT_SITES
# "wirebit" is the FINITE corruption class the wire checksums exist for
# (the blind spot of every value-space guard): a low bit flipped in the
# ENCODED frame (int8 mantissa / int16 index / f32 low-mantissa word)
# decodes to a plausible, in-band, wrong value — no NaN, no magnitude
# excursion.  At WIRE_SITES it fires through the encoded-payload wire
# tap; at host sites (serve.step payloads, staging) it flips low
# mantissa bits of the float tree in place.
CORRUPTION_MODES = ("nan", "bitflip", "scale", "wirebit", "stale_manifest")

# durability-only fault kinds (ckpt.save): "kill" truncates the save's
# file-op sequence at a planned prefix (``fraction`` of the op count) —
# the simulated mid-save crash the commit protocol must absorb;
# "diskfull" raises ENOSPC at the same point.  Neither is legal at any
# other site (a host boundary has no op stream to truncate).
DURABILITY_KINDS = ("kill", "diskfull")

# faults that can run inside an XLA callback (no raising in there)
_CALLBACK_KINDS = ("hang", "slowdown", "corruption")
# sites that ONLY exist inside an XLA callback
_CALLBACK_ONLY_SITES = ("collective", "reshard.transfer")
# corruption modes consumed by the VALUE taps (collective input, host
# payload trees); "wirebit" belongs to the encoded-payload wire tap
_VALUE_MODES = ("nan", "bitflip", "scale")
# wire-tap point (the string the transfer programs tap with) -> the
# chaos SITE whose wirebit specs fire there
_WIRE_POINT_SITES = {
    "ring.wire": "collective",          # ops.ring / ops.ring_hier hops
    "reshard.wire": "reshard.transfer",  # parallel.reshard segments
    "handoff.wire": "serve.handoff",     # serve.handoff page blocks
}
# the sites wirebit specs reach through the wire tap — DERIVED from the
# point map above so the exported constant can never drift from the
# real routing
WIRE_SITES = tuple(dict.fromkeys(_WIRE_POINT_SITES.values()))


class InjectedFault(RuntimeError):
    """A fault raised on purpose by a FaultPlan (transient by contract)."""

    def __init__(self, spec: "FaultSpec"):
        super().__init__(f"injected {spec.kind} at {spec.site} "
                         f"(step {spec.step})")
        self.spec = spec
        self.kind = spec.kind
        self.site = spec.site


class InjectedPreemption(InjectedFault):
    """The process 'lost its device slice' — recovery requires control-plane
    re-init + checkpoint restore, not a plain retry."""


class IntegrityError(RuntimeError):
    """A collective/loss integrity guard tripped: the step's numbers cannot
    be trusted and must not reach (or have been gated out of) the
    optimizer."""


class WireIntegrityError(IntegrityError):
    """The EXACT tier tripped: an encoded wire frame / KV page failed its
    bit-exact checksum (ops.integrity).  Distinguished from the
    value-space IntegrityError so recovery stats and chaos verdicts can
    prove WHICH tier caught a finite corruption — the class the
    value-space guards are provably blind to."""


def state_buffers_alive(state: Any) -> bool:
    """True when every device buffer in a state pytree is still live —
    the gate between the two recovery tiers (parallel.elastic): a
    preemption detected BEFORE the step dispatched leaves the in-memory
    state intact, so it can be migrated to the surviving mesh shape by
    collective redistribution (parallel.reshard); one detected at the
    wait boundary may have DONATED the state's buffers into the failed
    attempt, and only a checkpoint restore can reconstruct it."""
    import jax
    for leaf in jax.tree_util.tree_leaves(state):
        if isinstance(leaf, jax.Array) and leaf.is_deleted():
            return False
    return True


@dataclass(frozen=True)
class FaultSpec:
    """One planned fault: fire ``kind`` at ``site`` on trainer step
    ``step``.  ``duration_s`` is the sleep for hang/slowdown (a hang is a
    sleep chosen to exceed the watchdog limit; the daemon worker thread
    absorbs it).  ``mode``/``fraction`` shape corruption."""

    kind: str
    site: str
    step: int
    duration_s: float = 0.25
    mode: str = "nan"             # corruption: "nan" | "bitflip" | "scale"
    fraction: float = 0.01        # corrupted element fraction (>= 1 elem)

    def __post_init__(self):
        assert self.kind in FAULT_KINDS + DURABILITY_KINDS, self.kind
        assert self.site in SITES, self.site
        assert self.mode in CORRUPTION_MODES, self.mode
        if self.kind in DURABILITY_KINDS and self.site != "ckpt.save":
            raise ValueError(
                f"{self.kind!r} only exists at the 'ckpt.save' site: it "
                "truncates/fails the save file-op sequence at a planned "
                "prefix (fraction of the op count) — no other site has "
                "an op stream to interrupt")
        if self.site in CKPT_SITES and self.kind not in \
                DURABILITY_KINDS + ("corruption",):
            raise ValueError(
                f"{self.kind!r} cannot fire at the {self.site!r} site: "
                "durability sites take kill/diskfull (save only) and "
                "corruption (mode='wirebit' file bit-flip at rest, "
                "mode='stale_manifest') — hang/exception belong to the "
                "host boundaries around the checkpoint call")
        if self.site in CKPT_SITES and self.kind == "corruption" \
                and self.mode not in ("wirebit", "stale_manifest"):
            raise ValueError(
                f"corruption mode {self.mode!r} cannot fire at "
                f"{self.site!r}: stored-file damage is 'wirebit' (a low "
                "stored bit flips at rest) or 'stale_manifest' — the "
                "value modes corrupt live payload trees, not files")
        if self.mode == "stale_manifest" and self.site not in CKPT_SITES:
            raise ValueError(
                "mode='stale_manifest' only exists at the durability "
                "sites (ckpt.save / ckpt.restore): it swaps a step's "
                "manifest for a previous step's")
        if self.site in _CALLBACK_ONLY_SITES \
                and self.kind not in _CALLBACK_KINDS:
            raise ValueError(
                f"{self.kind!r} cannot fire at the {self.site!r} site: it "
                "executes inside an XLA callback, where raising aborts the "
                "runtime instead of unwinding the step — plan it at a host "
                "site (queue.*/staging) instead")
        if self.site == "reshard.transfer" and (
                self.kind != "corruption" or self.mode != "wirebit"):
            raise ValueError(
                "the 'reshard.transfer' site is the transfer program's "
                "wire tap: only corruption mode='wirebit' specs can fire "
                "there (the tap pops wirebit alone — any other spec "
                "would stay armed forever; hang/slowdown belong to the "
                "host boundaries around the transfer)")


class FaultPlan:
    """A deterministic schedule of FaultSpecs plus the machinery that fires
    them.  Thread-safe: host hooks and the in-program collective tap may
    run concurrently (queue issue thread vs XLA callback threads).

    Protocol with the hook sites::

        plan.begin_step(i)          # trainer loop, before dispatching step i
        plan.fire(site)             # host boundary: may sleep or raise
        x = plan.corrupt(site, x)   # host boundary carrying a payload
        y = plan.collective_payload(y)   # inside jit, via the ring tap

    Each spec fires at most once (``fired``) so a recovery retry of the
    same step re-runs clean — the injected fault is transient by
    construction, like the reference's nondeterministic hang."""

    def __init__(self, faults: Iterable[FaultSpec] = (), seed: int = 0):
        self.faults: Tuple[FaultSpec, ...] = tuple(faults)
        self.seed = seed
        self.fired: List[FaultSpec] = []
        self._step = -1
        self._lock = threading.RLock()
        # optional obs.events.EventStream: every fired spec lands as an
        # instant event so the Perfetto timeline shows the injected fault
        # on the same axis as the spans/tickets it perturbs (ElasticTrainer
        # attaches its profiler's stream automatically)
        self.events = None

    # -- construction -------------------------------------------------------

    @classmethod
    def random(cls, seed: int, n_steps: int, *, rate: float = 0.25,
               kinds: Sequence[str] = FAULT_KINDS,
               sites: Sequence[str] = TRAIN_SITES,
               duration_s: float = 0.25) -> "FaultPlan":
        """Seeded random plan: each step draws one fault with probability
        ``rate``; kind/site/mode are drawn uniformly from the legal
        combinations.  Same seed -> identical plan, always."""
        rng = np.random.default_rng(seed)
        specs = []
        for step in range(n_steps):
            if rng.random() >= rate:
                continue
            site = str(rng.choice(list(sites)))
            legal = [k for k in kinds
                     if site != "collective" or k in _CALLBACK_KINDS]
            if not legal:
                continue
            kind = str(rng.choice(legal))
            specs.append(FaultSpec(
                kind=kind, site=site, step=step, duration_s=duration_s,
                # value modes only: a random wirebit spec would need the
                # wire tap installed to fire at all — the dedicated
                # integrity cells own that mode deterministically
                mode=str(rng.choice(list(_VALUE_MODES)))))
        return cls(specs, seed=seed)

    @classmethod
    def sustained(cls, kind: str, site: str, *, start_step: int,
                  n_steps: int, duration_s: float = 0.25,
                  mode: str = "nan", fraction: float = 0.01,
                  seed: int = 0) -> "FaultPlan":
        """A REGIME SHIFT, not a glitch: one identical spec per step for
        ``n_steps`` consecutive steps from ``start_step``.  Single specs
        fire at most once (transient by contract), so a sustained
        condition — the straggling link whose codec break-even has moved
        (SparCML), the forced `slowdown@collective` cell that proves the
        drift observatory's detection→switch path end to end — is
        modeled as one spec per step, each firing exactly once."""
        assert n_steps >= 1, n_steps
        return cls([FaultSpec(kind, site, step=start_step + i,
                              duration_s=duration_s, mode=mode,
                              fraction=fraction)
                    for i in range(n_steps)], seed=seed)

    # -- stepping -----------------------------------------------------------

    def begin_step(self, step: int) -> None:
        with self._lock:
            self._step = int(step)

    @property
    def step(self) -> int:
        return self._step

    def _take(self, site: str, kinds: Sequence[str],
              limit: Optional[int] = None,
              modes: Optional[Sequence[str]] = None) -> List[FaultSpec]:
        """Pop (mark fired) the unfired specs matching (site, current step,
        kinds).  Fired-ness is per spec INSTANCE (identity, not dataclass
        equality): a plan may deliberately schedule several equal specs —
        e.g. one per expected retry — and each must fire exactly once.
        ``limit`` caps how many are popped per call: raising hooks take one
        at a time, so sibling specs stay armed for the retry.  ``modes``
        (corruption only) restricts which corruption modes this hook
        consumes: the VALUE tap must leave "wirebit" specs armed for the
        ENCODED-payload wire tap (and vice versa) — the two taps model
        different fault locations and must not steal each other's specs."""
        with self._lock:
            fired_ids = {id(f) for f in self.fired}
            out = [s for s in self.faults
                   if s.site == site and s.step == self._step
                   and s.kind in kinds and id(s) not in fired_ids
                   and (modes is None or s.kind != "corruption"
                        or s.mode in modes)]
            if limit is not None:
                out = out[:limit]
            self.fired.extend(out)
        ev = self.events
        if ev is not None:
            for s in out:
                ev.instant("chaos.fire", kind=s.kind, site=s.site,
                           step=s.step)
        return out

    # -- host-side firing ---------------------------------------------------

    def fire(self, site: str) -> None:
        """Host boundary hook: sleeps for hang/slowdown, raises for
        exception/preemption.  Corruption specs are left for corrupt()."""
        for spec in self._take(site, ("hang", "slowdown")):
            time.sleep(spec.duration_s)
        for spec in self._take(site, ("preemption",), limit=1):
            raise InjectedPreemption(spec)
        for spec in self._take(site, ("exception",), limit=1):
            raise InjectedFault(spec)

    def corrupt(self, site: str, tree: Any) -> Any:
        """Apply any pending corruption specs at ``site`` to a pytree of
        arrays; returns the tree unchanged (same objects, zero copies) when
        nothing fires."""
        specs = self._take(site, ("corruption",))
        if not specs:
            return tree
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        for spec in specs:
            # corrupt the largest float leaf: for a batch that is the
            # payload (not e.g. int labels); for a (state, batch) tree —
            # the queue.issue boundary — whichever of the master shard
            # and the batch is bigger, so the guard layer that catches
            # it depends on model-vs-batch size (both layers are pinned
            # down by the dedicated queue.wait / staging cells)
            fl = [i for i, l in enumerate(leaves)
                  if np.issubdtype(np.asarray(l).dtype, np.floating)]
            if not fl:
                continue
            i = max(fl, key=lambda j: np.asarray(leaves[j]).size)
            leaves[i] = self._corrupt_array(np.array(leaves[i]), spec)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _corrupt_array(self, arr: np.ndarray, spec: FaultSpec) -> np.ndarray:
        """Deterministic damage: indices and bits derive from
        (plan seed, spec step) only."""
        if spec.mode == "wirebit":
            # the FINITE class: a low STORED bit flips in the array's
            # native width (_corrupt_wire_array, its own rng — an f32
            # round-trip would round the flip away below bf16/f16
            # resolution and silently corrupt NOTHING), so the damaged
            # value stays plausible and in-band — invisible to NaN/
            # norm/magnitude guards by construction; only an exact
            # checksum (ops.integrity) can prove it
            return self._corrupt_wire_array(arr, spec)
        rng = np.random.default_rng((self.seed, spec.step, 0xC0FFEE))
        flat = arr.reshape(-1)
        k = max(1, int(flat.size * spec.fraction))
        idx = rng.choice(flat.size, size=min(k, flat.size), replace=False)
        if spec.mode == "nan":
            flat[idx] = np.nan
        elif spec.mode == "scale":
            flat[idx] = flat[idx] * np.float32(1e8) + np.float32(1e8)
        else:                                   # bitflip: exponent-high bit
            f32 = flat.astype(np.float32, copy=True)
            bits = f32.view(np.uint32)
            bits[idx] ^= np.uint32(1 << 30)
            flat[:] = f32.astype(flat.dtype)
        return arr

    def stage(self, batch: Any) -> Any:
        """The host staging boundary as one call (fire, then corrupt):
        what ``runtime.staging.Stager`` does internally when constructed
        with ``chaos=plan``, for callers staging batches without the
        native gather library (the elastic loop's ``stage_fn``)."""
        self.fire("staging")
        return self.corrupt("staging", batch)

    # -- in-program (collective) path --------------------------------------

    def collective_payload(self, arr: np.ndarray) -> np.ndarray:
        """The host half of the collective tap: called from inside the
        compiled step (one call per shard).  Sleeps for a pending
        hang/slowdown ON THE FIRST SHARD TO ARRIVE (a straggler device);
        corrupts the first arriving shard's payload for corruption specs."""
        for spec in self._take("collective", ("hang", "slowdown")):
            time.sleep(spec.duration_s)
        for spec in self._take("collective", ("corruption",),
                               modes=_VALUE_MODES):
            arr = self._corrupt_array(np.array(arr), spec)
        return arr

    # -- in-program (encoded wire) path -------------------------------------

    def wire_payload(self, arr: np.ndarray, point: str) -> np.ndarray:
        """The host half of the ENCODED-payload wire tap: called from
        inside a transfer program, once per payload array per hop, with
        the bytes exactly as they ride the wire (int8 mantissa/scale
        tiles, int16 top-k indices, raw f32 words).  Only "wirebit"
        corruption specs fire here — the finite low-bit class the exact
        frame checksums (ops.integrity) exist for; a flipped encoded bit
        decodes to a plausible in-band value no value-space guard can
        see."""
        site = _WIRE_POINT_SITES.get(point)
        if site is None:
            return arr
        # limit=1: ONE corruption event per wire crossing.  A transfer
        # program taps once per payload array, so sibling specs at the
        # same step stay armed for LATER payloads/attempts (the
        # bounded-retry cells need the retry to trip too) — and two
        # identical deterministic flips can never land on one array and
        # XOR-cancel each other
        for spec in self._take(site, ("corruption",), limit=1,
                               modes=("wirebit",)):
            arr = self._corrupt_wire_array(np.array(arr), spec)
        return arr

    # -- durability (checkpoint file) path ----------------------------------

    def take_save_interrupts(self) -> List[FaultSpec]:
        """Pop the pending kill/diskfull spec at ``ckpt.save`` for the
        save whose file-op sequence is about to execute
        (utils.checkpoint._exec_ops maps the spec's ``fraction`` to an
        op index and stops there — the simulated mid-save crash).
        ``limit=1``: ONE interrupt per save — a save dies once, so
        sibling specs at the same step stay armed for LATER saves
        instead of being popped-as-fired without ever firing (the
        wire tap's one-event-per-crossing discipline)."""
        return self._take("ckpt.save", DURABILITY_KINDS, limit=1)

    def damage_checkpoint(self, site: str, step_dir: str,
                          prev_manifest: Optional[str] = None) -> None:
        """Fire pending corruption specs at a durability site against a
        COMMITTED step directory — damage at rest, applied after the
        save commit (``ckpt.save``) or just before the restore audit
        (``ckpt.restore``).

        ``mode="wirebit"``: the lowest stored bit of one word in the
        data region of a deterministically chosen PRIMARY leaf file
        flips — a plausible, in-band value (f32 low-mantissa byte /
        int8 LSB) that no magnitude or finiteness guard can see; only
        the manifest's exact checksum audit proves it.
        ``mode="stale_manifest"``: the step's manifest is replaced with
        the PREVIOUS step's (operator error / misdirected copy) — the
        audit must reject it as describing other bytes (the step-field
        and self-checksum validation), never validate against it."""
        import os
        import shutil
        # lazy: runtime.chaos must stay importable without the utils
        # layer; utils.checkpoint only imports chaos lazily too
        from ..utils.checkpoint import (MANIFEST_FILE, flip_stored_bit,
                                        npy_data_offset)
        for spec in self._take(site, ("corruption",),
                               modes=("wirebit", "stale_manifest")):
            if spec.mode == "stale_manifest":
                if prev_manifest is not None and \
                        os.path.exists(prev_manifest):
                    shutil.copyfile(
                        prev_manifest,
                        os.path.join(step_dir, MANIFEST_FILE))
                continue
            # primary npy files only (mirror copies end ".m.npy"): the
            # repair tier exists exactly for a damaged primary
            try:
                names = sorted(
                    f for f in os.listdir(step_dir)
                    if f.endswith(".npy") and not f.endswith(".m.npy"))
            except FileNotFoundError:
                continue
            if not names:
                continue
            big = [f for f in names
                   if os.path.getsize(os.path.join(step_dir, f)) >= 1024]
            pool = big or names
            rng = np.random.default_rng((self.seed, spec.step, 0xD15C0))
            p = os.path.join(step_dir, str(rng.choice(pool)))
            with open(p, "rb") as f:
                header = f.read(16)
            # flip bit 0 of a 4-byte-aligned data byte (f32 low-mantissa
            # byte / int8 LSB — always finite, always in-band)
            n_words = max(1, (os.path.getsize(p)
                              - npy_data_offset(header)) // 4)
            flip_stored_bit(p, byte_off=4 * int(rng.integers(n_words)))

    def _corrupt_wire_array(self, arr: np.ndarray,
                            spec: FaultSpec) -> np.ndarray:
        """Deterministic low-bit damage to an ENCODED frame: the lowest
        stored bit of ``fraction`` of the words flips — int frames flip
        mantissa/index LSBs, f32 frames flip mantissa bit 1.  Always
        finite, always in-band, always a changed wire byte."""
        rng = np.random.default_rng((self.seed, spec.step, 0xB17F11B))
        flat = arr.reshape(-1)
        k = max(1, int(flat.size * spec.fraction))
        idx = rng.choice(flat.size, size=min(k, flat.size), replace=False)
        if flat.dtype == np.float32:
            flat.view(np.uint32)[idx] ^= np.uint32(1 << 1)
        elif flat.dtype.kind in "iu":
            flat[idx] ^= flat.dtype.type(1)
        else:   # other float widths: flip the lowest mantissa bit
            w = flat.view(np.uint16 if flat.dtype.itemsize == 2
                          else np.uint32)
            w[idx] ^= w.dtype.type(1)
        return arr


# ---------------------------------------------------------------------------
# the collective tap (ops.ring / ops.ring_pallas boundary)
# ---------------------------------------------------------------------------

_ACTIVE_PLAN: Optional[FaultPlan] = None


def _tap_fn(x, point: str):
    """Trace-time tap body installed into ops.ring: routes the payload
    through the ACTIVE plan on the host.  The callback executes on every
    step of the compiled program; with no active plan (or no pending spec)
    it is an identity copy."""
    import jax

    def host(v):
        plan = _ACTIVE_PLAN
        a = np.asarray(v)
        if plan is None:
            return a
        return np.asarray(plan.collective_payload(a), dtype=a.dtype)

    return jax.pure_callback(host, jax.ShapeDtypeStruct(x.shape, x.dtype), x)


def install_collective_tap() -> None:
    """Install the chaos tap into the explicit-ring collectives.  Must run
    BEFORE the trainer's step is first traced (the tap is compiled into the
    program); per-run plans are then switched via activate()."""
    from ..ops import ring
    ring.set_fault_tap(_tap_fn)


def uninstall_collective_tap() -> None:
    from ..ops import ring
    ring.set_fault_tap(None)


def _wire_tap_fn(x, point: str, consumed=None):
    """Trace-time ENCODED-payload tap body installed into ops.ring (and
    through it every ppermute-bearing transfer program: flat/hier rings,
    the reshard segments, the KV handoff): routes each wire payload
    through the ACTIVE plan's wirebit hook on the host.  Identity copy
    when no plan / no pending spec.  ``consumed`` (traced bool) gates
    the hook to devices whose received bytes the program actually uses
    (ops.ring._tap_wire docstring) — a spec must never be spent on a
    bystander's zero payload."""
    import jax
    import jax.numpy as jnp

    def host(v, c):
        plan = _ACTIVE_PLAN
        a = np.asarray(v)
        if plan is None or not bool(np.asarray(c)):
            return a
        return np.asarray(plan.wire_payload(a, point), dtype=a.dtype)

    c = jnp.bool_(True) if consumed is None else consumed
    return jax.pure_callback(host, jax.ShapeDtypeStruct(x.shape, x.dtype),
                             x, c)


def install_wire_tap() -> None:
    """Install the encoded-payload wire tap (the boundary the exact frame
    checksums guard — ops.integrity).  Must run BEFORE the consuming
    transfer program is first traced, same contract as
    install_collective_tap; per-run plans switch via activate()."""
    from ..ops import ring
    ring.set_wire_tap(_wire_tap_fn)


def uninstall_wire_tap() -> None:
    from ..ops import ring
    ring.set_wire_tap(None)


class activate:
    """Context manager binding a plan as the ambient target of the
    collective tap (and a convenience holder for host hooks).

    Dispatch is async: the tap's callback reads the ambient plan from XLA
    callback threads while the program runs, so any step that should see
    the plan must COMPLETE (``jax.block_until_ready`` on its outputs, or a
    blocking ``queue.wait``) before this context exits — the elastic loop
    already blocks per step inside ``_check``."""

    def __init__(self, plan: Optional[FaultPlan]):
        self.plan = plan

    def __enter__(self):
        global _ACTIVE_PLAN
        self._prev = _ACTIVE_PLAN
        _ACTIVE_PLAN = self.plan
        return self.plan

    def __exit__(self, *exc):
        global _ACTIVE_PLAN
        _ACTIVE_PLAN = self._prev
        return False


# ---------------------------------------------------------------------------
# collective integrity (pure JAX — runs inside shard_map)
# ---------------------------------------------------------------------------

def integrity_tol(coll, n: int) -> float:
    """Checksum tolerance for an n-way all-reduce under the configured wire
    format — derived from the codec's DECLARED error bound
    (compress.Codec.error_bound), not from a BFP special case, so the
    integrity layer works unmodified under any registered codec.

    Uncompressed rings/psum differ from the input sums only by f32
    reassociation.  A bounded codec adds per-hop quantization error
    (<= error_bound of the unit max per element per hop: 2^(1-m) for BFP's
    m-bit mantissa — the pre-subsystem hard-wired formula — 1/127 for
    stochastic int8), so the chunk-sum discrepancy is bounded by
    ~(n-1) * error_bound * (unitmax/mean) of the chunk L1.
    Unbounded-by-design codecs (top-k declares error_bound=1.0) saturate
    at the 0.5 cap: the checksum then only trips on the failures it can
    still prove — NaN/inf and runaway scale — with no false trips on
    intentional compression loss (the error-feedback carry, not a per-pass
    bound, is top-k's accuracy story).  Either way the tolerance is a
    GROSS-corruption tripwire (NaN, flipped exponent bits, runaway scale),
    not a bit-exactness check — in-bound quantization noise must pass."""
    from ..ops.fused_update import resolve_codec
    codec = resolve_codec(coll)
    if codec is None:
        return 1e-3
    return min(0.5, (n - 1) * float(codec.error_bound) * 8.0)


def chunk_checksums(flat: "Any", axis_name: str, n: int):
    """Inside shard_map: per-chunk input checksums of a local flat [L]
    contribution, reduced across the axis.  Returns (expect[n], l1[n]):
    expect[b] is the true sum of reduced chunk b; l1[b] the matching scale
    for a relative comparison."""
    import jax.numpy as jnp
    from jax import lax
    sums = flat.reshape(n, -1).sum(axis=1)
    l1 = jnp.abs(flat).reshape(n, -1).sum(axis=1)
    return lax.psum(sums, axis_name), lax.psum(l1, axis_name)


def collective_integrity(expect, l1, g_red, axis_name: str, n: int,
                         tol: float) -> Dict[str, Any]:
    """Inside shard_map, after ``g_red = reduce_scatter(flat)`` (pre-mean):
    compares this device's reduced-chunk sum against the input checksum
    and counts non-finites.  Returns replicated scalar diagnostics::

        integrity_ok   bool  — all chunks within tol AND fully finite
        integrity_err  f32   — worst relative chunk-sum discrepancy
        nonfinite      i32   — NaN/inf count across the reduced vector

    ``integrity_ok`` is safe to gate the optimizer with (NaN comparisons
    come out False, so a poisoned checksum fails closed)."""
    import jax.numpy as jnp
    from jax import lax
    idx = lax.axis_index(axis_name)
    mine = jnp.sum(g_red.astype(jnp.float32))
    onehot = (jnp.arange(n) == idx).astype(jnp.float32)
    # psum of masked per-device sums -> replicated [n] vector of the
    # actual reduced-chunk sums (all-gather without relying on tiling)
    got = lax.psum(onehot * mine, axis_name)
    nonfinite = lax.psum(jnp.sum(~jnp.isfinite(g_red)), axis_name)
    err = jnp.max(jnp.abs(expect - got) / (l1 + 1e-20))
    ok = (nonfinite == 0) & (err <= tol)
    return {"integrity_ok": ok, "integrity_err": err,
            "nonfinite": nonfinite}


def check_step_diag(diag: Dict[str, Any], step: int) -> None:
    """Host-side verdict on a step's integrity diagnostics (raises
    IntegrityError / WireIntegrityError).  Call AFTER the step's outputs
    are materialized.  The EXACT tier (``wire_ok``: bit-conservation of
    the encoded ring frames, ops.integrity) is checked FIRST — a wire
    trip is a different fact than a value-band excursion (it proves the
    bytes changed in flight, with no tolerance involved), and on the
    in-kernel fused-optimizer route this raise is the ONLY recovery path
    (the donated state cannot be gated in-graph; the elastic ladder
    discards the invalidated step)."""
    if not bool(diag.get("wire_ok", True)):
        raise WireIntegrityError(
            f"exact wire checksum tripped at step {step}: an encoded "
            "frame changed between send and receive (finite corruption "
            "class — invisible to the value band; gated/invalidated "
            "before the masters could absorb it)")
    nonfinite = int(diag.get("nonfinite", 0))
    ok = bool(diag.get("integrity_ok", True))
    if nonfinite or not ok:
        raise IntegrityError(
            f"collective integrity tripped at step {step}: "
            f"nonfinite={nonfinite}, "
            f"rel_err={float(diag.get('integrity_err', float('nan'))):.3g} "
            "(update was gated out before the optimizer)")


@dataclass
class NormDriftGuard:
    """Cheap host-side drift guard over a scalar series (gradient norm or
    loss): trips when the value is non-finite, or after ``warmup`` clean
    samples jumps ``factor``x above the running median."""

    factor: float = 1e3
    warmup: int = 3
    window: int = 32
    history: List[float] = field(default_factory=list)

    def check(self, value: float, what: str = "grad_norm") -> None:
        v = float(value)
        if not np.isfinite(v):
            raise IntegrityError(f"{what} is non-finite ({v})")
        h = self.history
        if len(h) >= self.warmup:
            med = float(np.median(h[-self.window:]))
            if med > 0 and v > self.factor * med:
                raise IntegrityError(
                    f"{what} drift: {v:.3g} is {v / med:.1f}x the running "
                    f"median {med:.3g} (factor limit {self.factor:g})")
        h.append(v)
        del h[:-self.window]
