"""Async collective queue — the host-side issue/wait ABI of the reference,
rebuilt on JAX's async dispatch.

Reference ABI (sw/mlp_mpi_example_f32.cpp):
  - ``all_reduce_setup(done_buf, len, node, fpga)``  (:65-98)  -> queue ctor
  - ``all_reduce(grad, weight, flags, done)``        (:114-155) -> issue()
  - ``wait(done_buf, request_id)`` spin-poll         (:157-180) -> wait()
  - <= 8 collectives in flight, round-robin done IDs
    (hw/all_reduce.sv:1228,1373; readme.pdf §2.1)    -> max_inflight window
  - per-collective latency + host-stall counters     (:100-112) -> Profiler

On TPU, "issue" is dispatching a jitted fused collective: XLA queues it and
overlaps it with subsequently dispatched compute exactly the way the FPGA
ring overlapped the next layer's backward GEMM (:752-764).  The queue adds
the reference's *bounded window* semantics — issue blocks on the oldest
outstanding ticket once max_inflight are in flight — plus latency/stall
accounting that XLA does not expose.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Optional

import jax

from ..utils.config import CollectiveConfig
from ..utils.observability import Profiler


@dataclass
class Ticket:
    """A completion handle — the done-flag cache line of the reference
    (done_buf[done_id << 4], sw/mlp_mpi_example_f32.cpp:157-180)."""
    uid: int
    result: Any                      # pytree of (possibly pending) jax arrays
    issued_at: float
    waited: bool = False
    ready_at: Optional[float] = None


class CollectiveQueue:
    """Bounded-window async issue queue over any jitted collective fn.

    fn(*args) -> pytree of arrays.  ``issue`` dispatches asynchronously and
    returns a Ticket; once ``max_inflight`` tickets are outstanding, issue
    first blocks on the oldest (the hardware's 8-deep command FIFO,
    hw/all_reduce.sv:110-244).  ``wait`` blocks until a ticket's result is
    materialized and records latency/stall attribution.
    """

    def __init__(self, fn: Callable, coll: CollectiveConfig,
                 profiler: Optional[Profiler] = None):
        self.fn = fn
        self.coll = coll
        self.profiler = profiler or Profiler()
        self._inflight: Deque[Ticket] = deque()
        self._uid = 0

    # -- reference ABI ------------------------------------------------------

    def issue(self, *args, raw_bytes: int = 0, wire_bytes: int = 0) -> Ticket:
        if len(self._inflight) >= self.coll.max_inflight:
            self.wait(self._inflight[0])
        result = self.fn(*args)          # async dispatch
        self._uid += 1
        t = Ticket(self._uid, result, time.perf_counter())
        self._inflight.append(t)
        st = self.profiler.collectives
        st.issued += 1
        st.raw_bytes += raw_bytes
        st.wire_bytes += wire_bytes or raw_bytes
        return t

    def wait(self, ticket: Ticket) -> Any:
        if ticket.waited:
            return ticket.result
        t0 = time.perf_counter()
        jax.block_until_ready(ticket.result)
        now = time.perf_counter()
        ticket.waited = True
        ticket.ready_at = now
        try:
            self._inflight.remove(ticket)
        except ValueError:
            pass
        st = self.profiler.collectives
        st.completed += 1
        st.record_latency(now - ticket.issued_at)
        st.stall_s += now - t0                    # network-bound time
        st.overlap_s += t0 - ticket.issued_at     # compute overlapped
        return ticket.result

    def wait_all(self):
        while self._inflight:
            self.wait(self._inflight[0])

    @property
    def outstanding(self) -> int:
        return len(self._inflight)
