"""Async collective queue — the host-side issue/wait ABI of the reference,
rebuilt on JAX's async dispatch.

Reference ABI (sw/mlp_mpi_example_f32.cpp):
  - ``all_reduce_setup(done_buf, len, node, fpga)``  (:65-98)  -> queue ctor
  - ``all_reduce(grad, weight, flags, done)``        (:114-155) -> issue()
  - ``wait(done_buf, request_id)`` spin-poll         (:157-180) -> wait()
  - <= 8 collectives in flight, round-robin done IDs
    (hw/all_reduce.sv:1228,1373; readme.pdf §2.1)    -> max_inflight window
  - per-collective latency + host-stall counters     (:100-112) -> Profiler

On TPU, "issue" is dispatching a jitted fused collective: XLA queues it and
overlaps it with subsequently dispatched compute exactly the way the FPGA
ring overlapped the next layer's backward GEMM (:752-764).  The queue adds
the reference's *bounded window* semantics — issue blocks on the oldest
outstanding ticket once max_inflight are in flight — plus latency/stall
accounting that XLA does not expose.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Optional

import jax

from ..utils.config import CollectiveConfig
from ..utils.observability import Profiler


@dataclass
class Ticket:
    """A completion handle — the done-flag cache line of the reference
    (done_buf[done_id << 4], sw/mlp_mpi_example_f32.cpp:157-180)."""
    uid: int
    result: Any                      # pytree of (possibly pending) jax arrays
    issued_at: float
    waited: bool = False
    ready_at: Optional[float] = None
    abandoned: bool = False          # dropped by recovery; never consumed
    # wire accounting carried per ticket so the telemetry plane can render
    # bytes-on-the-wire per collective, not just the running totals
    raw_bytes: int = 0
    wire_bytes: int = 0


class CollectiveQueue:
    """Bounded-window async issue queue over any jitted collective fn.

    fn(*args) -> pytree of arrays.  ``issue`` dispatches asynchronously and
    returns a Ticket; once ``max_inflight`` tickets are outstanding, issue
    first blocks on the oldest (the hardware's 8-deep command FIFO,
    hw/all_reduce.sv:110-244).  ``wait`` blocks until a ticket's result is
    materialized and records latency/stall attribution.
    """

    def __init__(self, fn: Callable, coll: CollectiveConfig,
                 profiler: Optional[Profiler] = None,
                 chaos: Optional[Any] = None) -> None:
        self.fn = fn
        self.coll = coll
        self.profiler = profiler or Profiler()
        # fault-injection hook (runtime.chaos.FaultPlan or None): fires at
        # the issue/wait boundaries — the reference ABI's two host-visible
        # points, where its real hang lived (the wait() spin,
        # sw/mlp_mpi_example_f32.cpp:157-180)
        self.chaos = chaos
        self._inflight: Deque[Ticket] = deque()
        self._uid = 0
        # bumped by abandon(): an issue() that straddles a recovery (its
        # worker thread outlived a watchdog timeout) sees the epoch moved
        # and marks its own ticket abandoned instead of enqueueing it.
        # _lock serializes the epoch/window/ticket-flag handshake between
        # recovery and zombie watchdog workers — the unsynchronized check
        # would let a zombie append a stale ticket right after abandon()
        # cleared the window, recreating the permanent wedge
        self._epoch = 0
        self._lock = threading.Lock()

    # -- reference ABI ------------------------------------------------------

    def issue(self, *args: Any, raw_bytes: int = 0,
              wire_bytes: int = 0) -> Ticket:
        with self._lock:
            epoch = self._epoch
        while True:
            with self._lock:
                if (epoch != self._epoch
                        or len(self._inflight) < self.coll.max_inflight):
                    break
                head = self._inflight[0]
            self.wait(head)                       # may stall (full window)
        if self.chaos is not None and epoch == self._epoch:
            self.chaos.fire("queue.issue")        # may stall (hang spec)
        with self._lock:
            alive = epoch == self._epoch
        if not alive:
            # recovery abandoned the window while this thread was stalled
            # above (a timed-out watchdog worker resuming): the attempt is
            # dead — dispatch nothing, consume no corruption specs, and
            # hand back a ticket wait() treats as already dropped
            self.profiler.collectives.record_abandoned()
            return Ticket(0, None, time.perf_counter(), abandoned=True)
        if self.chaos is not None:
            args = self.chaos.corrupt("queue.issue", args)
        result = self.fn(*args)          # async dispatch
        t = Ticket(0, result, time.perf_counter(),
                   raw_bytes=raw_bytes, wire_bytes=wire_bytes or raw_bytes)
        with self._lock:
            if epoch != self._epoch:     # abandoned during the dispatch
                t.abandoned = True
                self.profiler.collectives.record_abandoned()
                return t
            self._uid += 1
            t.uid = self._uid
            self._inflight.append(t)
        self.profiler.collectives.record_issue(raw_bytes, wire_bytes)
        self.profiler.events.instant("queue.issue", uid=t.uid,
                                     wire_bytes=t.wire_bytes)
        return t

    def wait(self, ticket: Ticket) -> Any:
        if ticket.waited:
            return ticket.result
        if ticket.abandoned:
            # a dead attempt's ticket (see issue()/abandon()): consume no
            # chaos specs, record no stats — the result is discarded
            ticket.waited = True
            return ticket.result
        if self.chaos is not None:
            self.chaos.fire("queue.wait")
        t0 = time.perf_counter()
        jax.block_until_ready(ticket.result)
        with self._lock:
            if ticket.abandoned:
                # recovery dropped this ticket while we were blocked (the
                # watchdog's worker thread outlives its timeout): the
                # result is never consumed — record nothing, fire nothing,
                # or the zombie would consume the live run's chaos specs
                # and inflate completed/stall in the very stats recovery
                # reports through
                ticket.waited = True
                return ticket.result
            # claim the ticket: from here abandon() can no longer flag it
            try:
                self._inflight.remove(ticket)
            except ValueError:
                pass
        if self.chaos is not None:
            # wire-corruption surface: the materialized result is what the
            # optimizer will consume
            ticket.result = self.chaos.corrupt("queue.wait", ticket.result)
        now = time.perf_counter()
        ticket.waited = True
        ticket.ready_at = now
        latency = now - ticket.issued_at
        stall = now - t0                          # network-bound time
        overlap = t0 - ticket.issued_at           # compute overlapped
        self.profiler.collectives.record_completion(latency, stall, overlap)
        # the ticket's full issue->ready interval as one structured span
        # (lane="queue" gives tickets their own Perfetto track): the
        # host-visible per-collective latency the reference reads from
        # lpbk_latency CSRs, here with stall/overlap split attached
        # issued_at is time.perf_counter() — the SAME clock the event
        # stream timestamps with (perf_counter_ns), so the span starts at
        # the true issue instant, not a now-minus-latency reconstruction
        self.profiler.events.emit(
                "span", "collective", t_ns=int(ticket.issued_at * 1e9),
                dur_ns=int(latency * 1e9),
                attrs={"lane": "queue", "uid": ticket.uid,
                       "stall_s": round(stall, 6),
                       "overlap_s": round(overlap, 6),
                       "wire_bytes": ticket.wire_bytes,
                       "raw_bytes": ticket.raw_bytes})
        return ticket.result

    def wait_all(self) -> None:
        while True:
            with self._lock:
                if not self._inflight:
                    return
                head = self._inflight[0]
            self.wait(head)

    def abandon(self) -> int:
        """Drop every inflight ticket WITHOUT waiting.  The recovery path
        after a detected hang: a wedged dispatch's ticket can never be
        waited out, and left in the window it wedges issue() itself once
        max_inflight stale tickets pile up (issue would block forever in
        wait() on a dead result — the reference's spin, one level up).
        The dropped results are simply never consumed; returns the count."""
        with self._lock:
            self._epoch += 1         # a stalled issue() sees this on resume
            n = len(self._inflight)
            for t in self._inflight:
                t.abandoned = True   # a blocked wait() sees this on resume
            self._inflight.clear()
        if n:
            self.profiler.collectives.record_abandoned(n)
            self.profiler.events.instant("queue.abandon", dropped=n)
        return n

    @property
    def outstanding(self) -> int:
        return len(self._inflight)
