"""ctypes wrapper for the native batch-staging engine (csrc/staging.cpp).

Shuffled-minibatch assembly is a row gather — dst[i] = src[idx[i]] — that
numpy performs single-threaded under the GIL.  The native engine runs it on
an OpenMP team inside a worker thread over a pool of reusable page-aligned
buffers, so batch k+1 stages while Python dispatches batch k (the staging
role the reference's C++ driver plays for its device DMA,
sw/mlp_mpi_example_f32.cpp:381-424).

Degrades gracefully: `Stager.available()` is False when the .so is absent
and cannot be built, and `data.epochs_of(native=...)` falls back to numpy.
Zero-copy: `wait()` returns a numpy view of the slot buffer — valid until
`release(slot)`; callers hand it to `jax.device_put` before releasing.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

_lib = None
_tried = False


def lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    import os
    from .native import load_native
    # FPGA_AI_NIC_STAGING_SO=libstaging_tsan.so runs the suite under
    # ThreadSanitizer (make -C csrc tsan)
    l = load_native(os.environ.get("FPGA_AI_NIC_STAGING_SO",
                                   "libstaging.so"))
    if l is None:
        return None
    l.stage_create.restype = ctypes.c_void_p
    l.stage_create.argtypes = [ctypes.c_int, ctypes.c_int64]
    l.stage_create_sized.restype = ctypes.c_void_p
    l.stage_create_sized.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                     ctypes.c_int]
    l.stage_destroy.argtypes = [ctypes.c_void_p]
    l.stage_submit.restype = ctypes.c_int
    l.stage_submit.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.POINTER(ctypes.c_int64),
                               ctypes.c_int64, ctypes.c_int64]
    l.stage_wait.restype = ctypes.c_void_p
    l.stage_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
    l.stage_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    _lib = l
    return _lib


def available() -> bool:
    return lib() is not None


class Stager:
    """Pool of staging buffers: `Stager(n_slots, bytes)` for uniform slots
    or `Stager.sized([b0, b1, ...])` for per-slot capacities (submits claim
    the smallest FREE slot that fits)."""

    def __init__(self, n_slots: int, slot_bytes: int, chaos=None):
        self._init([slot_bytes] * n_slots, chaos)

    @classmethod
    def sized(cls, slot_bytes_list, chaos=None) -> "Stager":
        self = cls.__new__(cls)
        self._init(list(slot_bytes_list), chaos)
        return self

    def _init(self, sizes, chaos=None):
        # fault-injection hook (runtime.chaos.FaultPlan or None): the host
        # staging boundary — where a wedged gather worker or a bad DMA
        # would surface in the reference's C++ driver
        self.chaos = chaos
        l = lib()
        assert l is not None, "native staging unavailable (csrc build failed)"
        self._l = l
        arr = (ctypes.c_int64 * len(sizes))(*sizes)
        self._pool = l.stage_create_sized(arr, len(sizes))
        if not self._pool:
            raise MemoryError(f"stage_create_sized({sizes})")
        self.n_slots = len(sizes)
        self._sizes = list(sizes)
        self.slot_bytes = max(sizes)
        self._waited = set()
        # submitted job keepalives: src/idx arrays must outlive the gather
        self._live = {}

    def submit(self, src: np.ndarray, idx: np.ndarray) -> int:
        """Enqueue dst[i] = src[idx[i]] over axis 0; returns a slot id.

        Raises when no FREE slot can fit the job: slots only return to the
        pool via release(), which only this thread can call, so blocking in
        the native wait would deadlock — with heterogeneous slot sizes the
        guard must consider capacities, not just counts (a free-but-small
        slot cannot satisfy a large job)."""
        if self.chaos is not None:
            self.chaos.fire("staging")
        src = np.ascontiguousarray(src)
        idx = np.ascontiguousarray(idx, np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= src.shape[0]):
            # the C++ gather memcpys unchecked in a worker thread; an OOB
            # index there is a silent wild read, so bound it here
            raise IndexError(f"index out of range [0, {src.shape[0]})")
        row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
        need = len(idx) * row_bytes
        free_caps = [c for i, c in enumerate(self._sizes)
                     if i not in self._live]
        if not any(c >= need for c in free_caps):
            if any(c >= need for c in self._sizes):
                raise RuntimeError(
                    f"no FREE slot fits {need} B (free capacities "
                    f"{sorted(free_caps)}); release() one before submitting "
                    "more (bounded prefetch window)")
        slot = self._l.stage_submit(
            self._pool, src.ctypes.data_as(ctypes.c_void_p),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx), row_bytes)
        if slot < 0:
            raise ValueError(
                f"batch ({len(idx)} rows x {row_bytes} B) exceeds slot size "
                f"{self.slot_bytes}")
        self._live[slot] = (src, idx, (len(idx),) + src.shape[1:], src.dtype)
        return slot

    def wait(self, slot: int) -> np.ndarray:
        """Block until the slot's gather is done; returns a VIEW of the slot
        buffer (valid until release)."""
        if slot not in self._live:
            # the native wait would block forever on a FREE/unknown slot
            # (and index out of bounds for an invalid id)
            raise KeyError(f"slot {slot} is not outstanding")
        src, idx, shape, dtype = self._live[slot]
        ptr = self._l.stage_wait(self._pool, slot)
        self._waited.add(slot)
        n = int(np.prod(shape, dtype=np.int64))
        buf = (ctypes.c_char * (n * dtype.itemsize)).from_address(ptr)
        out = np.frombuffer(buf, dtype=dtype).reshape(shape)
        if self.chaos is not None:
            # corrupt() copies only when a spec fires — the healthy path
            # keeps the zero-copy view contract
            out = self.chaos.corrupt("staging", out)
        return out

    def release(self, slot: int) -> None:
        """Return a slot to the pool.  Waits for the gather first if the
        caller has not: freeing a QUEUED slot would drop the src/idx
        keepalives while the worker still reads them (use-after-free) and
        desync the C++ slot state machine."""
        if slot not in self._live:
            raise KeyError(f"slot {slot} is not outstanding")
        if slot not in self._waited:
            self._l.stage_wait(self._pool, slot)
        self._live.pop(slot, None)
        self._waited.discard(slot)
        self._l.stage_release(self._pool, slot)

    def close(self) -> None:
        if self._pool:
            self._l.stage_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
