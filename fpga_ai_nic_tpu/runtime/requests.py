"""Serving-plane request intake — the host ABI of the inference service.

The training side of this repo rebuilt the reference NIC's issue/wait
queue (`runtime.queue`); the serving plane needs the request-level
analogue: a thread-safe intake queue a front-end submits generation
requests into, drained by the single-threaded engine loop
(`serve.engine.ServeEngine`).  Telemetry rides the SAME structured event
stream as the collective tickets — every submit lands an instant and
every completed request a span, so the Perfetto timeline shows request
lifetimes on the axis the queue/collective lanes already occupy.

``ServeStats`` follows the locked ``record_*`` discipline graftlint R1
froze for CollectiveStats/RecoveryStats: the front-end thread(s), the
engine loop and (under chaos) watchdog workers all touch these counters.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["Request", "RequestQueue", "ServeStats",
           "WAITING", "PREFILL", "DECODE", "FINISHED"]

# request lifecycle states (host-side; the device step never sees them)
WAITING = "waiting"      # queued or evicted — holds no slot, no pages
PREFILL = "prefill"      # slot assigned, replaying prompt (+ any generated
                         # tokens it lost to an eviction/preemption) in
                         # static chunks
DECODE = "decode"        # one token per engine tick
FINISHED = "finished"


@dataclass
class Request:
    """One generation request plus its host-side runtime bookkeeping.

    The device program never depends on any of this: slots, page
    assignments and replay targets change VALUES in the jitted step's
    operands, never shapes (the J10 recompile-free contract)."""

    uid: int
    prompt: np.ndarray               # int32 [prompt_len]
    max_new: int
    eos_id: Optional[int] = None
    not_before_s: float = 0.0        # arrival offset (offered-load shaping)
    tenant: Optional[str] = None     # traffic-mix label (telemetry only)

    # -- scheduler state (owned by serve.scheduler.ContinuousBatcher) -------
    state: str = WAITING
    slot: int = -1
    admit_seq: int = -1              # admission order; eviction picks newest
    generated: List[int] = field(default_factory=list)
    prefill_done: int = 0            # positions written this admission
    replay_len: int = 0              # prefill target for this admission
    evictions: int = 0

    # -- telemetry timestamps (perf_counter seconds; nan = not yet) ---------
    t_submit: float = float("nan")
    t_admit: float = float("nan")    # FIRST admission (queue wait endpoint)
    t_first: float = float("nan")    # first NEW token (TTFT endpoint)
    t_done: float = float("nan")

    # -- tick-domain milestones (fleet ticks; -1 = not yet) -----------------
    # wall clocks above are machine-dependent; the SLO observatory's
    # windowed latency series use THESE, so a seeded fleet run banks
    # bit-identical percentiles on CPU dryrun and TPU alike
    submit_tick: int = -1
    admit_tick: int = -1
    first_tick: int = -1
    done_tick: int = -1

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def n_tokens(self) -> int:
        """Positions the KV cache must hold right now: every prompt token
        plus every generated token except the newest (whose K/V is
        written by the decode step that consumes it)."""
        g = len(self.generated)
        return self.prompt_len + (g - 1 if g else 0)

    @property
    def done(self) -> bool:
        return self.state == FINISHED


class RequestQueue:
    """Thread-safe request intake with ticket telemetry.

    ``submit()`` may be called from any thread (a front-end, the bench
    driver's arrival process); ``pop_arrived()`` is the engine loop's
    single-threaded drain.  Arrival shaping: a request with
    ``not_before_s=t`` becomes visible t seconds after the queue's
    construction — how the bench sweeps offered load without threads."""

    def __init__(self, events: Optional[Any] = None,
                 stats: Optional["ServeStats"] = None) -> None:
        self.events = events             # obs.events.EventStream or None
        self.stats = stats or ServeStats()
        self._lock = threading.Lock()
        self._pending: List[Request] = []
        self._uid = 0
        self.t0 = time.perf_counter()

    def now(self) -> float:
        return time.perf_counter() - self.t0

    def submit(self, prompt: np.ndarray, max_new: int, *,
               eos_id: Optional[int] = None,
               not_before_s: float = 0.0) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        with self._lock:
            self._uid += 1
            req = Request(uid=self._uid, prompt=prompt, max_new=max_new,
                          eos_id=eos_id, not_before_s=float(not_before_s),
                          t_submit=time.perf_counter())
            self._pending.append(req)
        self.stats.record_submitted()
        if self.events is not None:
            self.events.instant("serve.submit", uid=req.uid,
                                prompt_len=req.prompt_len,
                                max_new=req.max_new)
        return req

    def pop_arrived(self) -> List[Request]:
        """Drain every request whose arrival offset has elapsed (FIFO
        within the drained set)."""
        now = self.now()
        with self._lock:
            out = [r for r in self._pending if r.not_before_s <= now]
            self._pending = [r for r in self._pending
                             if r.not_before_s > now]
        return out

    def next_arrival_in(self) -> Optional[float]:
        """Seconds until the earliest still-future arrival (None when the
        queue is drained) — the engine's idle-sleep bound."""
        now = self.now()
        with self._lock:
            if not self._pending:
                return None
            return max(0.0, min(r.not_before_s for r in self._pending) - now)

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)


@dataclass
class ServeStats:
    """Cross-thread serving counters, mutated ONLY through locked
    ``record_*`` methods (the R1 lock discipline: front-end submit
    threads, the engine loop and chaos/watchdog workers all land
    here)."""

    submitted: int = 0
    admitted: int = 0
    completed: int = 0
    evicted: int = 0
    tokens_out: int = 0
    serve_recoveries: int = 0
    handoffs_in: int = 0         # requests adopted via fleet KV handoff
    handoffs_out: int = 0        # requests migrated away (pages released)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def record_submitted(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_admitted(self) -> None:
        with self._lock:
            self.admitted += 1

    def record_completed(self, n_tokens: int) -> None:
        with self._lock:
            self.completed += 1
            self.tokens_out += int(n_tokens)

    def record_evicted(self, n: int = 1) -> None:
        with self._lock:
            self.evicted += int(n)

    def record_recovery(self) -> None:
        with self._lock:
            self.serve_recoveries += 1

    def record_handoff_in(self) -> None:
        with self._lock:
            self.handoffs_in += 1

    def record_handoff_out(self) -> None:
        with self._lock:
            self.handoffs_out += 1

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return {"submitted": self.submitted,
                    "admitted": self.admitted,
                    "completed": self.completed,
                    "evicted": self.evicted,
                    "tokens_out": self.tokens_out,
                    "serve_recoveries": self.serve_recoveries,
                    "handoffs_in": self.handoffs_in,
                    "handoffs_out": self.handoffs_out}
