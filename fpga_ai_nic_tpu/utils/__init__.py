from .config import (
    BFPConfig,
    CollectiveConfig,
    MeshConfig,
    MLPConfig,
    OptimizerConfig,
    TrainConfig,
)

__all__ = [
    "BFPConfig",
    "CollectiveConfig",
    "MeshConfig",
    "MLPConfig",
    "OptimizerConfig",
    "TrainConfig",
]
