"""Profiler-trace overlap analysis — stall attribution the TPU way.

The reference attributes stalls with hardware counters (stall_host_in/out,
stall_eth_in/out, hw/all_reduce.sv:94-97) because it owns every queue.  On
TPU the runtime hides queues, so SURVEY.md §5 concludes stall attribution
"must come from profiler trace analysis".  This module is that analysis:
it reads a JAX profiler trace (jax.profiler.trace / --trace-dir), walks the
device plane's sync ("XLA Ops") and async ("Async XLA Ops") lines, and
reports for every async op — collectives (all-reduce / all-gather /
reduce-scatter / collective-permute / all-to-all) and DMAs (copy/slice
starts) — how much of its wall time was *overlapped* by synchronous device
compute vs *exposed* (device otherwise idle: the TPU analogue of
stall_eth_in, wire time nothing hid).

Pure-python interval math over jax.profiler.ProfileData; no tensorboard /
xprof dependency.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# hyphenated HLO collective op names — the device-plane classifier matches
# these only.  The short jax-primitive names ("psum", ...) must NOT live
# here: _is_collective substring-matches, and on real-chip traces any
# fusion merely NAMED after a psum consumer (e.g. "psum_invariant_fusion")
# would be banked as async collective time, skewing overlap attribution.
_COLLECTIVE_MARKERS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast", "ragged-all-to-all",
)

# jax-level instruction names, CPU thunk executor only (XLA names HLO
# collectives there after the primitive that built them, e.g. "psum.7").
# Matched as the WHOLE base name plus an optional ".uid" suffix — never as
# a substring — so "psum.7" classifies but "my_psum_like_fusion" does not.
_CPU_PRIMITIVE_MARKERS = (
    "psum", "ppermute", "all_gather", "all_to_all", "psum_scatter",
    "reduce_scatter", "pmax", "pmin",
)
_CPU_PRIMITIVE_RE = re.compile(
    r"(?:%s)(?:\.\d+)?" % "|".join(_CPU_PRIMITIVE_MARKERS))

Interval = Tuple[float, float]          # (start_ns, end_ns)


# ---------------------------------------------------------------------------
# interval arithmetic (pure, unit-tested)
# ---------------------------------------------------------------------------

def merge_intervals(ivs: Iterable[Interval]) -> List[Interval]:
    """Union of possibly-overlapping intervals, sorted, coalesced."""
    out: List[Interval] = []
    for s, e in sorted(ivs):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def total_len(ivs: Sequence[Interval]) -> float:
    return sum(e - s for s, e in ivs)


def overlap_len(iv: Interval, merged: Sequence[Interval]) -> float:
    """Length of iv covered by a *merged* (sorted, disjoint) interval set.
    Bisects to the first candidate: real traces have ~1e5+ sync ops and a
    linear scan per async event would be O(A*S)."""
    import bisect
    s, e = iv
    cov = 0.0
    i = bisect.bisect_right(merged, (s, float("inf"))) - 1
    if i >= 0 and merged[i][1] <= s:
        i += 1
    i = max(i, 0)
    while i < len(merged) and merged[i][0] < e:
        ms, me = merged[i]
        cov += min(e, me) - max(s, ms)
        i += 1
    return cov


# ---------------------------------------------------------------------------
# trace loading
# ---------------------------------------------------------------------------

def find_xplane(trace_dir: str) -> str:
    """Newest .xplane.pb under a jax.profiler.trace output directory."""
    cands = []
    for root, _, files in os.walk(trace_dir):
        for f in files:
            if f.endswith(".xplane.pb"):
                p = os.path.join(root, f)
                cands.append((os.path.getmtime(p), p))
    if not cands:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    return max(cands)[1]


def _load_trace(trace_dir: str, data=None):
    """(xplane path, parsed profile data), reusing an already-parsed
    ``data`` when the caller has one — the tsl-proto shim walks every
    event in pure python, so re-parsing a multi-MB xplane per analysis
    pass dominates CLI runtime."""
    from ..compat import load_profile_data
    path = find_xplane(trace_dir)
    return path, (data if data is not None else load_profile_data(path))


def _is_collective(name: str) -> bool:
    """Device-plane classifier: hyphenated HLO collective names only."""
    n = name.lower()
    return any(m in n for m in _COLLECTIVE_MARKERS)


def _is_cpu_collective(base: str) -> bool:
    """CPU thunk classifier: HLO collective names, plus bare jax-primitive
    instruction names ("psum.7") matched on the full base name."""
    return (_is_collective(base)
            or _CPU_PRIMITIVE_RE.fullmatch(base.lower()) is not None)


def _attribution_report(sync_ivs: List[Interval],
                        async_evs: List[Tuple[str, Interval]],
                        classify=None) -> Dict:
    """Shared overlapped/exposed accounting for one device plane (TPU) or
    one thunk mesh (CPU): async op wall time split into the part covered
    by merged sync compute and the exposed remainder, ranked per op.
    `classify(name)` returns the async bucket key (default: collective vs
    dma by name)."""
    if classify is None:
        def classify(name):
            return ("async_collective_s" if _is_collective(name)
                    else "async_dma_s")
    merged = merge_intervals(sync_ivs)
    rep = {"sync_busy_s": total_len(merged) / 1e9,
           "async_s": 0.0, "async_collective_s": 0.0,
           "async_dma_s": 0.0, "overlapped_s": 0.0, "exposed_s": 0.0}
    exposed_by_op: Dict[str, float] = {}
    for name, iv in async_evs:
        dur = (iv[1] - iv[0]) / 1e9
        cov = overlap_len(iv, merged) / 1e9
        rep["async_s"] += dur
        rep[classify(name)] += dur
        rep["overlapped_s"] += cov
        exposed = dur - cov
        rep["exposed_s"] += exposed
        if exposed > 0:
            exposed_by_op[name] = exposed_by_op.get(name, 0.0) + exposed
    rep["overlap_frac"] = (rep["overlapped_s"] / rep["async_s"]
                           if rep["async_s"] else 1.0)
    rep["exposed_by_op"] = exposed_by_op
    rep["top_exposed"] = sorted(exposed_by_op.items(),
                                key=lambda kv: -kv[1])[:5]
    return rep


def analyze_trace(trace_dir: str, *,
                  plane_substr: str = "/device:", data=None) -> Dict:
    """Overlap/stall report for every device plane in the trace.

    Returns {"devices": {plane_name: report}, "xplane": path}; each report:
      sync_busy_s      — total synchronous device compute ("XLA Ops")
      async{,_collective,_dma}_s — async op wall time by class
      overlapped_s     — async time hidden under sync compute
      exposed_s        — async time with the device otherwise idle (stall)
      top_exposed      — worst offenders [(op, exposed_s)], most first
    """
    path, data = _load_trace(trace_dir, data)
    devices: Dict[str, Dict] = {}
    for plane in data.planes:
        if plane_substr not in plane.name:
            continue
        sync_ivs: List[Interval] = []
        async_evs: List[Tuple[str, Interval]] = []
        for line in plane.lines:
            if line.name == "XLA Ops":
                for ev in line.events:
                    sync_ivs.append((ev.start_ns,
                                     ev.start_ns + ev.duration_ns))
            elif line.name == "Async XLA Ops":
                for ev in line.events:
                    async_evs.append((ev.name.split(" = ")[0],
                                      (ev.start_ns,
                                       ev.start_ns + ev.duration_ns)))
        if not sync_ivs and not async_evs:
            continue
        # full exposed_by_op map kept so cross-device aggregation never
        # drops an op that is small per device but large fleet-wide
        devices[plane.name] = _attribution_report(sync_ivs, async_evs)
    if not devices:
        raise ValueError(
            f"{path} has no '{plane_substr}' plane with XLA Ops lines "
            "(CPU traces carry host thunk lines only; capture on TPU)")
    return {"devices": devices, "xplane": path}


# thunks execute on the per-shard executor threads AND the shared Eigen
# intra-op pool threads; both carry leaf op events.  The executor line's
# prefix follows the CPU client's name across jaxlibs: TfrtCpuClient
# before the PjRt rename (jax <= 0.4.x), PjRtCpuClient after.
_CPU_LINE_PREFIXES = ("tf_XLAPjRtCpuClient", "tf_XLATfrtCpuClient",
                      "tf_XLAEigen")
# leaf thunk events are bare HLO instruction names ("wrapped_tanh",
# "psum.7", "broadcast_add_fusion"); executor infrastructure events mostly
# carry spaces or "::" ("ThunkExecutor::Execute (...)", "end: X",
# "Wait: pending_threads=2/8") — the bare-word exceptions are listed
_CPU_OP_RE = re.compile(r"[\w.\-]+")
_CPU_INFRA = frozenset({"Rendezvous"})   # collective-internal wait event,
# already inside the enclosing psum/ppermute thunk interval
# control-flow thunks ENCLOSE their body's thunk events — counting a
# while-loop's full span as sync compute would blanket every collective
# inside it
_CPU_CONTAINER_RE = re.compile(r"(while|call|conditional)(\.\d+)?")


def analyze_cpu_thunk_trace(trace_dir: str, *,
                            data=None) -> Dict:
    """Overlap attribution from a CPU thunk-executor trace — the virtual
    8-device mesh's substitute for TPU device planes (which a CPU trace
    does not carry; capture with ``ProfileOptions.host_tracer_level=3`` so
    per-op thunk events appear).

    Semantics differ from the device-plane analysis and are labeled in
    the report: each ``tf_XLAPjRtCpuClient/*`` line is one shard's
    executor thread; a collective thunk's interval INCLUDES its
    rendezvous wait (the wire-time analogue), and its *overlapped* share
    is the part hidden under compute thunks running concurrently on the
    other shards' threads — the mesh-level "was anything useful happening
    while shards sat in the collective" question the reference answers
    with stall_eth counters (hw/all_reduce.sv:94-97).  Exposed = no shard
    computed: true mesh-wide stall."""
    path, data = _load_trace(trace_dir, data)
    sync_ivs: List[Interval] = []
    async_evs: List[Tuple[str, Interval]] = []
    n_lines = 0
    for plane in data.planes:
        if not plane.name.startswith("/host:"):
            continue
        for line in plane.lines:
            if not line.name.startswith(_CPU_LINE_PREFIXES):
                continue
            n_lines += 1
            for ev in line.events:
                if (not _CPU_OP_RE.fullmatch(ev.name)
                        or not ev.duration_ns
                        or ev.name in _CPU_INFRA
                        or _CPU_CONTAINER_RE.fullmatch(ev.name)):
                    continue
                iv = (ev.start_ns, ev.start_ns + ev.duration_ns)
                base = ev.name.removeprefix("wrapped_")
                if _is_cpu_collective(base):
                    async_evs.append((ev.name, iv))
                else:
                    sync_ivs.append(iv)
    if not async_evs and not sync_ivs:
        raise ValueError(
            f"{path} carries no leaf thunk events on "
            f"{'/'.join(_CPU_LINE_PREFIXES)} lines — capture with "
            "ProfileOptions.host_tracer_level=3")
    # every async event here IS a collective (that's how it was classified)
    rep = _attribution_report(sync_ivs, async_evs,
                              classify=lambda name: "async_collective_s")
    rep["mode"] = ("cpu-thunks: per-shard collective wall time (incl. "
                   "rendezvous wait) vs compute concurrently live on any "
                   "shard's executor thread")
    rep["n_executor_lines"] = n_lines
    return {"devices": {"cpu-thunk-mesh": rep}, "xplane": path}


def analyze_any(trace_dir: str, *, data=None) -> Dict:
    """Device-plane analysis when the trace has one (TPU), CPU thunk-mode
    otherwise — so the same tooling attributes collectives on the real
    chip and on the virtual mesh."""
    _, data = _load_trace(trace_dir, data)
    try:
        return analyze_trace(trace_dir, data=data)
    except ValueError:
        return analyze_cpu_thunk_trace(trace_dir, data=data)


def device_intervals(trace_dir: str, *,
                     data=None) -> List[Dict]:
    """Raw per-op intervals for the telemetry timeline (obs.timeline):
    every device-plane sync/async event as
    ``{"plane", "line", "name", "start_ns", "end_ns", "cls"}`` — TPU
    device planes when the trace has them, the CPU thunk-executor lines
    otherwise (classified with the same word-scoped rules the aggregate
    reports use, so the timeline and the attribution numbers can never
    disagree about what counts as a collective)."""
    path, data = _load_trace(trace_dir, data)
    out: List[Dict] = []
    for plane in data.planes:
        if "/device:" not in plane.name:
            continue
        for line in plane.lines:
            if line.name not in ("XLA Ops", "Async XLA Ops"):
                continue
            is_async = line.name == "Async XLA Ops"
            for ev in line.events:
                if not ev.duration_ns:
                    continue
                name = ev.name.split(" = ")[0]
                out.append({"plane": plane.name, "line": line.name,
                            "name": name, "start_ns": ev.start_ns,
                            "end_ns": ev.start_ns + ev.duration_ns,
                            "cls": "async" if is_async else "sync"})
    if out:
        return out
    # CPU thunk fallback (virtual-mesh traces): same event filtering as
    # analyze_cpu_thunk_trace, emitted as intervals instead of aggregates
    for plane in data.planes:
        if not plane.name.startswith("/host:"):
            continue
        for line in plane.lines:
            if not line.name.startswith(_CPU_LINE_PREFIXES):
                continue
            for ev in line.events:
                if (not _CPU_OP_RE.fullmatch(ev.name)
                        or not ev.duration_ns
                        or ev.name in _CPU_INFRA
                        or _CPU_CONTAINER_RE.fullmatch(ev.name)):
                    continue
                base = ev.name.removeprefix("wrapped_")
                out.append({"plane": plane.name, "line": line.name,
                            "name": ev.name, "start_ns": ev.start_ns,
                            "end_ns": ev.start_ns + ev.duration_ns,
                            "cls": ("async" if _is_cpu_collective(base)
                                    else "sync")})
    return out


def summarize(report: Dict) -> Dict:
    """Single flattened summary across device planes (the JSON-line shape
    examples embed), keeping the ranked worst stall offenders so the
    attribution names the op, not just the seconds."""
    devs = report["devices"].values()
    agg = {k: sum(d[k] for d in devs)
           for k in ("sync_busy_s", "async_s", "async_collective_s",
                     "async_dma_s", "overlapped_s", "exposed_s")}
    agg["overlap_frac"] = (agg["overlapped_s"] / agg["async_s"]
                           if agg["async_s"] else 1.0)
    agg["n_devices"] = len(report["devices"])
    by_op: Dict[str, float] = {}
    for d in devs:
        # aggregate the FULL per-op maps (falling back to the truncated
        # display list for hand-built reports) — a per-device top-5 merge
        # would drop ops that are small everywhere but large in total
        for name, s in (d.get("exposed_by_op") or
                        dict(d.get("top_exposed", ()))).items():
            by_op[name] = by_op.get(name, 0.0) + s
    agg["top_exposed"] = sorted(by_op.items(), key=lambda kv: -kv[1])[:5]
    return agg


# ---------------------------------------------------------------------------
# CLI: device-plane stall attribution without writing code
# ---------------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m fpga_ai_nic_tpu.utils.trace_analysis <trace-dir>`` —
    the stall-attribution report as one JSON object on stdout (the same
    numbers a driver embeds when run with ``--trace-dir``)."""
    ap = argparse.ArgumentParser(
        prog="python -m fpga_ai_nic_tpu.utils.trace_analysis",
        description="Overlap/stall attribution from a jax.profiler trace "
                    "directory: async collective/DMA wall time split into "
                    "compute-overlapped vs exposed (device idle).")
    ap.add_argument("trace_dir", help="jax.profiler.trace output directory")
    ap.add_argument("--mode", choices=("auto", "device", "cpu"),
                    default="auto",
                    help="device = TPU device planes only, cpu = thunk-"
                         "executor lines only, auto = device with cpu "
                         "fallback (default)")
    ap.add_argument("--per-plane", action="store_true",
                    help="full per-plane reports instead of the flattened "
                         "summary")
    ap.add_argument("--intervals", metavar="FILE", default=None,
                    help="also dump raw per-op intervals (obs.timeline "
                         "input shape) to FILE")
    args = ap.parse_args(argv)
    analyze = {"auto": analyze_any, "device": analyze_trace,
               "cpu": analyze_cpu_thunk_trace}[args.mode]
    try:
        # one parse serves the report AND the interval dump (the shim
        # loader walks the whole xplane in python — parse it once)
        _, data = _load_trace(args.trace_dir)
        report = analyze(args.trace_dir, data=data)
    except (FileNotFoundError, ValueError, ImportError) as e:
        # ImportError: no ProfileData loader on this jaxlib/container
        # (compat.load_profile_data) — same JSON error contract as a
        # missing xplane, never a raw traceback
        print(json.dumps({"error": str(e)}))
        return 1
    if args.intervals:
        with open(args.intervals, "w") as f:
            json.dump(device_intervals(args.trace_dir, data=data), f)
    out = dict(report if args.per_plane else summarize(report),
               xplane=report["xplane"])
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
