"""Observability: per-collective latency, stall attribution, wire counters.

The reference exports hardware counters over CSRs — per-collective active
cycles (`lpbk_latency`, hw/all_reduce.sv:92, read back at
sw/mlp_mpi_example_f32.cpp:100-106), stall attribution by cause
(`stall_host_in/out`, `stall_eth_in/out`, hw/all_reduce.sv:94-97), request
counters and BFP flit counters (hw/bfp_adapter.sv:705-729), plus a
DETAILED_PROFILE wall-clock bucket breakdown in the driver
(sw/mlp_mpi_example_f32.cpp:236-244,702-750).

On TPU the runtime hides queues, so stall attribution comes from the
issue/wait timeline (SURVEY.md §5): time blocked inside ``wait`` is
network-bound ("stall_collective"), time between a ticket's issue and its
wait call is overlapped compute ("overlap"), and wire bytes come from the
collective config, not sniffing.
"""

from __future__ import annotations

import json
import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class CollectiveStats:
    issued: int = 0
    completed: int = 0
    abandoned: int = 0        # inflight tickets dropped by recovery
    wire_bytes: int = 0
    raw_bytes: int = 0
    # running latency aggregates (O(1) memory — safe for million-step runs)
    latency_sum_s: float = 0.0
    latency_max_s: float = 0.0
    stall_s: float = 0.0      # blocked inside wait()  ("network-bound")
    overlap_s: float = 0.0    # issue->wait gap        ("compute overlapped")

    def record_latency(self, seconds: float) -> None:
        self.latency_sum_s += seconds
        self.latency_max_s = max(self.latency_max_s, seconds)

    def as_dict(self) -> Dict:
        n = self.completed
        return {
            "issued": self.issued,
            "completed": self.completed,
            "abandoned": self.abandoned,
            "wire_bytes": self.wire_bytes,
            "raw_bytes": self.raw_bytes,
            "compression_ratio": (self.raw_bytes / self.wire_bytes
                                  if self.wire_bytes else 1.0),
            "mean_latency_ms": (self.latency_sum_s / n * 1e3) if n else 0.0,
            "max_latency_ms": self.latency_max_s * 1e3,
            "stall_s": self.stall_s,
            "overlap_s": self.overlap_s,
        }


@dataclass
class RecoveryStats:
    """Fault/recovery accounting for the elastic loop (parallel.elastic).

    The reference has NOTHING here — its failure story is an undetected
    infinite hang (hw/README:3) — so these counters are the observable
    proof the gap is closed: every detected fault, every restart, and the
    mean-time-to-recovery all land in the same stats dump as the
    collective counters."""

    faults: Dict[str, int] = field(default_factory=lambda: defaultdict(int))
    recoveries: int = 0
    failed_recoveries: int = 0
    checkpoint_restores: int = 0
    mttr_sum_s: float = 0.0
    mttr_max_s: float = 0.0
    # bounded event log: [{step, kind, site, error, recovered_in_s}]
    events: List[Dict] = field(default_factory=list)
    max_events: int = 128

    def record_fault(self, kind: str, step: int, site: str = "",
                     error: str = "") -> Dict:
        self.faults[kind] += 1
        ev = {"step": step, "kind": kind, "site": site,
              "error": error[:200], "recovered_in_s": None}
        if len(self.events) < self.max_events:
            self.events.append(ev)
        return ev

    def record_recovery(self, seconds: float, *, restored: bool = False,
                        event: Dict = None) -> None:
        self.recoveries += 1
        if restored:
            self.checkpoint_restores += 1
        self.mttr_sum_s += seconds
        self.mttr_max_s = max(self.mttr_max_s, seconds)
        if event is not None:
            event["recovered_in_s"] = round(seconds, 4)

    def as_dict(self) -> Dict:
        n = self.recoveries
        return {
            "faults": dict(self.faults),
            "faults_total": sum(self.faults.values()),
            "recoveries": n,
            "failed_recoveries": self.failed_recoveries,
            "checkpoint_restores": self.checkpoint_restores,
            "mttr_mean_s": (self.mttr_sum_s / n) if n else 0.0,
            "mttr_max_s": self.mttr_max_s,
            "events": list(self.events),
        }


class Profiler:
    """Named wall-clock buckets (DETAILED_PROFILE equivalent) + collective
    stats. One instance per trainer/queue; cheap enough to leave on."""

    def __init__(self):
        self.buckets: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.collectives = CollectiveStats()
        self.recovery = RecoveryStats()

    @contextmanager
    def bucket(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.buckets[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> Dict:
        return {
            "buckets_s": dict(self.buckets),
            "counts": dict(self.counts),
            "collectives": self.collectives.as_dict(),
            "recovery": self.recovery.as_dict(),
        }

    def json_line(self) -> str:
        return json.dumps(self.report())
