"""Observability: per-collective latency, stall attribution, wire counters.

The reference exports hardware counters over CSRs — per-collective active
cycles (`lpbk_latency`, hw/all_reduce.sv:92, read back at
sw/mlp_mpi_example_f32.cpp:100-106), stall attribution by cause
(`stall_host_in/out`, `stall_eth_in/out`, hw/all_reduce.sv:94-97), request
counters and BFP flit counters (hw/bfp_adapter.sv:705-729), plus a
DETAILED_PROFILE wall-clock bucket breakdown in the driver
(sw/mlp_mpi_example_f32.cpp:236-244,702-750).

On TPU the runtime hides queues, so stall attribution comes from the
issue/wait timeline (SURVEY.md §5): time blocked inside ``wait`` is
network-bound ("stall_collective"), time between a ticket's issue and its
wait call is overlapped compute ("overlap"), and wire bytes come from the
collective config, not sniffing.

This module is now a thin facade over the structured telemetry plane
(`fpga_ai_nic_tpu.obs`): the aggregates below stay the O(1)-memory
summary every stats dump embeds, while ``Profiler.events`` (an
``obs.events.EventStream``) carries the individual spans/counters the
Perfetto timeline (`obs.timeline`) renders.  All counter mutation goes
through locked record_* methods — the elastic watchdog worker thread, XLA
callback threads and the trainer thread write these concurrently, and the
bare ``+=`` they replaced dropped updates under that interleaving.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..obs.events import EventStream


def _lock_field():
    # per-instance lock as a non-compared dataclass field (locks are
    # neither comparable nor picklable; stats dumps go through as_dict)
    return field(default_factory=threading.Lock, repr=False, compare=False)


@dataclass
class CollectiveStats:
    issued: int = 0
    completed: int = 0
    abandoned: int = 0        # inflight tickets dropped by recovery
    wire_bytes: int = 0
    raw_bytes: int = 0
    # running latency aggregates (O(1) memory — safe for million-step runs)
    latency_sum_s: float = 0.0
    latency_max_s: float = 0.0
    stall_s: float = 0.0      # blocked inside wait()  ("network-bound")
    overlap_s: float = 0.0    # issue->wait gap        ("compute overlapped")
    _lock: threading.Lock = _lock_field()

    # -- locked mutation (queue worker threads vs recovery thread) ----------

    def record_issue(self, raw_bytes: int = 0, wire_bytes: int = 0) -> None:
        with self._lock:
            self.issued += 1
            self.raw_bytes += raw_bytes
            self.wire_bytes += wire_bytes or raw_bytes

    def record_completion(self, latency_s: float, stall_s: float,
                          overlap_s: float) -> None:
        with self._lock:
            self.completed += 1
            self.latency_sum_s += latency_s
            self.latency_max_s = max(self.latency_max_s, latency_s)
            self.stall_s += stall_s
            self.overlap_s += overlap_s

    def record_abandoned(self, n: int = 1) -> None:
        with self._lock:
            self.abandoned += n

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self.latency_sum_s += seconds
            self.latency_max_s = max(self.latency_max_s, seconds)

    def as_dict(self) -> Dict:
        with self._lock:
            n = self.completed
            return {
                "issued": self.issued,
                "completed": self.completed,
                "abandoned": self.abandoned,
                "wire_bytes": self.wire_bytes,
                "raw_bytes": self.raw_bytes,
                "compression_ratio": (self.raw_bytes / self.wire_bytes
                                      if self.wire_bytes else 1.0),
                "mean_latency_ms": (self.latency_sum_s / n * 1e3) if n
                                   else 0.0,
                "max_latency_ms": self.latency_max_s * 1e3,
                "stall_s": self.stall_s,
                "overlap_s": self.overlap_s,
            }


@dataclass
class RecoveryStats:
    """Fault/recovery accounting for the elastic loop (parallel.elastic).

    The reference has NOTHING here — its failure story is an undetected
    infinite hang (hw/README:3) — so these counters are the observable
    proof the gap is closed: every detected fault, every restart, and the
    mean-time-to-recovery all land in the same stats dump as the
    collective counters."""

    faults: Dict[str, int] = field(default_factory=lambda: defaultdict(int))
    recoveries: int = 0
    failed_recoveries: int = 0
    checkpoint_restores: int = 0
    # live mesh-reshard recoveries (parallel.reshard): the first-tier
    # path that migrates the in-memory state to the surviving mesh shape
    # instead of restoring a checkpoint — tracked with its OWN MTTR
    # aggregates so the reshard-vs-restore claim is measurable from the
    # same stats dump
    reshards: int = 0
    mttr_sum_s: float = 0.0
    mttr_max_s: float = 0.0
    # single-tier recoveries only (see record_recovery): the *_n counts
    # are the matching mean denominators, NOT the occurrence counters
    # above (a reshard-then-restore recovery increments both occurrence
    # counters but neither MTTR aggregate)
    mttr_reshard_sum_s: float = 0.0
    mttr_reshard_max_s: float = 0.0
    mttr_reshard_n: int = 0
    mttr_restore_sum_s: float = 0.0
    mttr_restore_max_s: float = 0.0
    mttr_restore_n: int = 0
    # durability-plane counters (utils.checkpoint v2): peer repairs of
    # corrupt stored shards, absorbed save failures, emergency dumps
    ckpt_repairs: int = 0
    ckpt_repair_wire_bytes: int = 0
    ckpt_save_failures: int = 0
    emergency_dumps: int = 0
    # bounded event log: [{step, kind, site, error, recovered_in_s}]
    events: List[Dict] = field(default_factory=list)
    max_events: int = 128
    # faults recorded past max_events: the log truncates, the COUNT never
    # does — a dump with a full log must say what it left out
    events_dropped: int = 0
    _lock: threading.Lock = _lock_field()

    def record_fault(self, kind: str, step: int, site: str = "",
                     error: str = "") -> Dict:
        ev = {"step": step, "kind": kind, "site": site,
              "error": error[:200], "recovered_in_s": None}
        with self._lock:
            self.faults[kind] += 1
            if len(self.events) < self.max_events:
                self.events.append(ev)
            else:
                self.events_dropped += 1
        return ev

    def record_recovery(self, seconds: float, *, restored: bool = False,
                        resharded: bool = False,
                        event: Dict = None) -> None:
        # per-tier MTTR aggregates attribute the wall clock to the tier
        # that ALONE performed the recovery: a step that resharded and
        # then still needed a restore books its (multi-tier) duration
        # into neither — crediting it to both would corrupt exactly the
        # reshard-vs-restore comparison these aggregates exist to make.
        # The occurrence counters still count every tier that fired.
        with self._lock:
            self.recoveries += 1
            if restored:
                self.checkpoint_restores += 1
                if not resharded:
                    self.mttr_restore_sum_s += seconds
                    self.mttr_restore_max_s = max(self.mttr_restore_max_s,
                                                  seconds)
                    self.mttr_restore_n += 1
            if resharded:
                self.reshards += 1
                if not restored:
                    self.mttr_reshard_sum_s += seconds
                    self.mttr_reshard_max_s = max(self.mttr_reshard_max_s,
                                                  seconds)
                    self.mttr_reshard_n += 1
            self.mttr_sum_s += seconds
            self.mttr_max_s = max(self.mttr_max_s, seconds)
        if event is not None:
            event["recovered_in_s"] = round(seconds, 4)
            event["tier"] = ("reshard+restore" if resharded and restored
                             else "reshard" if resharded
                             else "restore" if restored else "retry")

    def record_failed_recovery(self) -> None:
        with self._lock:
            self.failed_recoveries += 1

    def record_ckpt_repair(self, wire_bytes: int = 0) -> None:
        """One stored shard healed from its peer mirror at restore time
        (utils.checkpoint peer repair; ``wire_bytes`` = the pair
        transfer program's exact payload)."""
        with self._lock:
            self.ckpt_repairs += 1
            self.ckpt_repair_wire_bytes += int(wire_bytes)

    def record_ckpt_save_failure(self) -> None:
        """A checkpoint save failed mid-sequence (disk-full / injected
        kill) and was absorbed — the commit protocol kept the directory
        restorable, and the next cadence save retries."""
        with self._lock:
            self.ckpt_save_failures += 1

    def record_emergency_dump(self) -> None:
        """The ladder exhausted and the live state was persisted as an
        emergency checkpoint ('dump before dying')."""
        with self._lock:
            self.emergency_dumps += 1

    def as_dict(self) -> Dict:
        with self._lock:
            n = self.recoveries
            nrs, nre = self.mttr_reshard_n, self.mttr_restore_n
            return {
                "faults": dict(self.faults),
                "faults_total": sum(self.faults.values()),
                "recoveries": n,
                "failed_recoveries": self.failed_recoveries,
                "checkpoint_restores": self.checkpoint_restores,
                "reshards": self.reshards,
                "ckpt_repairs": self.ckpt_repairs,
                "ckpt_repair_wire_bytes": self.ckpt_repair_wire_bytes,
                "ckpt_save_failures": self.ckpt_save_failures,
                "emergency_dumps": self.emergency_dumps,
                "mttr_mean_s": (self.mttr_sum_s / n) if n else 0.0,
                "mttr_max_s": self.mttr_max_s,
                "mttr_reshard_mean_s": (self.mttr_reshard_sum_s / nrs)
                                       if nrs else 0.0,
                "mttr_reshard_max_s": self.mttr_reshard_max_s,
                "mttr_restore_mean_s": (self.mttr_restore_sum_s / nre)
                                       if nre else 0.0,
                "mttr_restore_max_s": self.mttr_restore_max_s,
                "events": list(self.events),
                "events_dropped": self.events_dropped,
            }


class Profiler:
    """Named wall-clock buckets (DETAILED_PROFILE equivalent) + collective
    stats + the structured event stream underneath.  One instance per
    trainer/queue; cheap enough to leave on.

    Facade contract: ``buckets``/``counts``/``collectives``/``recovery``
    keep their pre-telemetry-plane shapes (every existing consumer — the
    chaos bench, the examples, the elastic loop — reads them unchanged);
    each ``bucket()`` additionally lands a span in ``self.events`` and
    ``report()`` gains an ``events`` summary with explicit
    ``events_dropped`` accounting."""

    def __init__(self, events: Optional[EventStream] = None,
                 capacity: int = 1 << 16):
        self.buckets: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.collectives = CollectiveStats()
        self.recovery = RecoveryStats()
        self.events = events if events is not None else EventStream(capacity)
        self._lock = threading.Lock()

    @contextmanager
    def bucket(self, name: str):
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.buckets[name] += dt
                self.counts[name] += 1
            self.events.emit("span", name, t_ns=t0_ns,
                             dur_ns=time.perf_counter_ns() - t0_ns)

    def report(self) -> Dict:
        with self._lock:
            buckets = dict(self.buckets)
            counts = dict(self.counts)
        return {
            "buckets_s": buckets,
            "counts": counts,
            "collectives": self.collectives.as_dict(),
            "recovery": self.recovery.as_dict(),
            "events": self.events.summary(),
        }

    def json_line(self) -> str:
        return json.dumps(self.report())

    def dump_events(self, path: str) -> str:
        """JSONL sink for the underlying stream (obs.timeline input)."""
        return self.events.dump_jsonl(path)
