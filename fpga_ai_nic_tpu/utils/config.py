"""Unified configuration system.

The reference scatters configuration across compile-time SystemVerilog macros
(`BFP_EN`, hw/all_reduce.sv:12-13), SV parameters (BUF_SIZE=512, NUM_FP=16,
MANT_SIZE=8; hw/all_reduce.sv:101-103,746), CLI positional args
(sw/mlp_mpi_example_f32.cpp:269-296), env vars (sw/run.sh:12-15) and side
files (hostlist / ikl_config, sw/README:1-3).  Here everything is a typed
dataclass with a single CLI entry point (``from_flags``).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple


@dataclass(frozen=True)
class BFPConfig:
    """Block-floating-point wire format.

    Mirrors the reference codec's parameterization (NUM, EXPONENT_SIZE,
    MANTISSA_SIZE, NX_MODE — hw/bf16_to_bfp_core.sv:30-34) with TPU-friendly
    storage: per-block int8 mantissas plus one int8 power-of-two scale
    exponent, value = mantissa * 2**scale_exp.  With block_size=16 and 8-bit
    mantissas this is bit-rate-identical to the reference's 136b-per-512b
    frame (hw/bfp_adapter.sv:63-77): 3.76x over f32, 1.88x over bf16.

    rounding:
      - "nearest": round-to-nearest-even (default; better accuracy than HW)
      - "rtz":     truncate toward zero, mirroring the RTL barrel-shifter
                   truncation (hw/bf16_to_bfp_core.sv:108-125) for parity
                   tests against the golden model.
    """

    block_size: int = 16          # NUM_FP (hw/all_reduce.sv:746)
    mantissa_bits: int = 8        # MANT_SIZE (hw/all_reduce.sv:746)
    rounding: str = "nearest"     # "nearest" | "rtz"
    # codec backend for the ring's per-hop encode/decode:
    #   "xla":    ops.bfp (block = consecutive elements, the reference's
    #             flat16 grouping) — the default: bit-exact vs
    #             ops.ring_golden on every platform.
    #   "pallas": ops.bfp_pallas (block = lane column, elements LANES
    #             apart) — the fused-kernel fast path for TPU.
    #   "auto":   pallas on TPU when the payload tiles onto (block, 128)
    #             lanes, xla elsewhere.
    # Every codec is bit-exact vs ops.bfp_golden under its own layout, but
    # the *block partition* differs between xla and pallas, so cross-codec
    # results differ by quantization grouping (same wire bytes, same error
    # bound).  "xla" stays the default so golden-compare guarantees hold
    # unchanged on TPU; opt into "auto"/"pallas" for wire-path speed.
    codec: str = "xla"

    def __post_init__(self) -> None:
        assert self.block_size >= 2 and self.block_size & (self.block_size - 1) == 0
        assert 2 <= self.mantissa_bits <= 8
        assert self.rounding in ("nearest", "rtz")
        assert self.codec in ("auto", "xla", "pallas")

    @property
    def compression_ratio_vs_f32(self) -> float:
        raw = 32 * self.block_size
        packed = self.mantissa_bits * self.block_size + 8
        return raw / packed


@dataclass(frozen=True)
class OptimizerSpec:
    """STATIC shape of a fused in-kernel optimizer — what the Pallas ring
    kernels specialize on (state operand count, update formula), as
    opposed to the hyperparameters, which ride the kernel as SMEM scalars
    (``optim.fused_hyperparams``) so an lr/schedule change never
    recompiles.  The reference bakes even the lr into RTL
    (hw/weight_update.sv:439-452); we bake only the FORMULA.

    kinds: "sgd" (stateless), "momentum" (1 state vector m),
    "adamw" (2 state vectors m, v).  Weight decay / schedules / bias
    correction are all dynamic scalars, never spec."""

    kind: str = "sgd"             # "sgd" | "momentum" | "adamw"

    def __post_init__(self) -> None:
        assert self.kind in ("sgd", "momentum", "adamw"), self.kind

    @property
    def state_keys(self) -> Tuple[str, ...]:
        """Optimizer-state slot names, in kernel operand order."""
        return {"sgd": (), "momentum": ("m",),
                "adamw": ("m", "v")}[self.kind]

    @property
    def n_state(self) -> int:
        return len(self.state_keys)

    @classmethod
    def from_optimizer(cls, opt: "OptimizerConfig") -> "OptimizerSpec":
        return cls(kind=opt.kind)


@dataclass(frozen=True)
class CollectiveConfig:
    """All-reduce engine configuration.

    slice_elems generalizes the reference's fixed 32 KiB ring slice
    (BUF_SIZE=512 cache lines, hw/all_reduce.sv:101-103); max_inflight
    mirrors the 8-deep collective queue with round-robin done IDs
    (hw/all_reduce.sv:1228,1373; readme.pdf §2.1).

    impl:
      - "xla":  lax.psum_scatter / all_gather — XLA schedules and overlaps.
      - "ring": explicit ppermute ring (the st_eth_t analogue); required for
                on-the-wire BFP compression.
    """

    impl: str = "xla"             # "xla" | "ring"
    compression: Optional[BFPConfig] = None
    # named gradient-compression codec (fpga_ai_nic_tpu.compress registry:
    # "bfp" | "topk" | "int8" | any registered plugin) with constructor
    # options as a (key, value) pair tuple — kept hashable so the frozen
    # config stays usable as a cache key:
    #   CollectiveConfig(impl="ring", codec="topk",
    #                    codec_opts=(("k", 32), ("bucket_elems", 256)))
    # codec=None + compression=BFPConfig(...) is the legacy BFP spelling
    # (still fully supported); codec="bfp" may combine with compression=
    # to reuse a BFPConfig.  Unknown names fail HERE, at construction,
    # with the registered list — not at first collective trace.
    #
    # codec="auto" defers the choice to the trace-time autotuner
    # (fpga_ai_nic_tpu.tune): the trainer resolves codec, pipeline_depth,
    # bucket_elems and topology ONCE at construction from the ring_cost
    # model parameterized by calibrated (banked-artifact) rates, then
    # trains on the resolved static config — no trace-time capture, and
    # the chosen plan is banked into obs_static_metrics() for obs-gate
    # to diff across PRs.  See docs/TUNING.md.
    codec: Optional[str] = None
    codec_opts: Tuple[Tuple[str, Any], ...] = ()
    # launch-ahead depth D of the fused Pallas ring's slice schedule
    # (ops.ring_pallas pipeline_depth: encode slice g+D while D RDMAs are
    # in flight).  None = the kernel's default (_PIPE_DEPTH, capped by
    # the slice plan); the autotuner owns it under codec="auto".  A
    # schedule choice, never a numerics choice.
    pipeline_depth: Optional[int] = None
    # collective topology over the (flat) axis:
    #   "flat":  the 1-D ring (the reference's only shape).
    #   "hier":  2-stage hierarchical (intra x inter) collectives
    #            (ops.ring_hier): full-precision reduce over the declared
    #            FAST intra factor first, then the codec ring only on the
    #            SLOW inter hop — EQuARX's quantize-only-the-slow-phase
    #            trick (arXiv:2506.17615).  Requires impl="ring" and
    #            intra_size > 1 dividing the axis size; codec applies to
    #            the inter hop ONLY (graftlint J9 pins the intra hop
    #            codec-free and both hops' bytes to the plan).
    topology: str = "flat"
    # declared intra/inter factorization of the flat axis for
    # topology="hier": the axis's n devices are ni = intra_size
    # consecutive ranks per fast group (device d -> group d // ni,
    # position d % ni), matching a dp x tp-style mesh flattened
    # major-to-minor.  0 = undeclared (required for "hier" unless the
    # autotuner owns the choice under codec="auto").
    intra_size: int = 0
    # run the compressed ring through the single fused Pallas kernel
    # (ops.ring_pallas: encode-into-hop with RDMA overlap) instead of the
    # separate encode/ppermute/decode XLA ops.  Implies the lane-layout
    # ("pallas") block partition; payloads are padded to (block*128)-lane
    # tiles per device chunk (ops.fused_update.pad_multiple); large
    # payloads stream HBM->VMEM through a fixed working set (resident /
    # streaming / segmented routing is automatic by size).
    #
    # Validation status: bit-exactness and the full flow-control protocol
    # (neighbor barrier + credit window) are exercised on every CI run —
    # the discharge-interpreter sweep and the threaded-interpreter
    # TestFlowControl battery in tests/test_ring_pallas.py — but the
    # kernels have NOT yet run on multi-chip ICI hardware.  Before first
    # production use on a real multi-chip mesh, run the hardware canary
    # (tools/first_contact.py stage 'canary', or loopback_microbench /
    # loopback_gather_microbench directly) on one chip of that platform.
    fused_kernel: bool = False
    # fuse the optimizer update into the gradient reduce-scatter (the
    # reference's weight_update.sv trick + ZeRO-1 weight-update sharding):
    # each replica updates its owned master shard and optimizer-state
    # shard AS the final-hop decode of that shard retires, and the
    # all-gather then distributes fresh params.  With fused_kernel=True
    # on TPU the update runs INSIDE the depth-D Pallas ring kernel
    # (ops.ring_pallas fused-opt variants: state shards are donated
    # kernel operands, hyperparams are SMEM scalars — an lr change never
    # recompiles); otherwise the same update formula
    # (optim.fused_apply_flat, bit-specified by the numpy golden twins in
    # optim.py) runs fused into the step right after the reduce.
    # Combines with integrity_check since PR 12: the EXACT wire-checksum
    # tier (ops.integrity) verifies the encoded ring frames with no
    # tolerance band, so the fused path carries integrity coverage too —
    # on the shared-formula routes (hier / off-TPU / n==1) a tripped
    # verdict gates the update in-graph (pre-step state preserved); on
    # the in-kernel TPU route the kernel accumulates the frame checksums
    # itself and a tripped conservation verdict invalidates the step
    # (check_step_diag raises WireIntegrityError -> the elastic ladder
    # restores/reshards; the donated in-kernel state is discarded with
    # the step).  The trainers still reject clip_norm (a global-norm
    # clip needs a barrier between the reduce and the update, which is
    # exactly the exposed optimizer time this mode removes).  See
    # docs/FUSED_OPTIMIZER.md.
    fused_optimizer: bool = False
    slice_elems: int = 8192       # 32 KiB of f32, matching BUF_SIZE=512 CLs
    # unroll the n-1 ring-hop loop at trace time: marginally better codegen
    # for tiny rings, O(n) compile-time blowup for real ones — rolled
    # lax.fori_loop is the default (hop count is data-independent either way)
    unroll_hops: bool = False
    max_inflight: int = 8
    # bucketed (DDP-style) all-reduce: min elements per bucket.  The
    # reference's granularity is one bucket per layer (one all_reduce()
    # call per bwd layer, sw/mlp_mpi_example_f32.cpp:753); 4M f32 = 16 MiB
    # amortizes per-collective latency while keeping backward overlap.
    bucket_elems: int = 4 * 1024 * 1024
    # collective integrity guard, two tiers computed inside the jitted
    # step:
    #   value tier (runtime.chaos): per-chunk checksums across the
    #     gradient reduce-scatter plus a NaN/inf count against a
    #     codec-derived tolerance band — the gross-corruption tripwire
    #     (NaN, flipped exponent bits, runaway scale).
    #   exact tier (ops.integrity, PR 12): bit-exact checksums over the
    #     ENCODED frames of every ring hop (flat and hier), verified by
    #     conservation — no tolerance band, so the FINITE wrong-value
    #     class (a flipped mantissa bit that decodes to a plausible
    #     number) trips too.  ``wire_ok`` lands in the step diag; the
    #     exact tier only exists on impl='ring' (XLA collectives own
    #     their own wire).
    # A tripped verdict GATES the optimizer update in-graph where the
    # pre-step state is still materialized (all unfused routes + the
    # shared-formula fused_optimizer routes) and surfaces the verdict in
    # the step's metrics dict for the elastic loop to act on; the
    # in-kernel fused TPU route surfaces the verdict only (its state is
    # donated — recovery is the elastic restore/reshard ladder).
    # integrity_tol=None derives the value-tier tolerance from the wire
    # format (chaos.integrity_tol): reassociation-only for f32,
    # quantization-bounded for BFP.
    integrity_check: bool = False
    integrity_tol: Optional[float] = None

    def __post_init__(self) -> None:
        assert self.impl in ("xla", "ring")
        if ((self.compression is not None or self.codec is not None)
                and self.impl != "ring"):
            raise ValueError("gradient compression requires impl='ring' "
                             "(XLA collectives cannot compress on the wire)")
        assert self.topology in ("flat", "hier"), self.topology
        assert self.pipeline_depth is None or self.pipeline_depth >= 1
        assert self.intra_size >= 0, self.intra_size
        if self.topology == "hier":
            if self.impl != "ring":
                raise ValueError(
                    "topology='hier' requires impl='ring': the 2-stage "
                    "intra/inter schedule is an explicit-ring program "
                    "(ops.ring_hier); XLA owns its own psum topology")
            if self.fused_kernel:
                raise ValueError(
                    "topology='hier' cannot ride fused_kernel yet: the "
                    "Pallas ring kernels drive the FULL axis's neighbor "
                    "permutation; run the separate-op hierarchical ring "
                    "(fused_kernel=False — fused_optimizer still works "
                    "through the shared update formula)")
            if self.intra_size <= 1 and self.codec != "auto":
                raise ValueError(
                    "topology='hier' needs a declared intra/inter "
                    "factorization: set intra_size > 1 (the fast-hop "
                    "group size; must divide the axis size), or use "
                    "codec='auto' and let the autotuner own it")
        if self.codec == "auto":
            # deferred to the trace-time autotuner (fpga_ai_nic_tpu.tune,
            # resolved once at trainer construction); nothing to validate
            # against the codec registry yet
            if self.fused_kernel:
                raise ValueError(
                    "codec='auto' cannot combine with fused_kernel=True: "
                    "the fused-capability check needs a concrete codec — "
                    "pick one, or let the tuner run the separate-op ring")
            if self.compression is not None:
                raise ValueError(
                    "codec='auto' conflicts with compression= (a "
                    "BFPConfig parameterizes the 'bfp' codec only)")
        if self.codec is not None:
            if not isinstance(self.codec_opts, tuple):
                raise ValueError("codec_opts must be a tuple of (key, "
                                 f"value) pairs, got {self.codec_opts!r}")
            if self.compression is not None and self.codec != "bfp":
                raise ValueError(
                    f"codec={self.codec!r} conflicts with compression= "
                    "(a BFPConfig): the BFPConfig parameterizes the 'bfp' "
                    "codec only")
        if self.codec == "auto":
            return      # registry resolution happens at autotune time
        if self.codec is not None or self.fused_kernel:
            if self.fused_kernel and (self.impl != "ring"
                                      or (self.compression is None
                                          and self.codec is None)):
                raise ValueError("fused_kernel is the compressed-ring "
                                 "Pallas path: requires impl='ring' and a "
                                 "codec (codec=/compression=)")
            # fail fast on unknown names / bad options, with the
            # registered-codec list in the error (compress.get_codec);
            # import is lazy so constructing codec-less configs never
            # touches the compress package, and one resolve serves both
            # the name validation and the fused-capability check
            from ..compress import resolve
            c = resolve(self)
            if self.fused_kernel and not c.supports_fused:
                raise ValueError(
                    f"codec {c.name!r} cannot ride the fused Pallas ring "
                    "(its wire frames are BFP int8 mantissa+scale tiles); "
                    "use the separate-op ring (fused_kernel=False) or "
                    "codec='bfp'")


@dataclass(frozen=True)
class OptimizerConfig:
    """Fused optimizer. The reference hard-codes SGD lr=0.1 in RTL
    (a = 0xBDCCCCCD = -0.1, hw/weight_update.sv:439-446); we make it a flag
    and add momentum/adamw for the larger model configs."""

    kind: str = "sgd"             # "sgd" | "momentum" | "adamw"
    learning_rate: float = 0.1
    momentum: float = 0.9
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    # learning-rate schedule (the reference cannot schedule at all — its lr
    # is an RTL constant; see optim.learning_rate_at)
    schedule: str = "constant"    # "constant" | "cosine" | "linear"
    warmup_steps: int = 0
    decay_steps: int = 0          # horizon for cosine/linear (incl. warmup)
    min_lr_ratio: float = 0.0     # floor as a fraction of learning_rate
    # global-norm gradient clipping (None = off).  The norm is computed
    # over the FULL flat gradient (psum across master-sharding axes), so
    # sharded and single-device training clip identically.
    clip_norm: Optional[float] = None

    def __post_init__(self) -> None:
        assert self.kind in ("sgd", "momentum", "adamw")
        # 0.0 would silently zero every gradient; "off" is None
        assert self.clip_norm is None or self.clip_norm > 0, self.clip_norm
        assert self.schedule in ("constant", "cosine", "linear")
        if self.schedule != "constant":
            assert self.decay_steps > self.warmup_steps >= 0, (
                "cosine/linear schedules need decay_steps > warmup_steps")


@dataclass(frozen=True)
class AdaptConfig:
    """Online plan adaptation (fpga_ai_nic_tpu.tune.adapt): the drift
    observatory that closes the autotune loop WHILE the job runs.

    The autotuner (codec="auto") resolves a plan once at construction
    from banked/live-calibrated rates; this config arms the runtime half:
    a bounded candidate set (the top ``n_candidates`` runner-up plans
    from the same argmin grid) is built AND traced up front, each step's
    measured wall time is joined against the active plan's modeled stage
    times into drift residuals (streamed as ``tune.drift.*`` metrics and
    an "attribution" Perfetto lane), and a host-side CUSUM detector with
    hysteresis swaps to a pre-compiled alternate plan at a step boundary
    when the modeled-vs-measured regime shifts for good (SparCML's
    break-even moving with the effective link rate).  Everything here is
    HOST-side and trace-time static: detection reads banked metrics,
    never runs inside jit (R2/R4), and a switch causes ZERO new traces
    (graftlint J13).  docs/TUNING.md carries the full contract."""

    enabled: bool = False
    # run the startup mesh microbenches (tune.adapt.live_calibrate) and
    # feed the measured rates into plan resolution at the `live`
    # provenance tier (above every banked artifact; dryrun-flagged on a
    # CPU mesh — the honesty rules of tune.calibration apply unchanged)
    live_calibration: bool = True
    # bounded pre-compiled candidate set: the argmin winner plus the
    # best runner-up plans from distinct (codec, topology) groups of the
    # same grid, every one traced at construction
    n_candidates: int = 3
    # drift plane: EWMA smoothing of the per-step residuals, the
    # per-step relative excess considered drift (CUSUM slack), the
    # accumulated-drift trip threshold, warmup steps spent establishing
    # the measured step-time baseline (re-entered after every switch),
    # and the post-trip hysteresis window during which the detector
    # stays disarmed (no flapping)
    ewma_alpha: float = 0.25
    drift_rel: float = 0.75
    cusum_threshold: float = 3.0
    warmup_steps: int = 3
    cooldown_steps: int = 8

    def __post_init__(self) -> None:
        assert 0.0 < self.ewma_alpha <= 1.0, self.ewma_alpha
        assert self.drift_rel > 0, self.drift_rel
        assert self.cusum_threshold > 0, self.cusum_threshold
        assert self.warmup_steps >= 1, self.warmup_steps
        assert self.cooldown_steps >= 0, self.cooldown_steps
        if self.enabled and self.n_candidates < 2:
            raise ValueError(
                "AdaptConfig.enabled needs n_candidates >= 2: a "
                "candidate set of one has nothing to switch to — the "
                "detector would observe drift it can never act on")


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh. The reference supports only a 1-D ring of FPGAs
    (data parallelism, sw/setup_route.sh); we generalize to the full
    dp x fsdp x tp x sp x ep product over ICI."""

    dp: int = 1                   # data parallel (the reference's only axis)
    fsdp: int = 1                 # ZeRO / fully-sharded data parallel
    tp: int = 1                   # tensor parallel
    sp: int = 1                   # sequence/context parallel (ring attention)
    pp: int = 1                   # pipeline parallel (GPipe microbatch ring)
    ep: int = 1                   # expert parallel (MoE all-to-all)

    @property
    def nproc(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.pp * self.ep

    def axis_sizes(self) -> Tuple[Tuple[str, int], ...]:
        return (("dp", self.dp), ("fsdp", self.fsdp), ("tp", self.tp),
                ("sp", self.sp), ("pp", self.pp), ("ep", self.ep))


@dataclass(frozen=True)
class MLPConfig:
    """The reference benchmark model: N fully-connected layers of equal width
    trained with softmax cross-entropy (sw/mlp_mpi_example_f32.cpp:284-296,
    canonical 10x2048x2048 f32, sw/run.sh:16)."""

    layer_sizes: Tuple[int, ...] = (2048,) * 11   # 10 layers of 2048x2048
    num_classes: Optional[int] = None             # defaults to last width
    dtype: str = "float32"
    fuse_bias: bool = True

    @property
    def n_layers(self) -> int:
        return len(self.layer_sizes) - 1


@dataclass(frozen=True)
class TrainConfig:
    """Training-loop configuration (ref driver CLI: iters MB fuse_type type
    bn bk bc C1..CN, sw/mlp_mpi_example_f32.cpp:269-296)."""

    iters: int = 20               # canonical run: 20 (sw/run.sh:16)
    global_batch: int = 5376      # canonical run: MB 5376 (sw/run.sh:16)
    accum_steps: int = 1          # gradient accumulation microbatches
    mesh: MeshConfig = field(default_factory=MeshConfig)
    collective: CollectiveConfig = field(default_factory=CollectiveConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    zero1: bool = True            # sharded optimizer state + fused gather
    seed: int = 0
    # in-graph training metrics (obs.metrics): grad norm, codec declared-
    # vs-observed error, EF residual mass, integrity drift — tapped to
    # the ambient MetricsSink via pure_callback.  TRACE-TIME gate: False
    # (the default) compiles the step to HLO bit-identical to a build
    # with no obs plumbing at all (tests/test_obs.py asserts this).
    obs_metrics: bool = False
    # online plan adaptation (tune.adapt.AdaptiveTrainer): live startup
    # calibration + modeled-vs-measured drift attribution + recompile-
    # free plan switching.  Host-side and off by default; see AdaptConfig.
    adapt: AdaptConfig = field(default_factory=AdaptConfig)

    @property
    def per_device_batch(self) -> int:
        n = self.mesh.nproc
        assert self.global_batch % n == 0, (self.global_batch, n)
        return self.global_batch // n


def coerce_value(T: Any, v: str) -> Any:
    """Parse a flag string as type T (bool truthy words, int/float/str,
    comma-separated int tuples).  Shared by from_flags and the example
    drivers' --model.* overlays."""
    if T is bool:
        return v.lower() in ("1", "true", "yes", "on")
    if T in (int, float, str):
        return T(v)
    if T is tuple:     # comma-separated ints, e.g. --model.layer_sizes=64,64
        return tuple(int(p) for p in v.split(",") if p)
    raise TypeError(f"cannot coerce flag value {v!r} to {T}")


_coerce = coerce_value


def from_flags(cls: Any, argv: Sequence[str]) -> Any:
    """Build a (possibly nested) config dataclass from --dotted.key=value
    flags, e.g. ``from_flags(TrainConfig, ["--mesh.dp=4", "--iters=100"])``."""
    cfg = cls()
    for arg in argv:
        if not arg.startswith("--"):
            raise ValueError(f"flags must look like --key=value, got {arg!r}")
        key, _, val = arg[2:].partition("=")
        path = key.split(".")
        try:
            cfg = _replace_path(cfg, path, val)
        except (ValueError, TypeError) as e:
            raise ValueError(f"--{key}={val}: {e}") from e
    return cfg


def _declared_type(cfg: Any, name: str) -> Any:
    """The field's annotation with Optional[...] unwrapped."""
    import typing
    T = typing.get_type_hints(type(cfg)).get(name)
    args = [a for a in typing.get_args(T) if a is not type(None)]
    return args[0] if len(args) == 1 else T


def _replace_path(cfg: Any, path: Sequence[str], val: str) -> Any:
    name, rest = path[0], path[1:]
    fields = {f.name: f for f in dataclasses.fields(cfg)}
    if name not in fields:
        raise ValueError(f"unknown config field {name!r} on {type(cfg).__name__}")
    cur = getattr(cfg, name)
    T = _declared_type(cfg, name) if cur is None else type(cur)
    if rest:
        if cur is None:
            if not dataclasses.is_dataclass(T):
                raise ValueError(f"{name} is not a nested config")
            # Optional nested config defaulting to None (e.g.
            # collective.compression): setting any sub-field turns it on
            # with defaults for the rest
            cur = T()
        new = _replace_path(cur, rest, val)
    elif dataclasses.is_dataclass(T):
        raise ValueError(f"{name} is a nested config; set a sub-field "
                         f"(...{name}.<field>=...)")
    elif cur is not None:
        new = coerce_value(T, val)
    else:
        # Optional scalar with a None default: the live value carries no
        # type, so coerce against the *declared* annotation — e.g.
        # '--num_classes=10' must become int 10, not whatever a literal
        # parse guesses.
        import typing
        new = coerce_value(typing.get_origin(T) or T, val)
    return dataclasses.replace(cfg, **{name: new})
