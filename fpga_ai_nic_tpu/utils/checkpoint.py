"""Checkpoint / resume — absent in the reference (SURVEY.md §5:
"Checkpoint / resume: none anywhere"), required by the larger BASELINE
configs (Llama-3 8B ZeRO-1 with BFP optimizer-state compression).

Two layers:
- ``save/restore``: orbax-backed full TrainState checkpointing.
- ``compress_state/decompress_state``: optional BFP compression of the f32
  master/optimizer shards (BASELINE.json config 5) using the native C++
  codec when available (runtime.native), else the numpy golden model —
  4 bytes -> ~1.06 bytes per element at a bounded quantization error.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..ops import bfp_golden
from ..runtime import native
from .config import BFPConfig


def _codec():
    if native.available():
        return native.bfp_encode, native.bfp_decode
    return (lambda x, b, m, r: bfp_golden.bfp_encode(x, b, m, r),
            lambda mant, se, b: bfp_golden.bfp_decode(mant, se, b))


def compress_array(x: np.ndarray, cfg: BFPConfig) -> Dict[str, Any]:
    enc, _ = _codec()
    flat = np.ascontiguousarray(x, np.float32).reshape(-1)
    pad = (-flat.shape[0]) % cfg.block_size
    if pad:
        flat = np.pad(flat, (0, pad))
    mant, scale = enc(flat, cfg.block_size, cfg.mantissa_bits, cfg.rounding)
    return {"mant": mant, "scale": scale, "shape": np.asarray(x.shape),
            "pad": np.asarray(pad), "block": np.asarray(cfg.block_size),
            "dtype": str(x.dtype)}


def decompress_array(blob: Dict[str, Any]) -> np.ndarray:
    _, dec = _codec()
    mant = np.asarray(blob["mant"], np.int8)
    out = dec(mant, np.asarray(blob["scale"], np.int8), int(blob["block"]))
    pad = int(blob["pad"])
    if pad:
        out = out[:-pad]
    return out.reshape(tuple(int(d) for d in np.asarray(blob["shape"]))).astype(
        blob["dtype"] if isinstance(blob["dtype"], str) else str(blob["dtype"]))


class Checkpointer:
    """Orbax-backed checkpoint manager with optional BFP-compressed
    optimizer/master state.

    ``async_save=True`` writes in a background thread (orbax
    AsyncCheckpointer): ``save`` returns as soon as the host copy is
    snapshotted, so checkpoint IO overlaps the next training steps; call
    ``wait_until_finished()`` (or just the next ``save``, which waits on
    the previous one) before reading the files.  Caveat: with ``compress``
    set, the BFP encode of the master/optimizer shards still runs
    synchronously inside ``save`` — only the file IO overlaps — so for
    GB-scale compressed state the async win is the write, not the
    encode."""

    def __init__(self, directory: str,
                 compress: Optional[BFPConfig] = None,
                 async_save: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.compress = compress
        self._ckptr = (ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
                       if async_save else ocp.PyTreeCheckpointer())

    _LAYOUT_FILE = "layer_layout.json"

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def _layout_path(self) -> str:
        return os.path.join(self.directory, self._LAYOUT_FILE)

    def save_layout(self, layout: Dict[str, Any]) -> Dict[str, Any]:
        """Record how the flat master bytes are ordered (e.g. the
        interleaved-1F1B layer permutation: layers_order / pp /
        virtual_stages).  A checkpoint that carries a layout sidecar can
        only be restored by a caller that declares a MATCHING layout —
        ``restore`` enforces it — so bytes can never be silently
        reinterpreted under a different pp/v/schedule."""
        with open(self._layout_path(), "w") as f:
            json.dump(layout, f)
        return layout

    def saved_layout(self) -> Optional[Dict[str, Any]]:
        if os.path.exists(self._layout_path()):
            with open(self._layout_path()) as f:
                return json.load(f)
        return None

    def _check_layout(self, expect: Optional[Dict[str, Any]]) -> None:
        saved = self.saved_layout()
        if saved is None and expect is None:
            return
        if saved is None:
            raise ValueError(
                f"restore declared layout {expect} but the checkpoint at "
                f"{self.directory} has no {self._LAYOUT_FILE} sidecar — it "
                "was saved in plain model order; drop expect_layout or "
                "re-save with save_layout()")
        if expect is None:
            raise ValueError(
                f"checkpoint at {self.directory} carries a layout sidecar "
                f"{saved} (its flat masters are NOT in model order); pass "
                "expect_layout= with the run's matching "
                "pp/virtual_stages/schedule to restore()")
        mismatched = {k: (saved.get(k), expect.get(k))
                      for k in set(saved) | set(expect)
                      if saved.get(k) != expect.get(k)}
        if mismatched:
            raise ValueError(
                "checkpoint layout mismatch (saved vs requested): "
                f"{mismatched} — restoring these bytes under the requested "
                "pp/virtual_stages/schedule would silently permute layers")

    def save(self, step: int, state,
             layout: Optional[Dict[str, Any]] = None) -> str:
        """Persist a trainer state.  TRAINER STATES (NamedTuples) carrying
        a flat master copy (w_own / w_master) drop their working ``params``
        tree: every trainer's ``restore_state`` rematerializes params from
        the masters, so persisting both would double checkpoint size (and
        wipe out the BFP compression win for bf16 models).  Plain dicts are
        saved verbatim — the masters-only heuristic never applies to user
        payloads whose keys merely resemble a trainer state's."""
        is_trainer_state = hasattr(state, "_asdict")
        tree = dict(state._asdict()) if is_trainer_state else state
        if is_trainer_state and "params" in tree and (
                "w_own" in tree or "w_master" in tree):
            tree = {k: v for k, v in tree.items() if k != "params"}
        tree = jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
        if self.compress is not None and isinstance(tree, dict):
            for key in ("w_own", "w_master"):
                if key in tree:
                    tree[key] = compress_array(tree[key], self.compress)
            if "opt_state" in tree:
                tree["opt_state"] = {
                    k: compress_array(v, self.compress)
                    for k, v in tree["opt_state"].items()}
        path = self._path(step)
        self._ckptr.save(path, tree, force=True)
        if layout is not None:
            self.save_layout(layout)
        elif os.path.exists(self._layout_path()):
            # a plain-order save must not inherit an earlier save's layout
            # sidecar: restore() would then demand (and validate against)
            # a layout these bytes are not in — the exact silent-permute
            # hazard the sidecar exists to prevent
            os.remove(self._layout_path())
        return path

    def restore(self, step: int,
                expect_layout: Optional[Dict[str, Any]] = None):
        self._check_layout(expect_layout)
        tree = self._ckptr.restore(self._path(step))
        if self.compress is not None:
            for key in ("w_own", "w_master"):
                if key in tree and isinstance(tree[key], dict):
                    tree[key] = decompress_array(tree[key])
            if "opt_state" in tree:
                tree["opt_state"] = {
                    k: decompress_array(v) if isinstance(v, dict) else v
                    for k, v in tree["opt_state"].items()}
        return tree

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed to disk."""
        if hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        # ignore orbax atomic-write temp dirs (step_N.orbax-checkpoint-tmp-*)
        # left behind by an interrupted save — this is the crash-recovery path
        steps = []
        for d in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)", d)
            if m:
                steps.append(int(m.group(1)))
        return max(steps) if steps else None
