"""Durable-state integrity — audited, crash-consistent, peer-repairable
checkpointing (the hardened LAST recovery tier; docs/DURABILITY.md).

Checkpoint / resume is absent in the reference (SURVEY.md §5:
"Checkpoint / resume: none anywhere").  Earlier revisions backed this
module with orbax; the durability plane v2 replaces that black box with
an explicit, auditable store, because every property the recovery
ladder leans on has to be *provable*:

  manifest     every ``save`` writes per-leaf (and per-shard) EXACT
               checksums over the stored representation — post-BFP-
               compress, the same odd-weighted u32 word sums the wire
               plane uses (`ops.integrity` / `compress.golden`), bit-
               exact with no tolerance band — committed atomically with
               the step bytes.
  commit       ``save`` is an explicit file-op sequence (the opstream
               emitter discipline applied to the filesystem): all files
               land in a ``step_N.tmp-write`` dir — leaves, layout
               sidecar, manifest — and ONE ``os.replace`` publishes the
               step.  Truncated at ANY op prefix, restore yields exactly
               the previous verified step or exactly the new one (the
               crash-point sweep in tests/test_checkpoint.py proves it
               exhaustively; ``op_hook`` is the sweep's seam).
  audit        every restore path re-checksums every leaf against the
               manifest before handing bytes to a trainer.  A single
               flipped stored bit can never restore silently (frozen as
               graftlint J14 — zero waivers, the J12 discipline applied
               to disk).
  peer repair  with ``mirror=True`` each ZeRO-1 shard is also stored
               under its dp PEER ((j+1) % n — the redundancy the
               replicated-params plane gives up when checkpoints persist
               masters only).  A corrupt primary shard is re-fetched
               from the peer via a reshard-style single-pair ppermute
               transfer program whose wire bytes equal EXACTLY the shard
               bytes (J8-style accounting, checked by J14), verified
               against the manifest, and healed in place.
  walk-back    ``restore_latest_verified`` falls back past corrupt/torn
               steps to the previous VERIFIED step — and REFUSES
               (CheckpointIntegrityError) when no clean source exists.
               It never silently restores damaged bytes.

``compress_state``-layer helpers (``compress_array`` /
``decompress_array``) are unchanged: optional BFP compression of the
f32 master/optimizer shards (BASELINE.json config 5) using the native
C++ codec when available (runtime.native), else the numpy golden model —
4 bytes -> ~1.06 bytes per element at a bounded quantization error.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np

from ..ops import bfp_golden
from ..runtime import native
from .config import BFPConfig

__all__ = [
    "Checkpointer", "CheckpointIntegrityError", "AuditReport", "FileOp",
    "compress_array", "decompress_array", "bytes_checksum", "peer_fetch",
    "pair_transfer_fn", "MANIFEST_FILE", "RESTORE_SURFACES",
    "npy_data_offset", "flip_stored_bit",
]

MANIFEST_FILE = "manifest.json"
_FORMAT = 2
_ALGO = "odd-weighted-u32-word-sum/v1"
# arrays below this size are never shard-split (the split exists for
# per-shard peer repair of the big flat masters, not for scalars)
_MIN_SHARD_BYTES = 512

# Every restore entrypoint in the tree.  graftlint J14 proves each one
# audits (a corrupted byte must refuse/repair, never restore silently);
# adding a path here without audit coverage is a J14 finding, and the
# waiver registry (lint.jaxpr_sweep.J14_WAIVERS) is pinned EMPTY.
RESTORE_SURFACES = (
    "Checkpointer.restore",
    "Checkpointer.restore_latest_verified",
    "ElasticTrainer._restore",
)


class CheckpointIntegrityError(RuntimeError):
    """A stored checkpoint failed its bit-exact audit and could not be
    repaired from a peer copy — restoring it would silently train on
    corrupted masters, so the restore path REFUSES instead (the caller
    walks back to the previous verified step, or surfaces the loss)."""


def _codec() -> Tuple[Callable[..., Any], Callable[..., Any]]:
    if native.available():
        return native.bfp_encode, native.bfp_decode
    return (lambda x, b, m, r: bfp_golden.bfp_encode(x, b, m, r),
            lambda mant, se, b: bfp_golden.bfp_decode(mant, se, b))


def compress_array(x: np.ndarray, cfg: BFPConfig) -> Dict[str, Any]:
    enc, _ = _codec()
    flat = np.ascontiguousarray(x, np.float32).reshape(-1)
    pad = (-flat.shape[0]) % cfg.block_size
    if pad:
        flat = np.pad(flat, (0, pad))
    mant, scale = enc(flat, cfg.block_size, cfg.mantissa_bits, cfg.rounding)
    return {"mant": mant, "scale": scale, "shape": np.asarray(x.shape),
            "pad": np.asarray(pad), "block": np.asarray(cfg.block_size),
            "dtype": str(x.dtype)}


def decompress_array(blob: Dict[str, Any]) -> np.ndarray:
    _, dec = _codec()
    mant = np.asarray(blob["mant"], np.int8)
    out = dec(mant, np.asarray(blob["scale"], np.int8), int(blob["block"]))
    pad = int(blob["pad"])
    if pad:
        out = out[:-pad]
    return out.reshape(tuple(int(d) for d in np.asarray(blob["shape"]))).astype(
        blob["dtype"] if isinstance(blob["dtype"], str) else str(blob["dtype"]))


# ---------------------------------------------------------------------------
# checksums over the STORED representation
# ---------------------------------------------------------------------------

# weighted-sum chunk: 4 Mi words (16 MiB of payload) bounds the u64
# temporaries to ~tens of MB regardless of leaf size; per-chunk sums of
# <= 2^22 masked-u32 terms stay < 2^54, far inside u64
_CHK_CHUNK_WORDS = 1 << 22


def _u32_words_checksum(words: np.ndarray) -> int:
    """Odd-weighted wraparound u32 word sum over a u32 vector, chunked
    so GB-scale leaves never allocate GB-scale temporaries.  Bit-equal
    to `compress.golden.golden_word_checksum` on the same words (pinned
    by test) — chunking only regroups an associative modular sum."""
    acc = 0
    for k in range(0, words.size, _CHK_CHUNK_WORDS):
        w = words[k:k + _CHK_CHUNK_WORDS].astype(np.uint64)
        idx = np.arange(k, k + w.size, dtype=np.uint64)
        weights = ((idx << np.uint64(1)) | np.uint64(1)) \
            & np.uint64(0xFFFFFFFF)
        acc += int(np.sum((w * weights) & np.uint64(0xFFFFFFFF),
                          dtype=np.uint64))
    return acc & 0xFFFFFFFF


def _u8_checksum(a: np.ndarray) -> int:
    """Checksum of a flat u8 view: bytes pack 4-per-u32-word
    (little-endian, zero-padded tail) — the SAME u32 word decomposition
    the wire plane's checksums ride (`ops.integrity.words_u32` bitcasts
    4-byte payloads word-for-word), at 1/4 the word count of per-byte
    widening."""
    pad = (-a.size) % 4
    if pad:
        a = np.concatenate([a, np.zeros(pad, np.uint8)])
    return _u32_words_checksum(a.view("<u4"))


def bytes_checksum(buf: bytes) -> int:
    """The manifest checksum: the wire plane's odd-weighted wraparound
    u32 word sum (`ops.integrity` / `compress.golden`) over a raw byte
    stream packed little-endian 4 bytes per u32 word (zero-padded tail)
    — dtype-agnostic, so ONE spec covers f32 masters, int8 BFP mantissa
    tiles and the manifest's own canonical JSON; equal by construction
    to ``golden_word_checksum`` over the u32 word view (pinned by
    test).  Exact integer arithmetic, no tolerance band; odd weights
    are invertible mod 2^32, so any single corrupted byte changes its
    word and hence the sum."""
    return _u8_checksum(np.frombuffer(buf, np.uint8))


def npy_data_offset(header: bytes) -> int:
    """Data-region offset of a v1 ``.npy`` file (u16 header length at
    bytes 8..9, data at 10+hlen) — THE single definition shared by the
    chaos/lint/bench/test tooling that flips stored bits; a future
    stored-format change lands here once."""
    return 10 + int.from_bytes(header[8:10], "little")


def flip_stored_bit(path: str, byte_off: int = 0, bit: int = 0) -> int:
    """Flip one DATA-region bit of a stored npy file in place (the
    damage-at-rest primitive the durability batteries inject); returns
    the absolute file offset flipped."""
    with open(path, "rb") as f:
        buf = bytearray(f.read())
    off = min(npy_data_offset(buf) + byte_off, len(buf) - 1)
    buf[off] ^= (1 << bit)
    with open(path, "wb") as f:
        f.write(buf)
    return off


def _c_contig(arr: np.ndarray) -> np.ndarray:
    """C-contiguous view/copy that PRESERVES ndim (np.ascontiguousarray
    silently promotes 0-d scalars to shape (1,), which would corrupt the
    stored shape of e.g. the step counter)."""
    return np.ascontiguousarray(arr).reshape(arr.shape)


def _array_checksum(arr: np.ndarray) -> int:
    # u8 view, not tobytes(): no full-buffer copy per checksum
    return _u8_checksum(_c_contig(arr).reshape(-1).view(np.uint8))


def _canonical_json(obj: Any) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


# ---------------------------------------------------------------------------
# the peer-repair transfer program (reshard-style single pair)
# ---------------------------------------------------------------------------

_PAIR_AXIS = "ckpt_pair"


@lru_cache(maxsize=32)
def pair_transfer_fn(nbytes: int) -> Tuple[Optional[Any], Optional[Any]]:
    """The repair program for an ``nbytes`` shard: ONE jitted shard_map
    over a 2-device pair mesh moving the peer-held mirror bytes to the
    owner with a single exact-length ``lax.ppermute`` — the reshard/
    handoff discipline applied to checkpoint repair.  The payload rides
    as raw u8 words (dtype-agnostic, bit-exact at any itemsize), the
    wire bytes equal EXACTLY the shard bytes (J14 checks the jaxpr the
    way J8/J11 check reshard/handoff), the source operand is donated,
    and the program is callback-free.  Returns ``(fn, mesh)``."""
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        return None, None
    mesh = Mesh(np.array(devs[:2]), (_PAIR_AXIS,))

    def body(x: jax.Array) -> jax.Array:
        return lax.ppermute(x, _PAIR_AXIS, [(0, 1)])

    fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(_PAIR_AXIS),
                               out_specs=P(_PAIR_AXIS), check_vma=False),
                 donate_argnums=(0,))
    return fn, mesh


def peer_fetch(arr: np.ndarray) -> Tuple[np.ndarray, int]:
    """Land a peer-held mirror shard on the owner device.  Row 0 (the
    peer) holds the mirror bytes, row 1 (the owner) zeros; one single-
    pair ppermute delivers exactly ``arr.nbytes`` and the landed row is
    returned bit-for-bit.  Returns ``(landed, wire_bytes)``; on a
    single-device runtime the fetch degenerates to a host copy with
    ``wire_bytes == 0`` (recorded honestly — nothing crossed a wire)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = _c_contig(arr)
    raw = arr.reshape(-1).view(np.uint8) if arr.ndim else arr[None].view(np.uint8)
    fn, mesh = pair_transfer_fn(raw.shape[0])
    if fn is None:
        return np.array(arr, copy=True), 0
    stacked = np.stack([raw, np.zeros_like(raw)])
    x = jax.device_put(stacked, NamedSharding(mesh, P(_PAIR_AXIS)))
    out = np.asarray(jax.block_until_ready(fn(x)))
    landed = out[1].view(arr.dtype).reshape(arr.shape)
    return landed, int(raw.nbytes)


# ---------------------------------------------------------------------------
# the save file-op stream
# ---------------------------------------------------------------------------

class FileOp(NamedTuple):
    """One filesystem action of a save/GC sequence.  ``save`` is planned
    as a list of these and executed in order — the opstream emitter
    discipline applied to the filesystem, so the crash-point sweep can
    truncate the sequence at every prefix and assert the commit protocol
    (tests/test_checkpoint.py).  Kinds:

      mkdir      create ``path`` (parents ok)
      write_npy  write ``data`` (np.ndarray) to ``path``
      write_json write ``data`` (json-able) to ``path``
      replace    atomic ``os.replace(path, data)`` — THE commit op
      remove     unlink ``path`` (missing ok)
      rmtree     remove the tree at ``path`` (missing ok)
      rmdir      remove the (now empty) dir at ``path`` (missing ok)
      gc_guard   read-back audit of the just-committed step (``data`` =
                 step): retention deletions only run if the NEW step
                 verifies on disk — a lying write can never leave the
                 directory with zero restorable steps
    """

    kind: str
    path: str
    data: Any = None


def _apply_op(op: FileOp) -> None:
    if op.kind == "mkdir":
        os.makedirs(op.path, exist_ok=True)
    elif op.kind == "write_npy":
        with open(op.path, "wb") as f:
            np.save(f, _c_contig(op.data))
    elif op.kind == "write_json":
        with open(op.path, "w") as f:
            json.dump(op.data, f)
    elif op.kind == "replace":
        os.replace(op.path, op.data)
    elif op.kind == "remove":
        try:
            os.remove(op.path)
        except FileNotFoundError:
            pass
    elif op.kind == "rmtree":
        shutil.rmtree(op.path, ignore_errors=True)
    elif op.kind == "rmdir":
        try:
            os.rmdir(op.path)
        except OSError:
            pass
    else:  # pragma: no cover - planner bug
        raise ValueError(f"unknown file op kind {op.kind!r}")


# ---------------------------------------------------------------------------
# audit report
# ---------------------------------------------------------------------------

@dataclass
class AuditReport:
    """Verdict of one step's bit-exact audit against its manifest."""

    step: int
    ok: bool = True                    # every primary byte matched
    restorable: bool = False           # clean, or every failure repaired
    failures: List[Dict[str, Any]] = field(default_factory=list)
    repaired: List[Dict[str, Any]] = field(default_factory=list)
    repair_wire_bytes: int = 0
    emergency: bool = False
    # the assembled (still-compressed) tree when restorable — restore
    # reuses it so audited bytes are the restored bytes, read once
    tree: Optional[Any] = None

    def describe(self) -> str:
        probs = "; ".join(
            f"{'/'.join(map(str, f['path']))}"
            + (f"[shard {f['shard']}]" if f.get("shard") is not None else "")
            + f": {f['reason']}" for f in self.failures) or "clean"
        return (f"step {self.step}: ok={self.ok} "
                f"restorable={self.restorable} repaired={len(self.repaired)}"
                f" ({probs})")


# ---------------------------------------------------------------------------
# tree <-> template flattening
# ---------------------------------------------------------------------------

def _template(tree: Any, leaves: List[Tuple[Tuple[Any, ...], np.ndarray]],
              path: Tuple[Any, ...] = ()) -> Any:
    """JSON template of ``tree`` with array leaves replaced by
    ``{"__leaf__": i}`` refs (appended to ``leaves``); container shape
    (dict/list/tuple) and inline scalars survive verbatim."""
    if isinstance(tree, dict):
        clash = {"__leaf__", "__tuple__", "__str__"} & set(map(str, tree))
        if clash:
            # the template's sentinel names: a user payload carrying one
            # would rebuild as the WRONG data (e.g. {'__leaf__': 0}
            # resolves to leaf 0's array) — a silent misrestore the
            # audited store must refuse at save time
            raise TypeError(
                f"cannot checkpoint dict at {path}: key(s) {sorted(clash)} "
                "collide with the manifest template's reserved names")
        return {str(k): _template(v, leaves, path + (str(k),))
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        body = [_template(v, leaves, path + (i,))
                for i, v in enumerate(tree)]
        return {"__tuple__": body} if isinstance(tree, tuple) else body
    if isinstance(tree, (np.ndarray, np.generic)):
        arr = np.asarray(tree)
        if arr.dtype.kind in "USO":
            if arr.ndim == 0:
                return {"__str__": str(arr.item())}
            raise TypeError(f"cannot checkpoint non-numeric array at "
                            f"{path} (dtype {arr.dtype})")
        leaves.append((path, arr))
        return {"__leaf__": len(leaves) - 1}
    if tree is None or isinstance(tree, (bool, int, float, str)):
        return tree
    raise TypeError(f"cannot checkpoint leaf of type {type(tree).__name__} "
                    f"at {path}")


def _rebuild(template: Any, arrays: List[np.ndarray]) -> Any:
    if isinstance(template, dict):
        if "__leaf__" in template:
            return arrays[template["__leaf__"]]
        if "__str__" in template:
            return template["__str__"]
        if "__tuple__" in template:
            return tuple(_rebuild(v, arrays) for v in template["__tuple__"])
        return {k: _rebuild(v, arrays) for k, v in template.items()}
    if isinstance(template, list):
        return [_rebuild(v, arrays) for v in template]
    return template


class Checkpointer:
    """Audited, crash-consistent checkpoint manager with optional BFP-
    compressed optimizer/master state, per-shard peer mirrors, bounded
    retention and chaos hooks (the durability plane v2 — see the module
    docstring and docs/DURABILITY.md for the protocol).

    ``async_save=True`` writes in a background thread: ``save`` returns
    as soon as the host copy is snapshotted (``jax.device_get``) — the
    BFP encode of the master/optimizer shards AND all file IO run in
    the background thread, so for GB-scale compressed state the caller
    stalls only for the device pull.  Call ``wait_until_finished()`` (or
    just the next ``save``, which waits on the previous one) before
    reading the files; background errors re-raise at the next sync
    point.

    ``shards=n`` splits big first-dim-divisible stored arrays (the flat
    ZeRO-1 masters/moments) into n per-device shard files; with
    ``mirror=True`` every shard (and every unsharded array) is ALSO
    stored under its dp peer, which is what makes a corrupt primary
    repairable (``peer_fetch``).  ``keep_last=N`` arms retention GC that
    never deletes the newest *verified* step.  ``chaos`` (a
    ``runtime.chaos.FaultPlan``) arms the durability fault sites
    ``ckpt.save`` / ``ckpt.restore``."""

    _LAYOUT_FILE = "layer_layout.json"

    def __init__(self, directory: str,
                 compress: Optional[BFPConfig] = None,
                 async_save: bool = False, *,
                 shards: Optional[int] = None,
                 mirror: bool = False,
                 keep_last: Optional[int] = None,
                 chaos: Any = None,
                 recovery: Any = None,
                 events: Any = None) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.compress = compress
        self._async = async_save
        self.shards = shards
        self.mirror = mirror
        self.keep_last = keep_last
        self.chaos = chaos
        self.recovery = recovery      # observability.RecoveryStats or None
        self.events = events          # obs EventStream or None
        # crash-point sweep seam: called (op_index, FileOp) BEFORE each
        # op of a save/GC sequence executes; an exception it raises
        # leaves exactly the prefix applied (the simulated crash)
        self.op_hook: Optional[Callable[[int, FileOp], None]] = None
        self._bg: Optional[threading.Thread] = None
        self._bg_exc: Optional[BaseException] = None
        self._recover_leftovers()

    # -- paths --------------------------------------------------------------

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def _tmp_path(self, step: int) -> str:
        return self._path(step) + ".tmp-write"

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._path(step), MANIFEST_FILE)

    def _layout_path(self, step: int) -> str:
        # INSIDE the step directory: the sidecar describes that step's
        # bytes and travels (and dies) with them — and under the v2
        # commit protocol it is written into the tmp dir BEFORE the
        # publishing rename, so step bytes and sidecar commit in ONE
        # atomic op (no crash window can strand a sidecar for a step
        # that never appeared, or publish a step missing its sidecar).
        return os.path.join(self._path(step), self._LAYOUT_FILE)

    def _legacy_layout_path(self) -> str:
        # directory-scoped sidecar location used by older revisions; read
        # as a fallback and migrated into the step dirs on the next save
        return os.path.join(self.directory, self._LAYOUT_FILE)

    def _all_steps(self) -> List[int]:
        steps = []
        for d in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)", d)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def _recover_leftovers(self) -> None:
        """Journal recovery for the same-step re-save window.  A crash
        between 'old dir steps aside' and 'tmp commits' leaves
        ``step_N.replaced`` (the old, fully verified copy) with no
        ``step_N`` — if that step was the directory's ONLY one, restore
        would otherwise refuse despite an intact copy on disk.  Roll
        the old copy back (one atomic rename); when the commit DID land
        the leftover trash is simply removed.  Uncommitted
        ``.tmp-write`` dirs are garbage by definition (their commit
        never happened — adopting one would resurrect a save the
        caller was told failed) and are cleaned here too.  Runs at
        construction (the restarting process) and at every sync point;
        never while a background save is in flight (callers join
        first)."""
        for d in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)\.replaced", d)
            if not m:
                continue
            trash = os.path.join(self.directory, d)
            committed = self._path(int(m.group(1)))
            if os.path.isdir(committed):
                shutil.rmtree(trash, ignore_errors=True)
            else:
                os.replace(trash, committed)   # roll the old step back
        for d in os.listdir(self.directory):
            if re.fullmatch(r"step_(\d+)\.tmp-write", d):
                shutil.rmtree(os.path.join(self.directory, d),
                              ignore_errors=True)

    # -- legacy sidecar migration (unchanged semantics) ---------------------

    def _migrate_legacy_layout(self) -> None:
        """Copy a directory-scoped sidecar (older revisions wrote one per
        DIRECTORY) into every existing step dir that lacks its own, then
        remove it — after which the per-step rules apply uniformly and a
        plain-order save can no longer strand older steps layout-less."""
        legacy = self._legacy_layout_path()
        if not os.path.exists(legacy):
            return
        with open(legacy) as f:
            layout = json.load(f)
        for s in self._all_steps():
            p = self._layout_path(s)
            if not os.path.exists(p):
                with open(p, "w") as f:
                    json.dump(layout, f)
        os.remove(legacy)

    def _apply_sidecar(self, step: int,
                       layout: Optional[Dict[str, Any]]) -> None:
        """Write (or, for ``None``, remove) step's sidecar on disk."""
        if layout is not None:
            os.makedirs(self._path(step), exist_ok=True)
            with open(self._layout_path(step), "w") as f:
                json.dump(layout, f)
        else:
            try:
                os.remove(self._layout_path(step))
            except FileNotFoundError:
                pass

    # -- async-save sidecar staging -----------------------------------------
    # The sidecar commits atomically INSIDE the step rename, but an async
    # save only materializes the step dir when the background write
    # commits.  So save() stages the layout in a DURABLE pending file
    # next to the step dir — not in memory — and any sync point moves it
    # in.  A crash between commit and flush leaves checkpoint + pending
    # file on disk, and saved_layout()/restore() honor the pending file,
    # so the layout is never silently lost (the silent-permute hazard the
    # sidecar exists to prevent).

    def _pending_path(self, step: int) -> str:
        return os.path.join(self.directory,
                            f"step_{step:08d}.layout-pending.json")

    def _stage_sidecar(self, step: int,
                       layout: Optional[Dict[str, Any]]) -> None:
        tmp = self._pending_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"layout": layout}, f)
        os.replace(tmp, self._pending_path(step))

    def _read_pending(self, step: int) -> Optional[Dict[str, Any]]:
        """The staged {'layout': ...} dict, or None if nothing is staged."""
        try:
            with open(self._pending_path(step)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def _flush_pending_sidecars(self, skip_step: Optional[int] = None
                                ) -> None:
        """Move staged sidecars into their (now committed) step dirs."""
        for fname in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)\.layout-pending\.json", fname)
            if not m:
                continue
            step = int(m.group(1))
            if step == skip_step or not os.path.isdir(self._path(step)):
                continue                 # not committed yet: stays staged
            pending = self._read_pending(step)
            if pending is not None:
                self._apply_sidecar(step, pending["layout"])
            os.remove(self._pending_path(step))

    def save_layout(self, layout: Dict[str, Any],
                    step: int) -> Dict[str, Any]:
        """Record how step ``step``'s flat master bytes are ordered (e.g.
        the interleaved-1F1B layer permutation: layers_order / pp /
        virtual_stages).  A checkpoint that carries a layout sidecar can
        only be restored by a caller that declares a MATCHING layout —
        ``restore`` enforces it — so bytes can never be silently
        reinterpreted under a different pp/v/schedule.  (Standalone use:
        waits out any in-flight async save first; ``save(layout=...)``
        defers instead and never blocks.)"""
        self.wait_until_finished()
        self._apply_sidecar(step, layout)
        return layout

    def saved_layout(self, step: Optional[int] = None
                     ) -> Optional[Dict[str, Any]]:
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        pending = self._read_pending(step)     # async save not yet flushed
        if pending is not None:
            return pending["layout"]
        if os.path.exists(self._layout_path(step)):
            with open(self._layout_path(step)) as f:
                return json.load(f)
        # pre-migration checkpoint: a directory-scoped sidecar governs
        # every step that has no per-step sidecar of its own
        legacy = self._legacy_layout_path()
        if os.path.isdir(self._path(step)) and os.path.exists(legacy):
            with open(legacy) as f:
                return json.load(f)
        return None

    def _check_layout(self, step: int,
                      expect: Optional[Dict[str, Any]]) -> None:
        saved = self.saved_layout(step)
        if saved is None and expect is None:
            return
        if saved is None:
            raise ValueError(
                f"restore declared layout {expect} but the checkpoint at "
                f"{self._path(step)} has no {self._LAYOUT_FILE} sidecar — "
                "it was saved in plain model order; drop expect_layout or "
                "re-save with save_layout()")
        if expect is None:
            raise ValueError(
                f"checkpoint at {self._path(step)} carries a layout "
                f"sidecar {saved} (its flat masters are NOT in model "
                "order); pass expect_layout= with the run's matching "
                "pp/virtual_stages/schedule to restore()")
        mismatched = {k: (saved.get(k), expect.get(k))
                      for k in set(saved) | set(expect)
                      if saved.get(k) != expect.get(k)}
        if mismatched:
            raise ValueError(
                "checkpoint layout mismatch (saved vs requested): "
                f"{mismatched} — restoring these bytes under the requested "
                "pp/virtual_stages/schedule would silently permute layers")

    # -- save ---------------------------------------------------------------

    def _host_tree(self, state: Any) -> Any:
        """The masters-only host snapshot of a trainer state.  TRAINER
        STATES (NamedTuples) carrying a flat master copy (w_own /
        w_master) drop their working ``params`` tree: every trainer's
        ``restore_state`` rematerializes params from the masters, so
        persisting both would double checkpoint size (and wipe out the
        BFP compression win for bf16 models).  The error-feedback
        residual (codec_state) is likewise dropped — a bounded
        per-device accumulator every restore_state re-zeros.  Plain
        dicts are saved verbatim — the masters-only heuristic never
        applies to user payloads whose keys merely resemble a trainer
        state's."""
        is_trainer_state = hasattr(state, "_asdict")
        tree = dict(state._asdict()) if is_trainer_state else state
        if is_trainer_state and "params" in tree and (
                "w_own" in tree or "w_master" in tree):
            tree = {k: v for k, v in tree.items() if k != "params"}
        if is_trainer_state and ("w_own" in tree or "w_master" in tree):
            tree = {k: v for k, v in tree.items() if k != "codec_state"}
        return jax.tree_util.tree_map(np.asarray, jax.device_get(tree))

    def _shard_plan(self, arr: np.ndarray, shards: Optional[int]) -> int:
        """How many shard files this stored array splits into (1 = whole
        file).  Split iff a dp width is declared, the first dim divides
        by it, and the array is big enough for per-shard repair to mean
        anything — the flat padded ZeRO-1 masters/moments by
        construction, never the step scalar."""
        n = shards or 1
        if (n > 1 and arr.ndim >= 1 and arr.shape[0] % n == 0
                and arr.nbytes >= _MIN_SHARD_BYTES):
            return n
        return 1

    def _plan_write_ops(self, step: int, tree: Any,
                        layout: Optional[Dict[str, Any]],
                        emergency: bool, shards: Optional[int]
                        ) -> List[FileOp]:
        """The save as an explicit file-op sequence.  Protocol: all
        files — leaf/shard/mirror npys, the layout sidecar, the manifest
        — land in ``step_N.tmp-write``; ONE ``os.replace`` publishes the
        step; post-commit ops (pending-sidecar flush, same-step-replace
        trash removal, retention GC) follow.  Any prefix leaves either
        the previous verified state or the fully committed new step."""
        path, tmp = self._path(step), self._tmp_path(step)
        leaves: List[Tuple[Tuple[Any, ...], np.ndarray]] = []
        template = _template(tree, leaves)
        ops: List[FileOp] = [FileOp("rmtree", tmp), FileOp("mkdir", tmp)]
        manifest_leaves: List[Dict[str, Any]] = []
        for i, (lpath, arr) in enumerate(leaves):
            arr = _c_contig(arr)
            name = f"leaf_{i:05d}"
            n_shards = self._shard_plan(arr, shards)
            entry: Dict[str, Any] = {
                "path": list(lpath), "name": name,
                "dtype": str(arr.dtype), "shape": list(arr.shape),
                "nbytes": int(arr.nbytes),
                "checksum": _array_checksum(arr),
            }
            if n_shards > 1:
                rows = arr.shape[0] // n_shards
                shard_entries = []
                for j in range(n_shards):
                    piece = arr[j * rows:(j + 1) * rows]
                    fname = f"{name}.s{j:02d}.npy"
                    ops.append(FileOp("write_npy",
                                      os.path.join(tmp, fname), piece))
                    srec = {"file": fname, "owner": j,
                            "checksum": _array_checksum(piece),
                            "nbytes": int(piece.nbytes)}
                    if self.mirror:
                        mname = f"{name}.s{j:02d}.m.npy"
                        ops.append(FileOp("write_npy",
                                          os.path.join(tmp, mname), piece))
                        srec["mirror"] = mname
                        srec["mirror_owner"] = (j + 1) % n_shards
                    shard_entries.append(srec)
                entry["shards"] = shard_entries
            else:
                fname = f"{name}.npy"
                ops.append(FileOp("write_npy",
                                  os.path.join(tmp, fname), arr))
                entry["file"] = fname
                if self.mirror:
                    mname = f"{name}.m.npy"
                    ops.append(FileOp("write_npy",
                                      os.path.join(tmp, mname), arr))
                    entry["mirror"] = mname
            manifest_leaves.append(entry)
        if layout is not None:
            ops.append(FileOp("write_json",
                              os.path.join(tmp, self._LAYOUT_FILE), layout))
        body = {
            "format": _FORMAT, "algo": _ALGO, "step": int(step),
            "emergency": bool(emergency),
            "compress": (None if self.compress is None else
                         {"block_size": self.compress.block_size,
                          "mantissa_bits": self.compress.mantissa_bits}),
            "shards": shards, "mirror": bool(self.mirror),
            "tree": template, "leaves": manifest_leaves,
        }
        body["self_checksum"] = bytes_checksum(
            _canonical_json(dict(body, self_checksum=0)))
        ops.append(FileOp("write_json",
                          os.path.join(tmp, MANIFEST_FILE), body))
        # -- the commit -----------------------------------------------------
        trash = None
        if os.path.isdir(path):
            # same-step re-save: the old dir steps aside first (os.replace
            # cannot atomically replace a non-empty dir).  A crash in the
            # window between the two renames leaves step_N.replaced with
            # no step_N; _recover_leftovers rolls the old verified copy
            # back at the next construction/sync point, so the step is
            # never lost — and never a mixed old/new dir (the trash name
            # never matches step_\d+).
            trash = path + ".replaced"
            ops.append(FileOp("rmtree", trash))
            ops.append(FileOp("replace", path, trash))
        ops.append(FileOp("replace", tmp, path))
        if trash is not None:
            ops.append(FileOp("rmtree", trash))
        if self._async:
            # this save's own staged sidecar is committed by the rename:
            # retire the pending file
            ops.append(FileOp("remove", self._pending_path(step)))
        ops.extend(self._plan_gc_ops(new_step=step))
        return ops

    def _plan_gc_ops(self, new_step: Optional[int]) -> List[FileOp]:
        """Retention ops: delete steps beyond ``keep_last``, NEVER the
        newest verified step.  On the save path the deletions sit
        behind a ``gc_guard`` op — a read-back audit of the freshly
        committed step, so a write the disk lied about can never cost
        the directory its only restorable step; a standalone ``gc()``
        (no new step) protects the newest step that audits restorable
        instead.  Victim manifests are removed FIRST, so a crash mid-GC
        leaves the half-deleted step definitively torn (unverified)
        instead of plausibly restorable."""
        if not self.keep_last:
            return []
        existing = self._all_steps()
        all_steps = sorted(set(existing) |
                           ({new_step} if new_step is not None else set()),
                           reverse=True)
        keep = set(all_steps[:self.keep_last])
        victims = [s for s in existing if s not in keep]
        if not victims:
            return []
        ops: List[FileOp] = []
        if new_step is not None:
            ops.append(FileOp("gc_guard", self._path(new_step), new_step))
        else:
            # no fresh write to verify: the newest step that audits
            # restorable survives even outside the window (the kept
            # window steps may themselves be corrupt — the walk must
            # not stop at them)
            for s in sorted(existing, reverse=True):
                if self.audit_step(s, repair="probe").restorable:
                    keep.add(s)
                    break
            victims = [s for s in existing if s not in keep]
            if not victims:
                return []
        for s in sorted(existing, reverse=True):
            if s in keep:
                continue
            d = self._path(s)
            ops.append(FileOp("remove", os.path.join(d, MANIFEST_FILE)))
            for fname in sorted(os.listdir(d)):
                if fname != MANIFEST_FILE:
                    ops.append(FileOp("remove", os.path.join(d, fname)))
            ops.append(FileOp("remove", self._pending_path(s)))
            ops.append(FileOp("rmdir", d))
        return ops

    def _exec_ops(self, ops: List[FileOp],
                  interruptible: bool = True) -> None:
        """Run a planned op sequence with the chaos + sweep seams: the
        op_hook fires before each op; an armed FaultPlan's
        kill/diskfull specs at ``ckpt.save`` interrupt at their planned
        op index (``fraction`` of the sequence), leaving exactly that
        prefix on disk — the injected crash.  Only SAVE sequences are
        interruptible: a standalone gc() must never pop (and thereby
        silently discard) a kill spec planned for the next save."""
        kill_at: Dict[int, Any] = {}
        if self.chaos is not None and interruptible and ops:
            for spec in self.chaos.take_save_interrupts():
                idx = min(max(int(spec.fraction * len(ops)), 0),
                          len(ops) - 1)
                kill_at.setdefault(idx, spec)
        for i, op in enumerate(ops):
            if self.op_hook is not None:
                self.op_hook(i, op)
            spec = kill_at.get(i)
            if spec is not None:
                from ..runtime import chaos as chaos_lib
                if spec.kind == "diskfull":
                    import errno
                    raise OSError(errno.ENOSPC,
                                  f"injected disk-full during {op.kind} "
                                  f"{os.path.basename(op.path)}")
                raise chaos_lib.InjectedFault(spec)
            if op.kind == "gc_guard":
                # read-back verify before retention deletes old copies:
                # a new step that does not audit restorable on disk
                # aborts the remaining (deletion-only) ops — the save
                # itself already committed and stays valid
                if not self.audit_step(int(op.data),
                                       repair="probe").restorable:
                    if self.events is not None:
                        self.events.instant("ckpt.gc_aborted",
                                            step=int(op.data))
                    return
                continue
            _apply_op(op)

    def _write_step(self, step: int, tree: Any,
                    layout: Optional[Dict[str, Any]],
                    emergency: bool, shards: Optional[int]) -> None:
        """Compress (if configured) + plan + execute the op stream.  In
        async mode this whole body runs on the background thread — the
        GB-scale BFP encode included, so ``save`` stalls the trainer
        only for the device_get snapshot."""
        if self.compress is not None and isinstance(tree, dict):
            for key in ("w_own", "w_master"):
                if key in tree:
                    tree = dict(tree, **{
                        key: compress_array(tree[key], self.compress)})
            if "opt_state" in tree:
                tree = dict(tree, opt_state={
                    k: compress_array(v, self.compress)
                    for k, v in tree["opt_state"].items()})
        self._exec_ops(self._plan_write_ops(step, tree, layout,
                                            emergency, shards))
        if self.chaos is not None:
            # durability damage-at-rest (file bit-flip / stale manifest)
            # fires AFTER the commit: the fault models rot/operator
            # error on a fully written checkpoint
            self.chaos.damage_checkpoint("ckpt.save", self._path(step),
                                         self._prev_manifest(step))

    def _prev_manifest(self, step: int) -> Optional[str]:
        prev = [s for s in self._all_steps() if s < step]
        return self._manifest_path(max(prev)) if prev else None

    def save(self, step: int, state: Any,
             layout: Optional[Dict[str, Any]] = None, *,
             emergency: bool = False,
             shards: Optional[int] = None) -> str:
        """Persist a trainer state (see ``_host_tree`` for what is
        dropped) under the audited commit protocol.  Returns the step
        path (async: the path it will commit to)."""
        tree = self._host_tree(state)
        self.wait_until_finished()       # serialize with the previous save
        self._migrate_legacy_layout()
        shards = self.shards if shards is None else shards
        if self._async:
            # stage the sidecar durably BEFORE the background write: a
            # crash between the commit and the next sync point must leave
            # the layout recoverable next to the committed bytes
            self._stage_sidecar(step, layout)

            def work() -> None:
                try:
                    self._write_step(step, tree, layout, emergency, shards)
                except BaseException as e:  # noqa: BLE001 — re-raised at sync
                    self._bg_exc = e

            self._bg = threading.Thread(target=work, daemon=True,
                                        name="ckpt-save")
            self._bg.start()
        else:
            self._write_step(step, tree, layout, emergency, shards)
        return self._path(step)

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed to disk,
        recover any crash leftovers, flush the committed steps' staged
        layout sidecars, and re-raise any background-save error (a
        silently failed save would leave the caller trusting a
        checkpoint that never landed)."""
        t, self._bg = self._bg, None
        if t is not None:
            t.join()
        self._recover_leftovers()
        self._flush_pending_sidecars()
        exc, self._bg_exc = self._bg_exc, None
        if exc is not None:
            raise exc

    # -- audit + repair -----------------------------------------------------

    def read_manifest(self, step: int) -> Optional[Dict[str, Any]]:
        """The step's manifest, validated (format, self-checksum, step
        field vs directory name).  None when absent/torn/stale — the
        step is then unverified by definition."""
        try:
            with open(self._manifest_path(step)) as f:
                man = json.load(f)
        except (FileNotFoundError, NotADirectoryError,
                json.JSONDecodeError, UnicodeDecodeError):
            return None
        if not isinstance(man, dict) or man.get("format") != _FORMAT:
            return None
        declared = man.get("self_checksum")
        body = dict(man, self_checksum=0)
        if declared != bytes_checksum(_canonical_json(body)):
            return None
        if int(man.get("step", -1)) != step:
            # a STALE manifest (copied from another step) must not
            # validate bytes it never described
            return None
        return man

    def _load_piece(self, d: str, fname: str, checksum: int,
                    dtype: str, rows_shape: Tuple[int, ...]
                    ) -> Tuple[Optional[np.ndarray], str]:
        """(array, '') on a bit-exact load, (None, reason) otherwise."""
        p = os.path.join(d, fname)
        try:
            arr = np.load(p, allow_pickle=False)
        except FileNotFoundError:
            return None, "missing file"
        except Exception as e:  # noqa: BLE001 — torn/garbled npy
            return None, f"unreadable ({type(e).__name__})"
        if str(arr.dtype) != dtype or tuple(arr.shape) != rows_shape:
            return None, (f"dtype/shape drift ({arr.dtype}{arr.shape} "
                          f"vs {dtype}{rows_shape})")
        if _array_checksum(arr) != checksum:
            return None, "checksum mismatch"
        return arr, ""

    def _heal(self, path: str, arr: np.ndarray) -> None:
        """Atomically rewrite a damaged primary from repaired bytes."""
        tmp = path + ".heal"
        with open(tmp, "wb") as f:
            np.save(f, _c_contig(arr))
        os.replace(tmp, path)

    def audit_step(self, step: int, repair: Any = False) -> AuditReport:
        """Bit-exact audit of one step against its manifest: every
        primary leaf/shard file is re-checksummed.  With ``repair=True``
        a corrupt primary whose PEER mirror verifies is fetched over the
        single-pair transfer program, re-verified against the manifest,
        and healed in place; ``repair="probe"`` verifies the mirror and
        counts the shard repairable WITHOUT moving bytes or healing (the
        non-mutating query latest_step(verified=True)/GC use).
        ``restorable`` means every byte of the assembled tree is
        manifest-verified (clean, repaired or probe-verified mirror) —
        the only state ``restore`` will hand to a trainer."""
        rep = AuditReport(step=step)
        man = self.read_manifest(step)
        if man is None:
            rep.ok = False
            rep.failures.append({"path": [MANIFEST_FILE], "shard": None,
                                 "reason": "manifest absent/torn/stale"})
            return rep
        rep.emergency = bool(man.get("emergency"))
        d = self._path(step)
        arrays: List[Optional[np.ndarray]] = []
        fatal = False
        for entry in man["leaves"]:
            dtype, shape = entry["dtype"], tuple(entry["shape"])
            if "shards" in entry:
                n = len(entry["shards"])
                rows = shape[0] // n
                pieces: List[Optional[np.ndarray]] = []
                for j, srec in enumerate(entry["shards"]):
                    pshape = (rows,) + shape[1:]
                    arr, why = self._load_piece(d, srec["file"],
                                                srec["checksum"], dtype,
                                                pshape)
                    if arr is None:
                        rep.ok = False
                        fail = {"path": entry["path"], "shard": j,
                                "reason": why}
                        if repair:
                            arr = self._repair_piece(
                                d, srec, dtype, pshape, rep, fail,
                                probe=repair == "probe")
                        if arr is None:
                            rep.failures.append(fail)
                    pieces.append(arr)
                if any(p is None for p in pieces):
                    fatal = True
                    arrays.append(None)
                elif repair == "probe":
                    arrays.append(None)   # verdict-only: no assembly
                else:
                    arrays.append(np.concatenate(pieces, axis=0))
            else:
                arr, why = self._load_piece(d, entry["file"],
                                            entry["checksum"], dtype, shape)
                if arr is None:
                    rep.ok = False
                    fail = {"path": entry["path"], "shard": None,
                            "reason": why}
                    if repair and entry.get("mirror"):
                        arr = self._repair_piece(
                            d, {"file": entry["file"],
                                "mirror": entry["mirror"],
                                "checksum": entry["checksum"]},
                            dtype, shape, rep, fail,
                            probe=repair == "probe")
                    if arr is None:
                        rep.failures.append(fail)
                        fatal = True
                arrays.append(arr)
        if not fatal:
            rep.restorable = True
            if repair != "probe":
                # probe callers (gc_guard, latest_step(verified=True))
                # need only the verdict — skipping assembly avoids a
                # second full in-memory copy of a GB-scale state
                rep.tree = _rebuild(man["tree"],
                                    [a for a in arrays])  # type: ignore[misc]
        return rep

    def _repair_piece(self, d: str, srec: Dict[str, Any], dtype: str,
                      shape: Tuple[int, ...], rep: AuditReport,
                      fail: Dict[str, Any],
                      probe: bool = False) -> Optional[np.ndarray]:
        """Peer repair of one corrupt primary: verify the mirror copy
        bit-exactly against the manifest, fetch it onto the owner via
        the pair transfer program, re-verify the LANDED bytes, heal the
        primary file.  ``probe`` stops after the mirror verification
        (repairability without mutation).  None (with ``fail['reason']``
        extended) when no clean source exists — the caller then refuses
        or walks back, never restores."""
        mname = srec.get("mirror")
        if not mname:
            fail["reason"] += "; no peer mirror to repair from"
            return None
        mirror, why = self._load_piece(d, mname, srec["checksum"],
                                       dtype, shape)
        if mirror is None:
            fail["reason"] += f"; peer mirror also bad ({why})"
            return None
        if probe:
            return mirror
        landed, wire = peer_fetch(mirror)
        if _array_checksum(landed) != srec["checksum"]:
            fail["reason"] += "; peer fetch landed corrupt"
            return None
        self._heal(os.path.join(d, srec["file"]), landed)
        rep.repair_wire_bytes += wire
        rec = {"path": fail["path"], "shard": fail.get("shard"),
               "file": srec["file"], "wire_bytes": wire}
        rep.repaired.append(rec)
        if self.events is not None:
            self.events.instant("ckpt.repair", step=rep.step,
                                file=srec["file"], wire_bytes=wire)
        if self.recovery is not None:
            self.recovery.record_ckpt_repair(wire_bytes=wire)
        return landed

    # -- restore ------------------------------------------------------------

    def _decompress_tree(self, tree: Any) -> Any:
        if self.compress is not None and isinstance(tree, dict):
            for key in ("w_own", "w_master"):
                if key in tree and isinstance(tree[key], dict):
                    tree[key] = decompress_array(tree[key])
            if "opt_state" in tree:
                tree["opt_state"] = {
                    k: decompress_array(v) if isinstance(v, dict) else v
                    for k, v in tree["opt_state"].items()}
        return tree

    def restore(self, step: int,
                expect_layout: Optional[Dict[str, Any]] = None) -> Any:
        """Audited restore of one step: every leaf re-checksummed
        against the manifest, corrupt shards peer-repaired when a clean
        mirror exists, and REFUSED (CheckpointIntegrityError) otherwise
        — bytes that fail their audit never reach a trainer.  There is
        no unaudited restore path (graftlint J14, zero waivers)."""
        self.wait_until_finished()       # commit in-flight saves + sidecars
        self._check_layout(step, expect_layout)
        if self.chaos is not None:
            # durability faults at the restore boundary (damage-at-rest
            # discovered on read): fire BEFORE the audit so the audit is
            # what catches them
            self.chaos.damage_checkpoint("ckpt.restore", self._path(step),
                                         self._prev_manifest(step))
        rep = self.audit_step(step, repair=True)
        if not rep.restorable:
            if self.events is not None:
                self.events.instant("ckpt.refused", step=step,
                                    detail=rep.describe()[:200])
            raise CheckpointIntegrityError(
                f"refusing to restore {self._path(step)}: "
                f"{rep.describe()} — no clean source for the failed "
                "leaves (restore never silently hands corrupt bytes to "
                "a trainer; fall back to an earlier verified step via "
                "restore_latest_verified)")
        return self._decompress_tree(rep.tree)

    def restore_latest_verified(
            self, expect_layout: Optional[Dict[str, Any]] = None,
    ) -> Tuple[int, Any]:
        """Walk the step directory BACKWARD past corrupt/torn steps to
        the newest step that audits clean (repairing where a peer copy
        allows), and restore it.  Raises CheckpointIntegrityError when
        no verified step exists — refusal, never a silent restore of
        damaged state."""
        self.wait_until_finished()
        steps = self._all_steps()
        tried = []
        for step in sorted(steps, reverse=True):
            try:
                return step, self.restore(step, expect_layout=expect_layout)
            except CheckpointIntegrityError as e:
                tried.append((step, str(e).splitlines()[0][:160]))
        raise CheckpointIntegrityError(
            f"no verified checkpoint under {self.directory}: "
            f"{len(steps)} step dir(s), every audit failed "
            f"({tried if tried else 'directory empty'})")

    def latest_step(self, verified: bool = False) -> Optional[int]:
        """Newest step number — by directory name (``verified=False``,
        the cheap legacy behavior; orbax-style atomic-write temp dirs
        and the v2 ``.tmp-write``/``.replaced`` names never match), or
        the newest step whose AUDIT passes (``verified=True``: walks
        backward past corrupt/torn steps; a step is counted when clean
        OR peer-repairable, since either restores bit-exactly)."""
        steps = self._all_steps()
        if not verified:
            return max(steps) if steps else None
        for step in sorted(steps, reverse=True):
            if self.audit_step(step, repair="probe").restorable:
                return step
        return None

    def is_emergency(self, step: int) -> bool:
        man = self.read_manifest(step)
        return bool(man and man.get("emergency"))

    # -- retention ----------------------------------------------------------

    def gc(self) -> List[int]:
        """Run retention now (``keep_last`` steps kept, plus the newest
        verified step unconditionally).  Returns the deleted steps."""
        before = set(self._all_steps())
        self._exec_ops(self._plan_gc_ops(new_step=None),
                       interruptible=False)
        return sorted(before - set(self._all_steps()))
