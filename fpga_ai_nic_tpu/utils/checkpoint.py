"""Checkpoint / resume — absent in the reference (SURVEY.md §5:
"Checkpoint / resume: none anywhere"), required by the larger BASELINE
configs (Llama-3 8B ZeRO-1 with BFP optimizer-state compression).

Two layers:
- ``save/restore``: orbax-backed full TrainState checkpointing.
- ``compress_state/decompress_state``: optional BFP compression of the f32
  master/optimizer shards (BASELINE.json config 5) using the native C++
  codec when available (runtime.native), else the numpy golden model —
  4 bytes -> ~1.06 bytes per element at a bounded quantization error.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..ops import bfp_golden
from ..runtime import native
from .config import BFPConfig


def _codec():
    if native.available():
        return native.bfp_encode, native.bfp_decode
    return (lambda x, b, m, r: bfp_golden.bfp_encode(x, b, m, r),
            lambda mant, se, b: bfp_golden.bfp_decode(mant, se, b))


def compress_array(x: np.ndarray, cfg: BFPConfig) -> Dict[str, Any]:
    enc, _ = _codec()
    flat = np.ascontiguousarray(x, np.float32).reshape(-1)
    pad = (-flat.shape[0]) % cfg.block_size
    if pad:
        flat = np.pad(flat, (0, pad))
    mant, scale = enc(flat, cfg.block_size, cfg.mantissa_bits, cfg.rounding)
    return {"mant": mant, "scale": scale, "shape": np.asarray(x.shape),
            "pad": np.asarray(pad), "block": np.asarray(cfg.block_size),
            "dtype": str(x.dtype)}


def decompress_array(blob: Dict[str, Any]) -> np.ndarray:
    _, dec = _codec()
    mant = np.asarray(blob["mant"], np.int8)
    out = dec(mant, np.asarray(blob["scale"], np.int8), int(blob["block"]))
    pad = int(blob["pad"])
    if pad:
        out = out[:-pad]
    return out.reshape(tuple(int(d) for d in np.asarray(blob["shape"]))).astype(
        blob["dtype"] if isinstance(blob["dtype"], str) else str(blob["dtype"]))


class Checkpointer:
    """Orbax-backed checkpoint manager with optional BFP-compressed
    optimizer/master state.

    ``async_save=True`` writes in a background thread (orbax
    AsyncCheckpointer): ``save`` returns as soon as the host copy is
    snapshotted, so checkpoint IO overlaps the next training steps; call
    ``wait_until_finished()`` (or just the next ``save``, which waits on
    the previous one) before reading the files.  Caveat: with ``compress``
    set, the BFP encode of the master/optimizer shards still runs
    synchronously inside ``save`` — only the file IO overlaps — so for
    GB-scale compressed state the async win is the write, not the
    encode."""

    def __init__(self, directory: str,
                 compress: Optional[BFPConfig] = None,
                 async_save: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.compress = compress
        self._async = async_save
        self._ckptr = (ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
                       if async_save else ocp.PyTreeCheckpointer())

    _LAYOUT_FILE = "layer_layout.json"

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def _layout_path(self, step: int) -> str:
        # INSIDE the step directory: the sidecar describes that step's
        # bytes and travels (and dies) with them.  A directory-scoped
        # sidecar lets a later plain-order save clear the layout an
        # earlier step's restore still depends on — restore(earlier)
        # would then silently permute layers.
        return os.path.join(self._path(step), self._LAYOUT_FILE)

    def _legacy_layout_path(self) -> str:
        # directory-scoped sidecar location used by older revisions; read
        # as a fallback and migrated into the step dirs on the next save
        return os.path.join(self.directory, self._LAYOUT_FILE)

    def _migrate_legacy_layout(self) -> None:
        """Copy a directory-scoped sidecar (older revisions wrote one per
        DIRECTORY) into every existing step dir that lacks its own, then
        remove it — after which the per-step rules apply uniformly and a
        plain-order save can no longer strand older steps layout-less."""
        legacy = self._legacy_layout_path()
        if not os.path.exists(legacy):
            return
        with open(legacy) as f:
            layout = json.load(f)
        for d in os.listdir(self.directory):
            if re.fullmatch(r"step_\d+", d):
                p = os.path.join(self.directory, d, self._LAYOUT_FILE)
                if not os.path.exists(p):
                    with open(p, "w") as f:
                        json.dump(layout, f)
        os.remove(legacy)

    def _apply_sidecar(self, step: int,
                       layout: Optional[Dict[str, Any]]) -> None:
        """Write (or, for ``None``, remove) step's sidecar on disk."""
        if layout is not None:
            os.makedirs(self._path(step), exist_ok=True)
            with open(self._layout_path(step), "w") as f:
                json.dump(layout, f)
        else:
            try:
                os.remove(self._layout_path(step))
            except FileNotFoundError:
                pass

    # -- async-save sidecar staging -----------------------------------------
    # The sidecar must live INSIDE the step dir, but an async save only
    # materializes that dir when the background write commits (orbax
    # writes a tmp dir and renames).  So save() stages the layout in a
    # DURABLE pending file next to the step dir — not in memory — and any
    # sync point moves it in.  A crash between commit and flush leaves
    # checkpoint + pending file on disk, and saved_layout()/restore()
    # honor the pending file, so the layout is never silently lost (the
    # silent-permute hazard the sidecar exists to prevent).

    def _pending_path(self, step: int) -> str:
        return os.path.join(self.directory,
                            f"step_{step:08d}.layout-pending.json")

    def _stage_sidecar(self, step: int,
                       layout: Optional[Dict[str, Any]]) -> None:
        tmp = self._pending_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"layout": layout}, f)
        os.replace(tmp, self._pending_path(step))

    def _read_pending(self, step: int) -> Optional[Dict[str, Any]]:
        """The staged {'layout': ...} dict, or None if nothing is staged."""
        try:
            with open(self._pending_path(step)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def _flush_pending_sidecars(self, skip_step: Optional[int] = None
                                ) -> None:
        """Move staged sidecars into their (now committed) step dirs."""
        for fname in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)\.layout-pending\.json", fname)
            if not m:
                continue
            step = int(m.group(1))
            if step == skip_step or not os.path.isdir(self._path(step)):
                continue                 # not committed yet: stays staged
            pending = self._read_pending(step)
            if pending is not None:
                self._apply_sidecar(step, pending["layout"])
            os.remove(self._pending_path(step))

    def save_layout(self, layout: Dict[str, Any],
                    step: int) -> Dict[str, Any]:
        """Record how step ``step``'s flat master bytes are ordered (e.g.
        the interleaved-1F1B layer permutation: layers_order / pp /
        virtual_stages).  A checkpoint that carries a layout sidecar can
        only be restored by a caller that declares a MATCHING layout —
        ``restore`` enforces it — so bytes can never be silently
        reinterpreted under a different pp/v/schedule.  (Standalone use:
        waits out any in-flight async save first; ``save(layout=...)``
        defers instead and never blocks.)"""
        self.wait_until_finished()
        self._apply_sidecar(step, layout)
        return layout

    def saved_layout(self, step: Optional[int] = None
                     ) -> Optional[Dict[str, Any]]:
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        pending = self._read_pending(step)     # async save not yet flushed
        if pending is not None:
            return pending["layout"]
        if os.path.exists(self._layout_path(step)):
            with open(self._layout_path(step)) as f:
                return json.load(f)
        # pre-migration checkpoint: a directory-scoped sidecar governs
        # every step that has no per-step sidecar of its own
        legacy = self._legacy_layout_path()
        if os.path.isdir(self._path(step)) and os.path.exists(legacy):
            with open(legacy) as f:
                return json.load(f)
        return None

    def _check_layout(self, step: int,
                      expect: Optional[Dict[str, Any]]) -> None:
        saved = self.saved_layout(step)
        if saved is None and expect is None:
            return
        if saved is None:
            raise ValueError(
                f"restore declared layout {expect} but the checkpoint at "
                f"{self._path(step)} has no {self._LAYOUT_FILE} sidecar — "
                "it was saved in plain model order; drop expect_layout or "
                "re-save with save_layout()")
        if expect is None:
            raise ValueError(
                f"checkpoint at {self._path(step)} carries a layout "
                f"sidecar {saved} (its flat masters are NOT in model "
                "order); pass expect_layout= with the run's matching "
                "pp/virtual_stages/schedule to restore()")
        mismatched = {k: (saved.get(k), expect.get(k))
                      for k in set(saved) | set(expect)
                      if saved.get(k) != expect.get(k)}
        if mismatched:
            raise ValueError(
                "checkpoint layout mismatch (saved vs requested): "
                f"{mismatched} — restoring these bytes under the requested "
                "pp/virtual_stages/schedule would silently permute layers")

    def save(self, step: int, state,
             layout: Optional[Dict[str, Any]] = None) -> str:
        """Persist a trainer state.  TRAINER STATES (NamedTuples) carrying
        a flat master copy (w_own / w_master) drop their working ``params``
        tree: every trainer's ``restore_state`` rematerializes params from
        the masters, so persisting both would double checkpoint size (and
        wipe out the BFP compression win for bf16 models).  Plain dicts are
        saved verbatim — the masters-only heuristic never applies to user
        payloads whose keys merely resemble a trainer state's."""
        is_trainer_state = hasattr(state, "_asdict")
        tree = dict(state._asdict()) if is_trainer_state else state
        if is_trainer_state and "params" in tree and (
                "w_own" in tree or "w_master" in tree):
            tree = {k: v for k, v in tree.items() if k != "params"}
        if is_trainer_state and ("w_own" in tree or "w_master" in tree):
            # the error-feedback residual (codec_state) is a bounded
            # per-device accumulator every restore_state re-zeros — for a
            # top-k run it is n x full-model f32, so persisting it would
            # balloon the checkpoint ~(n+1)x for bytes thrown away on
            # restore (EF is self-healing; see TrainState.codec_state)
            tree = {k: v for k, v in tree.items() if k != "codec_state"}
        tree = jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
        if self.compress is not None and isinstance(tree, dict):
            for key in ("w_own", "w_master"):
                if key in tree:
                    tree[key] = compress_array(tree[key], self.compress)
            if "opt_state" in tree:
                tree["opt_state"] = {
                    k: compress_array(v, self.compress)
                    for k, v in tree["opt_state"].items()}
        self._migrate_legacy_layout()
        path = self._path(step)
        # layout=None on a force=True re-save of the SAME step must clear
        # that step's earlier sidecar (plain-order bytes must never
        # validate against a stale layout); other steps' sidecars are
        # theirs and stay untouched
        if self._async:
            # stage the sidecar durably BEFORE the background write: a
            # crash between the commit and the next sync point must leave
            # the layout recoverable next to the committed bytes
            self._stage_sidecar(step, layout)
        self._ckptr.save(path, tree, force=True)
        if self._async:
            # orbax serialized any EARLIER async save before starting this
            # one, so earlier staged sidecars are committed — flush them
            self._flush_pending_sidecars(skip_step=step)
        else:
            self._apply_sidecar(step, layout)
        return path

    def restore(self, step: int,
                expect_layout: Optional[Dict[str, Any]] = None):
        self.wait_until_finished()       # commit in-flight saves + sidecars
        self._check_layout(step, expect_layout)
        tree = self._ckptr.restore(self._path(step))
        if self.compress is not None:
            for key in ("w_own", "w_master"):
                if key in tree and isinstance(tree[key], dict):
                    tree[key] = decompress_array(tree[key])
            if "opt_state" in tree:
                tree["opt_state"] = {
                    k: decompress_array(v) if isinstance(v, dict) else v
                    for k, v in tree["opt_state"].items()}
        return tree

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed to disk,
        then flush the committed steps' staged layout sidecars."""
        if hasattr(self._ckptr, "wait_until_finished"):
            self._ckptr.wait_until_finished()
        self._flush_pending_sidecars()

    def latest_step(self) -> Optional[int]:
        # ignore orbax atomic-write temp dirs (step_N.orbax-checkpoint-tmp-*)
        # left behind by an interrupted save — this is the crash-recovery path
        steps = []
        for d in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)", d)
            if m:
                steps.append(int(m.group(1)))
        return max(steps) if steps else None
