"""Per-bucket magnitude top-k with error feedback (SparCML family,
arXiv:1802.02021 / 1802.08021).

The gradient is viewed as independent buckets of ``bucket_elems``
consecutive elements; each bucket keeps only its ``k`` largest-magnitude
entries.  The wire payload per bucket is (f32 values [k], int16 indices
[k]) — 6 bytes per kept element, so the rate is tunable by k alone
(defaults: 512-element buckets, k=64 -> 5.33x vs f32).  Bucketing bounds
both the selection cost (k-select over 512, not over the whole model) and
the worst-case information loss per region of the vector — the same
reasoning as SparCML's blocked top-k — and makes slicing safe: any ring
slice that is a whole number of buckets quantizes identically
(`Codec.sliceable`).

Top-k is NOT a bounded-error codec: a one-shot pass can drop almost all
of a bucket's mass (declared ``error_bound = 1.0``, which the integrity
layer maps to its gross-corruption cap — see chaos.integrity_tol).  It
converges because of ERROR FEEDBACK: the dropped residual ``r`` is carried
in the train state and re-added to the next step's gradient, so every
coordinate is eventually transmitted (encode sees ``g + r``; what it drops
becomes the new ``r``).  The trainers thread this through
``TrainState.codec_state`` / ``FSDPState.codec_state``.

Tie-breaking is part of the bit spec: ``lax.top_k`` returns equal values
in ascending index order, which `compress.golden.topk_encode` reproduces
with a stable argsort — the JAX and numpy implementations must agree bit
for bit (tests/test_codec.py).

No Pallas kernel: the payload is index-gathered, and ``lax.top_k``
already lowers to the TPU's native sort network — a hand kernel would
re-implement that sort for zero wire-byte savings.  The VPU-shaped codecs
(bfp, int8) are where the Pallas encode/decode kernels live.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .base import Codec, DTypeLike, register


@register
class TopKCodec(Codec):
    """Per-bucket magnitude top-k, error-feedback by default."""

    name = "topk"
    idempotent = True          # re-selecting a k-sparse bucket is exact
    supports_fused = False

    def __init__(self, bucket_elems: int = 512, k: int = 64,
                 error_feedback: bool = True) -> None:
        assert 0 < k <= bucket_elems, (k, bucket_elems)
        assert bucket_elems <= 32768, "int16 wire indices"
        self.bucket_elems = int(bucket_elems)
        self.k = int(k)
        self.error_feedback = bool(error_feedback)

    # -- wire transform -----------------------------------------------------

    def encode(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        B = self.bucket_elems
        assert x.shape[0] % B == 0, (x.shape, B)
        xb = x.astype(jnp.float32).reshape(-1, B)
        _, idx = lax.top_k(jnp.abs(xb), self.k)       # ties: lowest index
        vals = jnp.take_along_axis(xb, idx, axis=-1)
        return vals, idx.astype(jnp.int16)

    def decode(self, payload: Tuple[jax.Array, ...], n_elems: int,
               dtype: DTypeLike = jnp.float32) -> jax.Array:
        vals, idx = payload
        B = self.bucket_elems
        nb = n_elems // B
        rows = jnp.arange(nb, dtype=jnp.int32)[:, None]
        out = jnp.zeros((nb, B), jnp.float32)
        # top-k indices are distinct within a bucket, so set (not add)
        out = out.at[rows, idx.astype(jnp.int32)].set(vals)
        return out.reshape(n_elems).astype(dtype)

    # -- structure ----------------------------------------------------------

    @property
    def pad_elems(self) -> int:
        return self.bucket_elems

    # -- declared accuracy / rate ------------------------------------------

    @property
    def error_bound(self) -> float:
        # a dropped coordinate can equal the bucket max (ties at the k-th
        # magnitude): top-k is unbounded-relative-error by design; the
        # residual carry, not a per-pass bound, is the accuracy story
        return 1.0

    def wire_bytes(self, n_elems: int) -> int:
        assert n_elems % self.bucket_elems == 0
        return (n_elems // self.bucket_elems) * self.k * (4 + 2)

    def describe(self) -> Dict[str, Any]:
        d = super().describe()
        d.update(bucket_elems=self.bucket_elems, k=self.k,
                 density=round(self.k / self.bucket_elems, 4))
        return d
