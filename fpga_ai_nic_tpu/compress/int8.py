"""Per-block-scaled int8 quantization with stochastic rounding — the
EQuARX-style (arXiv:2506.17615) low-bit quantized all-reduce codec.

Wire format per block of ``block_size`` f32 values: int8 quantized values
plus ONE bf16 linear scale (``scale = bf16(max|x| / 127)``; all-zero
blocks get scale 1.0 so decode is exact).  Unlike BFP's power-of-two
shared exponent, the linear scale uses the full int8 range on every block
— tighter error per bit at the cost of a 2-byte (not 1-byte) scale:
4B/(B+2) vs f32, 3.56x at the default B=16.

The scale is bf16 (EQuARX's own choice) for a reason beyond rate: the
decode product ``q * scale`` then has <= 15 significand bits — EXACTLY
representable in f32 — so the multiply never rounds, which makes it
FMA-IMMUNE: XLA:CPU freely contracts a*b+c into fused multiply-adds
(even across lax.optimization_barrier), and an inexact decode multiply
fused with the ring's accumulate would change bits vs the numpy golden
and make sliced/whole hops diverge.  Exact multiplies are the same
immunity BFP gets from power-of-two scales; any future codec whose
decode ends in an INEXACT op will hit this wall (measured here first on
the f32-scale draft of this codec).

Rounding:
  - "stochastic" (default; EQuARX §3): ``q = floor(x/scale + u)`` with
    u ~ U[0,1), which is UNBIASED — E[decode] = x — so quantization noise
    averages out across devices and steps instead of accumulating as bias.
  - "nearest": deterministic round-to-nearest; half the worst-case error,
    but biased on the wire's repeated-requantization path.

Determinism (the golden-compare contract): u is NOT drawn from a stateful
PRNG — it is a counter-free hash of each value's own f32 BIT PATTERN mixed
with the codec seed (murmur3 finalizer).  That keeps every pass
reproducible, makes the numpy golden (`compress.golden.int8_encode`) bit-
exact against both backends, and — because u depends on the value, not on
the element's position — makes ring slicing a pure schedule change: a
sliced hop sees the same values, hence the same u, hence the same bits
(`Codec.sliceable`).

Backends, mirroring `ops.bfp` / `ops.bfp_pallas`:
  - "xla" (default): consecutive-element blocks ("flat" layout) — golden
    bit-exact on every platform.
  - "pallas": fused VMEM encode/decode kernels with LANE-COLUMN blocks
    (the "sublane" layout — block max is a sublane reduction on the VPU),
    golden bit-exact vs layout="sublane".
  - "auto": pallas on TPU when the payload tiles onto (block, 128) lanes.
Same rate and error bound either way; the block PARTITION differs, so the
two backends are distinct bit streams (exactly BFP's xla/pallas story).

Not idempotent: decode lands off the next pass's grid (the re-quantized
block max shifts the scale), so repeated requantization adds bounded noise
per pass rather than being a projection.  The ring all-gather is unaffected
(one encode, payload forwarded verbatim); the reduce-scatter's per-hop
requantization noise is covered by ``error_bound`` and measured end-to-end
by evals/codec_convergence.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .base import Codec, DTypeLike, register
from ..ops import bfp_pallas as _bfp_pl
from ..ops.bfp_pallas import LANES


def _hash_u01(bits: jax.Array, seed: int) -> jax.Array:
    """uint32 value bits -> deterministic pseudo-uniform f32 in [0, 1).

    murmur3 finalizer over (bits ^ seed-stamp); the top 24 bits scale to
    [0, 1 - 2^-24] exactly in f32.  The numpy golden twin is
    compress.golden.hash_u01 — constants are the bit spec."""
    z = bits ^ jnp.uint32((seed * 0x9E3779B9) & 0xFFFFFFFF)
    z = z ^ (z >> 16)
    z = z * jnp.uint32(0x85EBCA6B)
    z = z ^ (z >> 13)
    z = z * jnp.uint32(0xC2B2AE35)
    z = z ^ (z >> 16)
    return (z >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)


# ---------------------------------------------------------------------------
# XLA backend ("flat" layout: consecutive elements per block)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("block_size", "rounding",
                                             "seed"))
def int8_encode(x: jax.Array, block_size: int = 16,
                rounding: str = "stochastic",
                seed: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Flat f32/bf16 [n] (n % block == 0) -> (int8 q [n], bf16 scale
    [n/block])."""
    x = x.astype(jnp.float32)
    xb = x.reshape(-1, block_size)
    maxabs = jnp.max(jnp.abs(xb), axis=-1)
    # multiply-by-reciprocal IS the spec (not a /127 the compiler may or
    # may not strength-reduce), and the bf16-ROUNDED scale is what both
    # sides use (encode divides by it, decode multiplies by it) — the
    # golden must match bit-for-bit
    scale = jnp.where(maxabs > 0, maxabs * jnp.float32(1.0 / 127.0),
                      jnp.float32(1.0)).astype(jnp.bfloat16)
    v = xb / scale.astype(jnp.float32)[:, None]
    if rounding == "stochastic":
        bits = lax.bitcast_convert_type(x, jnp.uint32).reshape(xb.shape)
        v = jnp.floor(v + _hash_u01(bits, seed))
    else:
        v = jnp.round(v)
    q = jnp.clip(v, -127.0, 127.0).astype(jnp.int8)
    return q.reshape(x.shape), scale


@functools.partial(jax.jit, static_argnames=("block_size", "dtype"))
def int8_decode(q: jax.Array, scale: jax.Array, block_size: int = 16,
                dtype: DTypeLike = jnp.float32) -> jax.Array:
    qb = q.reshape(-1, block_size).astype(jnp.float32)
    # int8 x bf16 -> <= 15 significand bits: this multiply is EXACT in
    # f32 (never rounds), hence FMA-safe — see module docstring
    return (qb * scale.astype(jnp.float32)[:, None]).reshape(q.shape).astype(
        dtype)


# ---------------------------------------------------------------------------
# Pallas backend ("sublane" layout: lane-column blocks, as bfp_pallas)
# ---------------------------------------------------------------------------

def _encode_kernel(x_ref: Any, q_ref: Any, scale_ref: Any, *,
                   block_size: int, rounding: str,
                   seed: int) -> None:
    from jax.experimental.pallas import tpu as pltpu
    x = x_ref[:]                                   # (T*B, 128) f32
    T = x.shape[0] // block_size
    maxabs = jnp.max(jnp.abs(x).reshape(T, block_size, LANES), axis=1)
    scale = jnp.where(maxabs > 0, maxabs * jnp.float32(1.0 / 127.0),
                      jnp.float32(1.0)).astype(jnp.bfloat16)  # (T, 128)
    sf = scale.astype(jnp.float32)
    v = x / _bfp_pl._bcast_blocks(sf, block_size, "repeat")
    if rounding == "stochastic":
        v = jnp.floor(v + _hash_u01(pltpu.bitcast(x, jnp.uint32), seed))
    else:
        v = jnp.round(v)
    q_ref[:] = jnp.clip(v, -127.0, 127.0).astype(jnp.int8)
    scale_ref[:] = scale


def _decode_kernel(q_ref: Any, scale_ref: Any, out_ref: Any, *,
                   block_size: int) -> None:
    q = q_ref[:].astype(jnp.float32)
    sf = scale_ref[:].astype(jnp.float32)
    out_ref[:] = q * _bfp_pl._bcast_blocks(sf, block_size, "repeat")


def int8_encode_pallas(x: jax.Array, block_size: int = 16,
                       rounding: str = "stochastic", seed: int = 0,
                       interpret: Optional[bool] = None,
                       tiles_per_step: int = _bfp_pl._DEF_TILES
                       ) -> Tuple[jax.Array, jax.Array]:
    """Sublane-layout fused encode (bit spec: golden.int8_encode with
    layout="sublane").  Un-jitted, callable inside vma-checked shard_maps
    — same contract as bfp_pallas.bfp_encode_inline."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .. import compat
    if interpret is None:
        interpret = not _bfp_pl._is_tpu()
    n = x.shape[0]
    assert n % (block_size * LANES) == 0, (n, block_size * LANES)
    x2 = x.astype(jnp.float32).reshape(-1, LANES)
    n_tiles = x2.shape[0] // block_size
    t, steps = _bfp_pl._grid(n_tiles, block_size, tiles_per_step)
    q, scale = pl.pallas_call(
        functools.partial(_encode_kernel, block_size=block_size,
                          rounding=rounding, seed=seed),
        grid=(steps,),
        in_specs=[pl.BlockSpec((t * block_size, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((t * block_size, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            compat.shape_dtype_struct(x2.shape, jnp.int8,
                                      vma=jax.typeof(x2).vma),
            compat.shape_dtype_struct((n_tiles, LANES), jnp.bfloat16,
                                      vma=jax.typeof(x2).vma),
        ],
        interpret=interpret,
    )(x2)
    return q.reshape(n), scale.reshape(n // block_size)


def int8_decode_pallas(q: jax.Array, scale: jax.Array, block_size: int = 16,
                       dtype: DTypeLike = jnp.float32,
                       interpret: Optional[bool] = None,
                       tiles_per_step: int = _bfp_pl._DEF_TILES) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .. import compat
    if interpret is None:
        interpret = not _bfp_pl._is_tpu()
    n = q.shape[0]
    q2 = q.reshape(-1, LANES)
    s2 = scale.reshape(-1, LANES)
    t, steps = _bfp_pl._grid(s2.shape[0], block_size, tiles_per_step)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_size=block_size),
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((t * block_size, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t * block_size, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct(
            q2.shape, jnp.float32,
            vma=jax.typeof(q2).vma | jax.typeof(s2).vma),
        interpret=interpret,
    )(q2, s2)
    return out.reshape(n).astype(dtype)


# ---------------------------------------------------------------------------
# the codec
# ---------------------------------------------------------------------------

@register
class Int8Codec(Codec):
    """Per-block linear int8, stochastic rounding (see module docstring)."""

    name = "int8"
    idempotent = False
    supports_fused = False     # fused ring frames carry int8 SCALES (BFP)

    def __init__(self, block_size: int = 16, rounding: str = "stochastic",
                 seed: int = 0, backend: str = "xla",
                 error_feedback: bool = False) -> None:
        assert rounding in ("stochastic", "nearest"), rounding
        assert backend in ("xla", "pallas", "auto"), backend
        assert block_size >= 2
        self.block_size = int(block_size)
        self.rounding = rounding
        self.seed = int(seed)
        self.backend = backend
        self.error_feedback = bool(error_feedback)

    def _use_pallas(self, n_elems: int) -> bool:
        return self.backend == "pallas" or (
            self.backend == "auto" and _bfp_pl._is_tpu()
            and n_elems % (self.block_size * LANES) == 0)

    # -- wire transform -----------------------------------------------------

    def encode(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        if self._use_pallas(x.shape[0]):
            return tuple(int8_encode_pallas(x, self.block_size,
                                            self.rounding, self.seed))
        return tuple(int8_encode(x, self.block_size, self.rounding,
                                 self.seed))

    def decode(self, payload: Tuple[jax.Array, ...], n_elems: int,
               dtype: DTypeLike = jnp.float32) -> jax.Array:
        q, scale = payload
        if self._use_pallas(n_elems):
            return int8_decode_pallas(q, scale, self.block_size, dtype)
        return int8_decode(q, scale, self.block_size, dtype)

    # -- structure ----------------------------------------------------------

    @property
    def pad_elems(self) -> int:
        return self.block_size

    def sliceable(self, chunk_elems: int,
                  slice_elems: Optional[int]) -> bool:
        return (super().sliceable(chunk_elems, slice_elems)
                # same backend-consistency rules as BFPCodec: the block
                # partition must not depend on how the chunk is sliced
                and self._use_pallas(slice_elems) == self._use_pallas(
                    chunk_elems)
                and not (self._use_pallas(slice_elems)
                         and slice_elems % (self.block_size * LANES)))

    # -- declared accuracy / rate ------------------------------------------

    @property
    def error_bound(self) -> float:
        # grid step = bf16(blockmax/127) <= (1 + 2^-8) * blockmax/127;
        # stochastic floor can land a full step away, nearest half a step
        step = (1.0 + 2.0 ** -8) / 127.0
        return step if self.rounding == "stochastic" else step / 2

    def wire_bytes(self, n_elems: int) -> int:
        assert n_elems % self.block_size == 0
        return n_elems + 2 * (n_elems // self.block_size)

    def describe(self) -> Dict[str, Any]:
        d = super().describe()
        d.update(block_size=self.block_size, rounding=self.rounding,
                 seed=self.seed, backend=self.backend)
        return d
