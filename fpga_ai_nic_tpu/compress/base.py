"""Codec protocol + registry — the pluggable gradient-compression layer.

The reference NIC ships exactly one wire codec, baked into RTL
(hw/bfp_adapter.sv sitting between the ring engine and the MAC); our
reproduction initially hard-wired the same choice by name through
`ops.ring`, `ops.ring_pallas` and `runtime.chaos`.  But BFP is one point
in a family: SparCML (arXiv:1802.08021) ships sparse top-k with error
feedback, EQuARX (arXiv:2506.17615) ships low-bit block-quantized
all-reduce.  This module is the seam that lets all of them ride the same
ring: a formal ``Codec`` contract, a name registry, and the resolution
rule from ``CollectiveConfig(codec=..., codec_opts=...)``.

The contract (what the ring, the trainers and the integrity layer each
rely on):

  encode/decode   The wire transform.  ``encode`` maps a flat f32 vector
                  to a TUPLE of arrays (the hop payload — each element is
                  ``lax.ppermute``d independently); ``decode`` inverts it
                  given the element count.  Both run inside jit/shard_map.
  pad_elems       Element alignment of one independent compression unit
                  (BFP block / top-k bucket / int8 block).  Flat vectors
                  are padded so each device chunk is a whole number of
                  units (`ops.fused_update.pad_multiple`), and ring slices
                  must be unit multiples so slicing changes the schedule,
                  never the bits (`sliceable`).
  error_feedback  Whether the codec wants a residual carried across steps
                  (``state_init``): lossy-by-design codecs (top-k) re-add
                  what they dropped to the next step's gradient, turning
                  a biased one-shot truncation into an unbiased-in-the-
                  limit stream (SparCML §3).  The trainers thread the
                  residual through ``TrainState``/``FSDPState``.
  error_bound     Declared per-pass worst-case |x - decode(encode(x))| as
                  a fraction of the unit's max-abs value.  The collective
                  integrity layer (`runtime.chaos.integrity_tol`) derives
                  its corruption-vs-quantization tripwire from THIS
                  number instead of special-casing BFP: anything outside
                  the declared bound is corruption, anything inside must
                  pass.
  idempotent      decode∘encode is a projection (second pass is bit-
                  identical).  The ring all-gather forwards one encoded
                  payload verbatim either way, but idempotent codecs
                  additionally guarantee sliced/unsliced hop equality
                  under re-encoding and exact EF fixed points.
  supports_fused  May ride the fused Pallas ring (`ops.ring_pallas`),
                  whose wire frames are int8 mantissa+scale tiles — today
                  BFP only; the registry check turns a silent fallback
                  into a fail-fast config error.

Every codec must have a numpy golden twin in `compress.golden`, and the
JAX implementation must match it bit for bit (tests/test_codec.py) — the
same spec-first discipline as `ops.bfp_golden`/`ops.ring_golden`.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Mapping, Optional, Tuple, Type

import jax
import jax.numpy as jnp

# numpy/jax dtype designator (jax.typing.DTypeLike is unstable across the
# jaxlib versions this repo supports, so the alias stays loose on purpose)
DTypeLike = Any


class Codec(abc.ABC):
    """One gradient-compression wire format (see module docstring)."""

    #: registry key (class attribute; set by subclasses)
    name: str = ""
    #: decode∘encode is a projection: a second pass is bit-identical
    idempotent: bool = False
    #: carries an error-feedback residual across trainer steps
    error_feedback: bool = False
    #: may ride the fused Pallas ring kernels (ops.ring_pallas)
    supports_fused: bool = False

    # -- wire transform -----------------------------------------------------

    @abc.abstractmethod
    def encode(self, x: jax.Array) -> Tuple[jax.Array, ...]:
        """Flat f32/bf16 [n] (n % pad_elems == 0) -> payload tuple."""

    @abc.abstractmethod
    def decode(self, payload: Tuple[jax.Array, ...], n_elems: int,
               dtype: DTypeLike = jnp.float32) -> jax.Array:
        """Payload tuple -> flat [n_elems] in ``dtype``."""

    def roundtrip(self, x: jax.Array) -> jax.Array:
        """decode(encode(x)) — the quantization one wire pass applies."""
        return self.decode(self.encode(x), x.shape[0], x.dtype)

    # -- structure ----------------------------------------------------------

    @property
    @abc.abstractmethod
    def pad_elems(self) -> int:
        """Elements per independent compression unit (alignment quantum)."""

    def sliceable(self, chunk_elems: int, slice_elems: Optional[int]) -> bool:
        """May a [chunk_elems] hop be streamed as [slice_elems] slices with
        IDENTICAL bits?  True only when slicing cannot change the unit
        partition (and actually splits the chunk)."""
        return (slice_elems is not None
                and chunk_elems > slice_elems
                and chunk_elems % slice_elems == 0
                and slice_elems % self.pad_elems == 0)

    # -- error-feedback residual -------------------------------------------

    def state_init(self, n_elems: int) -> Optional[jax.Array]:
        """Fresh residual carry for an [n_elems] gradient stream (None for
        codecs without error feedback)."""
        if not self.error_feedback:
            return None
        return jnp.zeros((n_elems,), jnp.float32)

    # -- declared accuracy / rate ------------------------------------------

    @property
    @abc.abstractmethod
    def error_bound(self) -> float:
        """Worst-case per-element |x - roundtrip(x)| as a fraction of the
        unit's max-abs value, for ONE encode/decode pass.  The integrity
        layer treats anything beyond this (x hop count, see
        runtime.chaos.integrity_tol) as corruption."""

    @abc.abstractmethod
    def wire_bytes(self, n_elems: int) -> int:
        """Bytes one encoded [n_elems] payload puts on the wire."""

    @property
    def compression_ratio_vs_f32(self) -> float:
        n = self.pad_elems
        return 4.0 * n / self.wire_bytes(n)

    # -- description --------------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        """Static facts for bench artifacts / docs tables."""
        return {
            "codec": self.name,
            "pad_elems": self.pad_elems,
            "compression_ratio_vs_f32":
                round(self.compression_ratio_vs_f32, 3),
            "error_bound": self.error_bound,
            "error_feedback": self.error_feedback,
            "idempotent": self.idempotent,
            "supports_fused": self.supports_fused,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"{type(self).__name__}({self.describe()})"


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Type[Codec]] = {}


def register(cls: Type[Codec]) -> Type[Codec]:
    """Class decorator: add a Codec subclass under ``cls.name``."""
    assert issubclass(cls, Codec) and cls.name, cls
    _REGISTRY[cls.name] = cls
    return cls


def available_codecs() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def get_codec(name: str, opts: Optional[Mapping[str, Any]] = None) -> Codec:
    """Instantiate a registered codec by name.

    Unknown names fail fast and NAME the alternatives — a config typo must
    die at construction, not at first collective trace (satellite of the
    codec-subsystem issue)."""
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown codec {name!r}: registered codecs are "
            f"{list(available_codecs())}")
    return _REGISTRY[name](**dict(opts or {}))


def resolve(coll: Any) -> Optional[Codec]:
    """The codec a CollectiveConfig asks for (None = uncompressed).

    Resolution order:
      - ``coll.codec`` names a registered codec; ``coll.codec_opts``
        (a (key, value) tuple-of-pairs, kept hashable for the frozen
        dataclass) are its constructor kwargs.  ``codec="bfp"`` honors a
        simultaneously-set ``coll.compression`` BFPConfig.
      - legacy: ``coll.compression`` alone still means BFP (the pre-
        subsystem spelling; every existing call site keeps working).
    """
    from .bfp import BFPCodec
    name = getattr(coll, "codec", None)
    if name:
        opts = dict(getattr(coll, "codec_opts", ()) or ())
        if name == "bfp" and coll.compression is not None:
            return BFPCodec(cfg=coll.compression, **opts)
        return get_codec(name, opts)
    if getattr(coll, "compression", None) is not None:
        return BFPCodec(cfg=coll.compression)
    return None


def as_codec(compression: Any) -> Optional[Codec]:
    """Normalize a ring-level ``compression=`` argument: None, a Codec, or
    (back-compat) a bare BFPConfig."""
    if compression is None or isinstance(compression, Codec):
        return compression
    from ..utils.config import BFPConfig
    if isinstance(compression, BFPConfig):
        from .bfp import BFPCodec
        return BFPCodec(cfg=compression)
    raise TypeError(
        f"compression must be None, a compress.Codec, or a BFPConfig; "
        f"got {type(compression).__name__}")
