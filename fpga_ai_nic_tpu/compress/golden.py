"""Numpy golden models for every registered codec + the codec-generic
ring golden — the bit-level spec the JAX implementations must match.

Same discipline as `ops.bfp_golden`/`ops.ring_golden` (which remain the
BFP spec and are reused here): the golden is the specification, the JAX/
Pallas code is an implementation, and tests/test_codec.py holds them
bit-for-bit equal — including tie-breaking (top-k) and the stochastic-
rounding hash (int8), which are therefore part of the contract, not
implementation accidents.

`ring_reduce_scatter`/`ring_all_gather`/`ring_all_reduce` here generalize
`ops.ring_golden` from "BFPConfig or None" to ANY (encode∘decode)
roundtrip callable, with the identical hop schedule and f32 add order —
so a single golden covers the codec x slice_elems matrix.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import numpy as np

from ..ops import bfp_golden

RoundtripFn = Callable[[np.ndarray], np.ndarray]


# ---------------------------------------------------------------------------
# top-k (spec for compress.topk.TopKCodec)
# ---------------------------------------------------------------------------

def topk_encode(x: np.ndarray, bucket_elems: int = 512,
                k: int = 64) -> Tuple[np.ndarray, np.ndarray]:
    """Flat f32 [n] -> (values f32 [nb, k], indices int16 [nb, k]).

    Tie rule (the lax.top_k contract): equal magnitudes keep ascending
    index order — reproduced by a STABLE argsort on the negated
    magnitudes."""
    x = np.asarray(x, np.float32)
    assert x.ndim == 1 and x.shape[0] % bucket_elems == 0
    xb = x.reshape(-1, bucket_elems)
    order = np.argsort(-np.abs(xb), axis=-1, kind="stable")[:, :k]
    vals = np.take_along_axis(xb, order, axis=-1)
    return vals, order.astype(np.int16)


def topk_decode(vals: np.ndarray, idx: np.ndarray, n_elems: int,
                bucket_elems: int = 512) -> np.ndarray:
    nb = n_elems // bucket_elems
    out = np.zeros((nb, bucket_elems), np.float32)
    rows = np.arange(nb)[:, None]
    out[rows, idx.astype(np.int64)] = vals
    return out.reshape(n_elems)


def topk_roundtrip(x: np.ndarray, bucket_elems: int = 512,
                   k: int = 64) -> np.ndarray:
    vals, idx = topk_encode(x, bucket_elems, k)
    return topk_decode(vals, idx, x.shape[0], bucket_elems)


# ---------------------------------------------------------------------------
# int8 (spec for compress.int8.Int8Codec)
# ---------------------------------------------------------------------------

def hash_u01(bits: np.ndarray, seed: int) -> np.ndarray:
    """Numpy twin of compress.int8._hash_u01 (murmur3 finalizer over the
    value bits ^ seed stamp); constants are the bit spec."""
    with np.errstate(over="ignore"):
        z = bits.astype(np.uint32) ^ np.uint32((seed * 0x9E3779B9)
                                               & 0xFFFFFFFF)
        z = z ^ (z >> np.uint32(16))
        z = z * np.uint32(0x85EBCA6B)
        z = z ^ (z >> np.uint32(13))
        z = z * np.uint32(0xC2B2AE35)
        z = z ^ (z >> np.uint32(16))
    return (z >> np.uint32(8)).astype(np.float32) * np.float32(2.0 ** -24)


def _to_bf16(x: np.ndarray) -> np.ndarray:
    """f32 -> bf16 (round-to-nearest-even), kept as ml_dtypes.bfloat16 —
    the exact cast jax's .astype(jnp.bfloat16) performs."""
    import ml_dtypes
    return x.astype(ml_dtypes.bfloat16)


def int8_encode(x: np.ndarray, block_size: int = 16,
                rounding: str = "stochastic", seed: int = 0,
                layout: str = "flat16"
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Flat f32 [n] -> (int8 q [n], bf16 scale [n/block]).  The bf16
    scale makes the decode product exact in f32 (<= 15 significand bits)
    — the FMA-immunity the spec requires; see compress.int8.

    layout: "flat16" = consecutive-element blocks (the XLA backend);
    "sublane" = lane-column blocks (the Pallas kernels) — reusing
    ops.bfp_golden's partition machinery so the two codecs share one
    layout definition."""
    x = np.ascontiguousarray(x, np.float32)
    xb = bfp_golden._to_blocks(x, block_size, layout)
    maxabs = np.abs(xb).max(axis=-1)
    # multiply-by-reciprocal + bf16 rounding is the bit spec
    # (see compress.int8)
    scale = _to_bf16(np.where(maxabs > 0, maxabs * np.float32(1.0 / 127.0),
                              np.float32(1.0)).astype(np.float32))
    v = xb / scale.astype(np.float32)[..., None]
    if rounding == "stochastic":
        bits = bfp_golden._to_blocks(x.view(np.uint32), block_size, layout)
        v = np.floor(v + hash_u01(bits, seed))
    elif rounding == "nearest":
        v = np.rint(v)
    else:
        raise ValueError(rounding)
    q = np.clip(v, -127.0, 127.0).astype(np.int8)
    return (bfp_golden._from_blocks(q, x.shape, block_size, layout),
            scale.reshape(-1))


def int8_decode(q: np.ndarray, scale: np.ndarray, block_size: int = 16,
                dtype: Any = np.float32,
                layout: str = "flat16") -> np.ndarray:
    qb = bfp_golden._to_blocks(np.asarray(q, np.int8), block_size,
                               layout).astype(np.float32)
    x = qb * np.asarray(scale).reshape(-1).astype(np.float32)[..., None]
    return bfp_golden._from_blocks(x, q.shape, block_size, layout).astype(
        dtype)


def int8_roundtrip(x: np.ndarray, block_size: int = 16,
                   rounding: str = "stochastic", seed: int = 0,
                   layout: str = "flat16") -> np.ndarray:
    q, s = int8_encode(x, block_size, rounding, seed, layout)
    return int8_decode(q, s, block_size, np.float32, layout)


# ---------------------------------------------------------------------------
# codec-generic roundtrip lookup
# ---------------------------------------------------------------------------

def roundtrip_fn(codec: Any) -> RoundtripFn:
    """The numpy golden roundtrip matching a compress.Codec instance's
    configuration (including backend/layout dispatch by payload size)."""
    from .bfp import BFPCodec, use_pallas
    from .int8 import Int8Codec
    from .topk import TopKCodec

    if isinstance(codec, BFPCodec):
        cfg = codec.cfg

        def rt(x: np.ndarray) -> np.ndarray:
            layout = ("sublane" if use_pallas(cfg, x.shape[0]) else "flat16")
            mant, se = bfp_golden.bfp_encode(
                x, cfg.block_size, cfg.mantissa_bits, cfg.rounding,
                layout=layout)
            return bfp_golden.bfp_decode(mant, se, cfg.block_size,
                                         layout=layout)
        return rt
    if isinstance(codec, TopKCodec):
        return lambda x: topk_roundtrip(x, codec.bucket_elems, codec.k)
    if isinstance(codec, Int8Codec):
        def rt(x: np.ndarray) -> np.ndarray:
            layout = ("sublane" if codec._use_pallas(x.shape[0])
                      else "flat16")
            return int8_roundtrip(x, codec.block_size, codec.rounding,
                                  codec.seed, layout)
        return rt
    raise TypeError(f"no golden model registered for {type(codec).__name__}")


# ---------------------------------------------------------------------------
# codec-generic ring golden (generalizes ops.ring_golden)
# ---------------------------------------------------------------------------

def _rt(x: np.ndarray, roundtrip: Optional[RoundtripFn]) -> np.ndarray:
    return x if roundtrip is None else roundtrip(np.asarray(x, np.float32))


def ring_reduce_scatter(shards: np.ndarray,
                        roundtrip: Optional[RoundtripFn] = None
                        ) -> np.ndarray:
    """[n, L] per-device inputs -> [n, L//n] owned reduced chunks, with
    ``roundtrip`` applied to every hop payload — the identical schedule and
    f32 add order as ops.ring_golden.ring_reduce_scatter (which this
    generalizes from BFP to any codec)."""
    n, L = shards.shape
    assert L % n == 0
    chunks = shards.reshape(n, n, L // n).astype(np.float32).copy()
    for s in range(n - 1):
        sends = [_rt(chunks[i, (i - s - 1) % n], roundtrip)
                 for i in range(n)]
        for i in range(n):
            chunks[i, (i - s - 2) % n] += sends[(i - 1) % n]
    return np.stack([chunks[i, i] for i in range(n)])


def ring_all_gather(owned: np.ndarray,
                    roundtrip: Optional[RoundtripFn] = None) -> np.ndarray:
    """[n, C] owned chunks -> [n, n*C] reassembled replicas.  The chunk is
    encoded ONCE on first send and the payload forwarded verbatim (decode
    of the same payload is deterministic), so replicas are identical even
    for non-idempotent codecs — matching ops.ring.ring_all_gather."""
    n, C = owned.shape
    out = np.zeros((n, n, C), np.float32)
    carry = np.stack([_rt(owned[i], roundtrip) for i in range(n)])
    for i in range(n):
        out[i, i] = carry[i]
    for s in range(n - 1):
        carry = carry[(np.arange(n) - 1) % n]
        for i in range(n):
            out[i, (i - s - 1) % n] = carry[i]
    return out.reshape(n, n * C)


def ring_all_reduce(shards: np.ndarray,
                    roundtrip: Optional[RoundtripFn] = None) -> np.ndarray:
    return ring_all_gather(ring_reduce_scatter(shards, roundtrip), roundtrip)
