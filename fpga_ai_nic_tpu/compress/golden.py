"""Numpy golden models for every registered codec + the codec-generic
ring golden — the bit-level spec the JAX implementations must match.

Same discipline as `ops.bfp_golden`/`ops.ring_golden` (which remain the
BFP spec and are reused here): the golden is the specification, the JAX/
Pallas code is an implementation, and tests/test_codec.py holds them
bit-for-bit equal — including tie-breaking (top-k) and the stochastic-
rounding hash (int8), which are therefore part of the contract, not
implementation accidents.

`ring_reduce_scatter`/`ring_all_gather`/`ring_all_reduce` here generalize
`ops.ring_golden` from "BFPConfig or None" to ANY (encode∘decode)
roundtrip callable, with the identical hop schedule and f32 add order —
so a single golden covers the codec x slice_elems matrix.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import numpy as np

from ..ops import bfp_golden

RoundtripFn = Callable[[np.ndarray], np.ndarray]


# ---------------------------------------------------------------------------
# top-k (spec for compress.topk.TopKCodec)
# ---------------------------------------------------------------------------

def topk_encode(x: np.ndarray, bucket_elems: int = 512,
                k: int = 64) -> Tuple[np.ndarray, np.ndarray]:
    """Flat f32 [n] -> (values f32 [nb, k], indices int16 [nb, k]).

    Tie rule (the lax.top_k contract): equal magnitudes keep ascending
    index order — reproduced by a STABLE argsort on the negated
    magnitudes."""
    x = np.asarray(x, np.float32)
    assert x.ndim == 1 and x.shape[0] % bucket_elems == 0
    xb = x.reshape(-1, bucket_elems)
    order = np.argsort(-np.abs(xb), axis=-1, kind="stable")[:, :k]
    vals = np.take_along_axis(xb, order, axis=-1)
    return vals, order.astype(np.int16)


def topk_decode(vals: np.ndarray, idx: np.ndarray, n_elems: int,
                bucket_elems: int = 512) -> np.ndarray:
    nb = n_elems // bucket_elems
    out = np.zeros((nb, bucket_elems), np.float32)
    rows = np.arange(nb)[:, None]
    out[rows, idx.astype(np.int64)] = vals
    return out.reshape(n_elems)


def topk_roundtrip(x: np.ndarray, bucket_elems: int = 512,
                   k: int = 64) -> np.ndarray:
    vals, idx = topk_encode(x, bucket_elems, k)
    return topk_decode(vals, idx, x.shape[0], bucket_elems)


# ---------------------------------------------------------------------------
# int8 (spec for compress.int8.Int8Codec)
# ---------------------------------------------------------------------------

def hash_u01(bits: np.ndarray, seed: int) -> np.ndarray:
    """Numpy twin of compress.int8._hash_u01 (murmur3 finalizer over the
    value bits ^ seed stamp); constants are the bit spec."""
    with np.errstate(over="ignore"):
        z = bits.astype(np.uint32) ^ np.uint32((seed * 0x9E3779B9)
                                               & 0xFFFFFFFF)
        z = z ^ (z >> np.uint32(16))
        z = z * np.uint32(0x85EBCA6B)
        z = z ^ (z >> np.uint32(13))
        z = z * np.uint32(0xC2B2AE35)
        z = z ^ (z >> np.uint32(16))
    return (z >> np.uint32(8)).astype(np.float32) * np.float32(2.0 ** -24)


def _to_bf16(x: np.ndarray) -> np.ndarray:
    """f32 -> bf16 (round-to-nearest-even), kept as ml_dtypes.bfloat16 —
    the exact cast jax's .astype(jnp.bfloat16) performs."""
    import ml_dtypes
    return x.astype(ml_dtypes.bfloat16)


def int8_encode(x: np.ndarray, block_size: int = 16,
                rounding: str = "stochastic", seed: int = 0,
                layout: str = "flat16"
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Flat f32 [n] -> (int8 q [n], bf16 scale [n/block]).  The bf16
    scale makes the decode product exact in f32 (<= 15 significand bits)
    — the FMA-immunity the spec requires; see compress.int8.

    layout: "flat16" = consecutive-element blocks (the XLA backend);
    "sublane" = lane-column blocks (the Pallas kernels) — reusing
    ops.bfp_golden's partition machinery so the two codecs share one
    layout definition."""
    x = np.ascontiguousarray(x, np.float32)
    xb = bfp_golden._to_blocks(x, block_size, layout)
    maxabs = np.abs(xb).max(axis=-1)
    # multiply-by-reciprocal + bf16 rounding is the bit spec
    # (see compress.int8)
    scale = _to_bf16(np.where(maxabs > 0, maxabs * np.float32(1.0 / 127.0),
                              np.float32(1.0)).astype(np.float32))
    v = xb / scale.astype(np.float32)[..., None]
    if rounding == "stochastic":
        bits = bfp_golden._to_blocks(x.view(np.uint32), block_size, layout)
        v = np.floor(v + hash_u01(bits, seed))
    elif rounding == "nearest":
        v = np.rint(v)
    else:
        raise ValueError(rounding)
    q = np.clip(v, -127.0, 127.0).astype(np.int8)
    return (bfp_golden._from_blocks(q, x.shape, block_size, layout),
            scale.reshape(-1))


def int8_decode(q: np.ndarray, scale: np.ndarray, block_size: int = 16,
                dtype: Any = np.float32,
                layout: str = "flat16") -> np.ndarray:
    qb = bfp_golden._to_blocks(np.asarray(q, np.int8), block_size,
                               layout).astype(np.float32)
    x = qb * np.asarray(scale).reshape(-1).astype(np.float32)[..., None]
    return bfp_golden._from_blocks(x, q.shape, block_size, layout).astype(
        dtype)


def int8_roundtrip(x: np.ndarray, block_size: int = 16,
                   rounding: str = "stochastic", seed: int = 0,
                   layout: str = "flat16") -> np.ndarray:
    q, s = int8_encode(x, block_size, rounding, seed, layout)
    return int8_decode(q, s, block_size, np.float32, layout)


# ---------------------------------------------------------------------------
# codec-generic roundtrip lookup
# ---------------------------------------------------------------------------

def roundtrip_fn(codec: Any) -> RoundtripFn:
    """The numpy golden roundtrip matching a compress.Codec instance's
    configuration (including backend/layout dispatch by payload size)."""
    from .bfp import BFPCodec, use_pallas
    from .int8 import Int8Codec
    from .topk import TopKCodec

    if isinstance(codec, BFPCodec):
        cfg = codec.cfg

        def rt(x: np.ndarray) -> np.ndarray:
            layout = ("sublane" if use_pallas(cfg, x.shape[0]) else "flat16")
            mant, se = bfp_golden.bfp_encode(
                x, cfg.block_size, cfg.mantissa_bits, cfg.rounding,
                layout=layout)
            return bfp_golden.bfp_decode(mant, se, cfg.block_size,
                                         layout=layout)
        return rt
    if isinstance(codec, TopKCodec):
        return lambda x: topk_roundtrip(x, codec.bucket_elems, codec.k)
    if isinstance(codec, Int8Codec):
        def rt(x: np.ndarray) -> np.ndarray:
            layout = ("sublane" if codec._use_pallas(x.shape[0])
                      else "flat16")
            return int8_roundtrip(x, codec.block_size, codec.rounding,
                                  codec.seed, layout)
        return rt
    raise TypeError(f"no golden model registered for {type(codec).__name__}")


# ---------------------------------------------------------------------------
# codec-generic ring golden (generalizes ops.ring_golden)
# ---------------------------------------------------------------------------

def _rt(x: np.ndarray, roundtrip: Optional[RoundtripFn]) -> np.ndarray:
    return x if roundtrip is None else roundtrip(np.asarray(x, np.float32))


def ring_reduce_scatter(shards: np.ndarray,
                        roundtrip: Optional[RoundtripFn] = None
                        ) -> np.ndarray:
    """[n, L] per-device inputs -> [n, L//n] owned reduced chunks, with
    ``roundtrip`` applied to every hop payload — the identical schedule and
    f32 add order as ops.ring_golden.ring_reduce_scatter (which this
    generalizes from BFP to any codec)."""
    n, L = shards.shape
    assert L % n == 0
    chunks = shards.reshape(n, n, L // n).astype(np.float32).copy()
    for s in range(n - 1):
        sends = [_rt(chunks[i, (i - s - 1) % n], roundtrip)
                 for i in range(n)]
        for i in range(n):
            chunks[i, (i - s - 2) % n] += sends[(i - 1) % n]
    return np.stack([chunks[i, i] for i in range(n)])


def ring_all_gather(owned: np.ndarray,
                    roundtrip: Optional[RoundtripFn] = None) -> np.ndarray:
    """[n, C] owned chunks -> [n, n*C] reassembled replicas.  The chunk is
    encoded ONCE on first send and the payload forwarded verbatim (decode
    of the same payload is deterministic), so replicas are identical even
    for non-idempotent codecs — matching ops.ring.ring_all_gather."""
    n, C = owned.shape
    out = np.zeros((n, n, C), np.float32)
    carry = np.stack([_rt(owned[i], roundtrip) for i in range(n)])
    for i in range(n):
        out[i, i] = carry[i]
    for s in range(n - 1):
        carry = carry[(np.arange(n) - 1) % n]
        for i in range(n):
            out[i, (i - s - 1) % n] = carry[i]
    return out.reshape(n, n * C)


def ring_all_reduce(shards: np.ndarray,
                    roundtrip: Optional[RoundtripFn] = None) -> np.ndarray:
    return ring_all_gather(ring_reduce_scatter(shards, roundtrip), roundtrip)


# ---------------------------------------------------------------------------
# hierarchical (intra x inter) 2-stage ring golden (spec for
# ops.ring_hier: raw f32 on the fast intra hop, ``roundtrip`` only on
# the slow inter hop — same schedule, same f32 add order)
# ---------------------------------------------------------------------------

def hier_reduce_scatter(shards: np.ndarray, n_intra: int,
                        roundtrip: Optional[RoundtripFn] = None
                        ) -> np.ndarray:
    """[n, L] per-device inputs -> [n, L//n] owned reduced chunks with
    natural ownership (device d ends with chunk d), computed as phase A
    (codec-FREE flat-ring schedule inside each group of ``n_intra``
    consecutive ranks, unit = the ng*C elements whose intra index
    matches) then phase B (the flat-ring schedule across groups with
    ``roundtrip`` on every hop payload) — bit-for-bit the spec of
    ops.ring_hier.hier_reduce_scatter for any codec."""
    n, L = shards.shape
    ni = int(n_intra)
    assert n % ni == 0 and L % n == 0, (n, ni, L)
    ng, C = n // ni, L // n
    # units[d, j'] = concat over g' of chunk g'*ni + j' of device d
    units = (shards.reshape(n, ng, ni, C).astype(np.float32)
             .transpose(0, 2, 1, 3).reshape(n, ni, ng * C).copy())
    for s in range(ni - 1):          # phase A: intra, RAW (no roundtrip)
        sends = [units[d, (d % ni - s - 1) % ni] for d in range(n)]
        for d in range(n):
            g, j = d // ni, d % ni
            src = g * ni + (j - 1) % ni          # intra predecessor
            units[d, (j - s - 2) % ni] += sends[src]
    # own[d, q] = group-partial sum of chunk q*ni + (d % ni)
    own = np.stack([units[d, d % ni].reshape(ng, C) for d in range(n)])
    for s in range(ng - 1):          # phase B: inter, codec on the wire
        sends = [_rt(own[d, (d // ni - s - 1) % ng], roundtrip)
                 for d in range(n)]
        for d in range(n):
            g, j = d // ni, d % ni
            src = ((g - 1) % ng) * ni + j        # inter predecessor
            own[d, (g - s - 2) % ng] += sends[src]
    return np.stack([own[d, d // ni] for d in range(n)])


def hier_all_gather(owned: np.ndarray, n_intra: int,
                    roundtrip: Optional[RoundtripFn] = None) -> np.ndarray:
    """[n, C] owned chunks -> [n, n*C] reassembled replicas: the codec
    inter gather first (each chunk quantized ONCE when it crosses the
    slow boundary, forwarded verbatim — replicas identical), then the
    raw intra gather.  Matches ops.ring_hier.hier_all_gather; with
    n_inter == 1 nothing is quantized (no slow boundary exists)."""
    n, C = owned.shape
    ni = int(n_intra)
    assert n % ni == 0, (n, ni)
    ng = n // ni
    owned = owned.astype(np.float32)
    # phase B': inter all-gather across groups (members share j)
    blocks = np.zeros((n, ng, C), np.float32)
    if ng > 1:
        carry = np.stack([_rt(owned[d], roundtrip) for d in range(n)])
        for d in range(n):
            blocks[d, d // ni] = carry[d]
        for s in range(ng - 1):
            nxt = np.empty_like(carry)
            for d in range(n):
                g, j = d // ni, d % ni
                nxt[d] = carry[((g - 1) % ng) * ni + j]
            carry = nxt
            for d in range(n):
                blocks[d, (d // ni - s - 1) % ng] = carry[d]
    else:
        for d in range(n):
            blocks[d, 0] = owned[d]
    # phase A': raw intra all-gather of the [ng*C] block
    flat = blocks.reshape(n, ng * C)
    out = np.zeros((n, ni, ng * C), np.float32)
    carry = flat.copy()
    for d in range(n):
        out[d, d % ni] = carry[d]
    for s in range(ni - 1):
        nxt = np.empty_like(carry)
        for d in range(n):
            g, j = d // ni, d % ni
            nxt[d] = carry[g * ni + (j - 1) % ni]
        carry = nxt
        for d in range(n):
            out[d, (d % ni - s - 1) % ni] = carry[d]
    # out[d, p] = chunks {q*ni + p}; restore natural chunk order
    return (out.reshape(n, ni, ng, C).transpose(0, 2, 1, 3)
            .reshape(n, n * C))


def hier_all_reduce(shards: np.ndarray, n_intra: int,
                    roundtrip: Optional[RoundtripFn] = None) -> np.ndarray:
    return hier_all_gather(hier_reduce_scatter(shards, n_intra, roundtrip),
                           n_intra, roundtrip)


# ---------------------------------------------------------------------------
# exact wire checksums (spec for ops.integrity — the PR-12 exact tier)
# ---------------------------------------------------------------------------

def golden_words_u32(x: np.ndarray) -> np.ndarray:
    """Numpy twin of ops.integrity.words_u32: a payload array as the flat
    uint32 word vector the checksum is defined over — 4-byte dtypes
    reinterpret word-for-word (little-endian, the only byte order this
    stack runs on), 1-/2-byte dtypes zero-extend."""
    x = np.ascontiguousarray(x).reshape(-1)
    size = x.dtype.itemsize
    if size == 4:
        return x.view(np.uint32)
    if size == 2:
        return x.view(np.uint16).astype(np.uint32)
    if size == 1:
        return x.view(np.uint8).astype(np.uint32)
    raise TypeError(f"no wire payload may have itemsize {size}")


_U32 = np.uint64(0xFFFFFFFF)


def golden_word_checksum(x: np.ndarray) -> np.uint32:
    """Numpy twin of ops.integrity.word_checksum: the odd-weighted
    wraparound word sum  sum_i (2i+1) * word_i  (mod 2^32).  Every
    product is reduced mod 2^32 BEFORE the sum (the jax side works in
    u32 wraparound throughout); the masked-u32 partial sums then cannot
    overflow u64 for any physical payload size."""
    w = golden_words_u32(x).astype(np.uint64)
    weights = (((np.arange(w.shape[0], dtype=np.uint64) << np.uint64(1))
                | np.uint64(1)) & _U32)
    prod = (w * weights) & _U32
    return np.uint32(int(np.sum(prod, dtype=np.uint64)) & 0xFFFFFFFF)


def golden_payload_checksum(payload) -> np.uint32:
    """Numpy twin of ops.integrity.payload_checksum: per-element odd
    multipliers over a hop's payload tuple."""
    acc = 0
    for k, p in enumerate(payload):
        acc += (2 * k + 1) * int(golden_word_checksum(np.asarray(p)))
    return np.uint32(acc & 0xFFFFFFFF)


def golden_page_checksums(pool) -> np.ndarray:
    """Numpy twin of ops.integrity.page_checksums: [n_pages] uint32 — one
    checksum per KV-pool page over every layer's K and V bytes, word
    weights restarting per page per array, odd per-array multipliers in
    layer-major K-then-V order."""
    acc = None
    j = 0
    for layer in pool:
        for key in ("k", "v"):
            arr = np.ascontiguousarray(np.asarray(layer[key]))
            n_pages = arr.shape[0]
            w = golden_words_u32(arr).reshape(n_pages, -1).astype(np.uint64)
            weights = (((np.arange(w.shape[1], dtype=np.uint64)
                         << np.uint64(1)) | np.uint64(1)) & _U32)
            prod = (w * weights[None, :]) & _U32
            per_page = np.sum(prod, axis=1, dtype=np.uint64) & _U32
            term = (np.uint64(2 * j + 1) * per_page) & _U32
            acc = term if acc is None else (acc + term) & _U32
            j += 1
    return acc.astype(np.uint32)
