"""Pluggable gradient-compression codecs for the ring collectives.

The reference ships ONE wire codec (BFP, hw/bfp_adapter.sv).  This package
turns that single trick into a framework seam: a formal `Codec` protocol
(encode/decode payload tuples, error-feedback residual carry, declared
error bound — see `compress.base`), a name registry, numpy golden twins
(`compress.golden`), and three registered implementations:

  bfp    the reference wire format, refactored out of the previously
         hard-wired path — behavior-identical (`compress.bfp`)
  topk   per-bucket magnitude top-k with error feedback, SparCML-style
         (`compress.topk`)
  int8   per-block linear int8 with stochastic rounding, EQuARX-style,
         with fused Pallas encode/decode kernels (`compress.int8`)

Select via ``CollectiveConfig(impl="ring", codec="topk",
codec_opts=(("k", 32),))``; the legacy ``compression=BFPConfig(...)``
spelling still resolves to the bfp codec (`resolve`).  Unknown names fail
fast at config construction with the registered list.
"""

from .base import (Codec, as_codec, available_codecs, get_codec,  # noqa: F401
                   register, resolve)
from . import base, golden  # noqa: F401
# importing the implementation modules registers them
from . import bfp, int8, topk  # noqa: F401
from .bfp import BFPCodec  # noqa: F401
from .int8 import Int8Codec  # noqa: F401
from .topk import TopKCodec  # noqa: F401

__all__ = [
    "Codec", "BFPCodec", "TopKCodec", "Int8Codec",
    "register", "get_codec", "available_codecs", "resolve", "as_codec",
    "base", "bfp", "topk", "int8", "golden",
]
