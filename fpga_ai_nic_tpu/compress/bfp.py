"""BFP as a registered codec — the reference wire format behind the
generic `compress.Codec` seam.

This is a REFACTOR, not a reimplementation: the encode/decode pair and the
pallas-vs-xla dispatch are the exact functions `ops.ring` hard-wired before
the codec subsystem existed (`use_pallas`/`codec_pair` below are that code,
moved), so ``codec="bfp"`` is bit-identical to the legacy
``compression=BFPConfig(...)`` path — enforced by tests/test_codec.py's
bit-compare and by every pre-existing golden test in tests/test_ring.py,
which still run through this module.

Numerics spec: `ops.bfp_golden` ("flat16" layout for the XLA backend,
"sublane" for the Pallas kernels).  error_bound: one ULP of the block grid,
``2**(1 - mantissa_bits)`` of the block max (the bound
`runtime.chaos.integrity_tol` used to special-case).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .base import Codec, DTypeLike, register
from ..ops import bfp as _bfp_xla
from ..ops import bfp_pallas as _bfp_pl
from ..utils.config import BFPConfig


def use_pallas(cfg: BFPConfig, n_elems: int) -> bool:
    """Does this payload ride the fused Pallas codec kernels?  (Moved
    verbatim from ops.ring._use_pallas — the dispatch is part of the bit
    contract: xla and pallas backends quantize in different block
    partitions.)"""
    return cfg.codec == "pallas" or (
        cfg.codec == "auto" and _bfp_pl._is_tpu()
        and n_elems % (cfg.block_size * _bfp_pl.LANES) == 0)


def codec_pair(cfg: BFPConfig, n_elems: int) -> Tuple[Callable, Callable]:
    """(encode, decode) for a flat [n_elems] payload (moved verbatim from
    ops.ring._codec).

    codec="auto" picks the fused Pallas kernels on TPU when the payload
    tiles onto (block, 128)-lane registers, else the XLA ops; the default
    "xla" keeps golden bit-exactness on every platform (see BFPConfig)."""
    if use_pallas(cfg, n_elems):
        # inline (un-jitted) kernels: a nested closed_call inside a
        # vma-checked shard_map trips the checker
        def enc(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
            return _bfp_pl.bfp_encode_inline(x, cfg.block_size,
                                             cfg.mantissa_bits,
                                             cfg.rounding)

        def dec(mant: jax.Array, se: jax.Array,
                dtype: DTypeLike) -> jax.Array:
            return _bfp_pl.bfp_decode_inline(mant, se, cfg.block_size,
                                             dtype)
    else:
        def enc(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
            return _bfp_xla.bfp_encode(x, cfg.block_size,
                                       cfg.mantissa_bits, cfg.rounding)

        def dec(mant: jax.Array, se: jax.Array,
                dtype: DTypeLike) -> jax.Array:
            return _bfp_xla.bfp_decode(mant, se, cfg.block_size, dtype)

    return enc, dec


@register
class BFPCodec(Codec):
    """Block-floating-point: int8 mantissas + one shared int8 power-of-two
    exponent per block (hw/bfp_adapter.sv's 136b-per-512b frame)."""

    name = "bfp"
    idempotent = True          # re-quantizing the decoded grid is exact
    error_feedback = False     # bounded error; EF optional via opts
    supports_fused = True      # ops.ring_pallas's wire frames ARE this

    def __init__(self, cfg: Optional[BFPConfig] = None,
                 error_feedback: bool = False, **overrides: Any) -> None:
        """``overrides`` are BFPConfig fields (mantissa_bits=..., etc.) so
        ``codec_opts`` can parameterize without constructing a BFPConfig;
        ``error_feedback=True`` opts the bounded codec into a residual
        carry too (useful at low mantissa widths)."""
        self.cfg = replace(cfg or BFPConfig(), **overrides)
        self.error_feedback = bool(error_feedback)

    # -- wire transform -----------------------------------------------------

    def encode(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        enc, _ = codec_pair(self.cfg, x.shape[0])
        return tuple(enc(x))

    def decode(self, payload: Tuple[jax.Array, ...], n_elems: int,
               dtype: DTypeLike = jnp.float32) -> jax.Array:
        mant, se = payload
        _, dec = codec_pair(self.cfg, n_elems)
        return dec(mant, se, dtype)

    # -- structure ----------------------------------------------------------

    @property
    def pad_elems(self) -> int:
        return self.cfg.block_size

    def sliceable(self, chunk_elems: int,
                  slice_elems: Optional[int]) -> bool:
        cfg = self.cfg
        return (super().sliceable(chunk_elems, slice_elems)
                # sliced and whole-chunk paths must resolve to the SAME
                # backend, or slicing would change the block partition
                # (and the bits)
                and use_pallas(cfg, slice_elems) == use_pallas(cfg,
                                                               chunk_elems)
                # a pallas-bound slice must actually tile onto (block, 128)
                # lanes; fall back to the whole-chunk hop instead of
                # tripping the kernel's tiling assert (forced
                # codec="pallas" case)
                and not (use_pallas(cfg, slice_elems)
                         and slice_elems % (cfg.block_size * _bfp_pl.LANES)))

    # -- declared accuracy / rate ------------------------------------------

    @property
    def error_bound(self) -> float:
        # one grid step of the block's scale: 2^(1-m) of the block max
        return 2.0 ** (1 - self.cfg.mantissa_bits)

    def wire_bytes(self, n_elems: int) -> int:
        return _bfp_xla.wire_bytes(n_elems, self.cfg)

    def describe(self) -> Dict[str, Any]:
        d = super().describe()
        d.update(block_size=self.cfg.block_size,
                 mantissa_bits=self.cfg.mantissa_bits,
                 rounding=self.cfg.rounding, backend=self.cfg.codec)
        return d
