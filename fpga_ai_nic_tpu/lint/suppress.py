"""``# graftlint: disable=RN -- reason`` suppression comments.

Grammar (one comment, same line as the finding or the line directly above,
or ``disable-file`` anywhere at module top level):

    # graftlint: disable=R2 -- trace-time constant, read once per process
    # graftlint: disable=R1,R3 -- <reason covering both>
    # graftlint: disable-file=R5 -- this whole tool is a fixture generator

The reason is MANDATORY: a bare disable is itself an R0 error, as is an
unknown rule code.  Suppressed findings still print (marked suppressed) so
a blanket-suppression drift is visible in every lint run.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Set, Tuple

from .findings import AST_CODES, Finding

_PAT = re.compile(
    r"#\s*graftlint:\s*(disable(?:-file)?)\s*=\s*"
    r"([A-Za-z0-9,\s]+?)\s*(?:--\s*(\S.*?))?\s*$")


@dataclass
class Suppressions:
    # line -> (codes, reason); a finding at line L checks L then L-1
    by_line: Dict[int, Tuple[Set[str], str]]
    file_wide: Dict[str, str]          # code -> reason
    errors: List[Finding]              # R0 findings (bad suppressions)

    def lookup(self, code: str, line: int) -> Tuple[bool, str]:
        for ln in (line, line - 1):
            if ln in self.by_line:
                codes, reason = self.by_line[ln]
                if code in codes:
                    return True, reason
        if code in self.file_wide:
            return True, self.file_wide[code]
        return False, ""


def scan(path: str, text: str) -> Suppressions:
    sup = Suppressions(by_line={}, file_wide={}, errors=[])
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        comments = [(i + 1, line[line.index("#"):])
                    for i, line in enumerate(text.splitlines()) if "#" in line]
    for line_no, comment in comments:
        # only colon-marked directives are parsed; prose that merely
        # mentions the linter (docs, rule references) is not a directive
        if not re.search(r"graftlint\s*:", comment):
            continue
        m = _PAT.search(comment)
        if not m:
            sup.errors.append(Finding(
                "R0", path, line_no,
                "malformed graftlint directive (want "
                "'# graftlint: disable=RN -- reason'): %r" % comment.strip()))
            continue
        kind, codes_s, reason = m.group(1), m.group(2), m.group(3) or ""
        codes = {c.strip().upper() for c in codes_s.split(",") if c.strip()}
        bad = codes - set(AST_CODES)
        if bad:
            sup.errors.append(Finding(
                "R0", path, line_no,
                "unknown rule code(s) %s in graftlint disable"
                % ",".join(sorted(bad))))
            codes -= bad
        if not reason:
            sup.errors.append(Finding(
                "R0", path, line_no,
                "graftlint disable without a reason — add "
                "'-- <why this is safe>'"))
            continue       # a reasonless disable suppresses nothing
        if not codes:
            continue
        if kind == "disable-file":
            for c in codes:
                sup.file_wide[c] = reason
        else:
            cur, cur_reason = sup.by_line.get(line_no, (set(), reason))
            sup.by_line[line_no] = (cur | codes, cur_reason)
    return sup
