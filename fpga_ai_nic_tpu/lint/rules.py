"""R1–R5 AST rule implementations.

Every rule is a generator ``rule(ctx) -> Iterable[Finding]`` over one
parsed module (`engine.ModuleCtx`).  Rules are heuristic by design — they
encode the *bug classes the advisor rounds actually found* (docs/LINT.md
maps each rule to its motivating finding), tuned so the current tree is
clean without blanket suppressions.  False positives are handled with
``# graftlint: disable=RN -- reason`` (reason mandatory, rule R0).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding

# ---------------------------------------------------------------------------
# R1 — lock discipline on the shared stats objects
# ---------------------------------------------------------------------------

# Counter fields of utils.observability.CollectiveStats / RecoveryStats.
# These are written concurrently by the trainer thread, the elastic
# watchdog worker and XLA callback threads; PR 4 routed ALL mutation
# through locked record_* methods after bare `+=` provably dropped
# updates.  This rule freezes that invariant.
COLLECTIVE_COUNTERS = frozenset({
    "issued", "completed", "abandoned", "wire_bytes", "raw_bytes",
    "latency_sum_s", "latency_max_s", "stall_s", "overlap_s"})
RECOVERY_COUNTERS = frozenset({
    "faults", "recoveries", "failed_recoveries", "checkpoint_restores",
    "mttr_sum_s", "mttr_max_s", "events", "events_dropped"})
STATS_CLASSES = {"CollectiveStats": COLLECTIVE_COUNTERS,
                 "RecoveryStats": RECOVERY_COUNTERS}
ALL_COUNTERS = COLLECTIVE_COUNTERS | RECOVERY_COUNTERS
# attribute / variable names through which the stats objects travel
STATS_HANDLES = {"collectives", "recovery", "stats", "cstats", "rstats"}
MUTATING_METHODS = {"append", "extend", "insert", "pop", "clear", "update",
                    "setdefault", "remove"}


def _enclosing_class(ctx, node) -> Optional[ast.ClassDef]:
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


def _counter_mutation(ctx, target) -> Optional[Tuple[str, ast.AST]]:
    """(field, object-expr) if ``target`` writes a stats counter field.
    Handles ``obj.field`` and ``obj.field[key]`` targets."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute) and target.attr in ALL_COUNTERS:
        return target.attr, target.value
    return None


def _is_stats_object(ctx, obj, fieldname, node) -> bool:
    """Does ``obj`` (the expression left of .fieldname) plausibly hold a
    CollectiveStats/RecoveryStats instance?"""
    dotted = ctx.dotted(obj)
    if not dotted:
        return False
    last = dotted.split(".")[-1]
    if dotted == "self":
        cls = _enclosing_class(ctx, node)
        return (cls is not None and cls.name in STATS_CLASSES
                and fieldname in STATS_CLASSES[cls.name])
    if last in ("collectives", "cstats"):
        return fieldname in COLLECTIVE_COUNTERS
    if last in ("recovery", "rstats"):
        return fieldname in RECOVERY_COUNTERS
    # generic handles ('stats', ...): either stats class may be behind
    # them, so any counter field counts
    return last in STATS_HANDLES


def _under_lock(ctx, node) -> bool:
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if ctx.dotted(item.context_expr).endswith("_lock"):
                    return True
    return False


def _in_record_method(ctx, node) -> bool:
    fn = ctx.enclosing_function(node)
    while fn is not None:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls = _enclosing_class(ctx, fn)
            if (cls is not None and cls.name in STATS_CLASSES
                    and fn.name.startswith("record_")):
                return True
        fn = ctx.enclosing_function(fn)
    return False


def rule_r1_lock_discipline(ctx) -> Iterable[Finding]:
    for node in ast.walk(ctx.tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, ast.Call):
            # obj.field.append(...) and friends mutate the field too
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS):
                targets = [f.value]
        for t in targets:
            hit = _counter_mutation(ctx, t)
            if hit is None:
                continue
            fieldname, obj = hit
            if not _is_stats_object(ctx, obj, fieldname, node):
                continue
            if _in_record_method(ctx, node):
                if _under_lock(ctx, node):
                    continue
                yield Finding(
                    "R1", ctx.path, node.lineno,
                    f"stats counter '{fieldname}' mutated inside a record_* "
                    "method but OUTSIDE `with self._lock:` — the lock is "
                    "the whole point of the record_* funnel")
                continue
            yield Finding(
                "R1", ctx.path, node.lineno,
                f"stats counter '{fieldname}' mutated outside a locked "
                "record_* method (cross-thread `+=` drops updates; route "
                "through CollectiveStats/RecoveryStats.record_*)")


# ---------------------------------------------------------------------------
# traced-function discovery (shared by R2 and R3)
# ---------------------------------------------------------------------------

# wrappers whose function arguments are traced at jit time; bare names
# cover `from jax import jit` style imports
_WRAPPERS = {"jit", "pmap", "shard_map", "pallas_call", "core_map"}
# dotted-only wrappers (too generic as bare names)
_DOTTED_WRAPPERS = {"lax.scan", "jax.lax.scan", "lax.fori_loop",
                    "jax.lax.fori_loop", "lax.while_loop",
                    "jax.lax.while_loop", "lax.cond", "jax.lax.cond",
                    "jax.checkpoint", "jax.remat", "jax.grad",
                    "jax.value_and_grad", "jax.vmap"}
_CALLBACK_FUNCS = {"pure_callback", "io_callback"}


@dataclass
class TracedInfo:
    traced: Dict[ast.AST, str] = field(default_factory=dict)
    kernels: Dict[ast.AST, str] = field(default_factory=dict)
    host_defs: Set[ast.AST] = field(default_factory=set)
    host_subtrees: List[ast.AST] = field(default_factory=list)


def _wrapper_kind(ctx, func_expr) -> str:
    d = ctx.dotted(func_expr)
    if not d:
        return ""
    last = d.split(".")[-1]
    if last in _WRAPPERS:
        return last
    if d in _DOTTED_WRAPPERS or (ctx.from_imports.get(d, "") or "").endswith(
            tuple("." + w for w in _WRAPPERS)):
        return last
    return ""


def _is_callback_call(ctx, call: ast.Call) -> bool:
    d = ctx.dotted(call.func)
    last = d.split(".")[-1] if d else ""
    return last in _CALLBACK_FUNCS or d.endswith("debug.callback")


def find_traced_functions(ctx) -> TracedInfo:
    info = TracedInfo()
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    # host-callback targets are NOT traced (they run on the host thread):
    # exclude the first argument of pure_callback/io_callback/debug.callback
    host_names: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_callback_call(ctx, node):
            if node.args:
                tgt = node.args[0]
                info.host_subtrees.append(tgt)
                if isinstance(tgt, ast.Name):
                    host_names.add(tgt.id)
    for name in host_names:
        for d in defs_by_name.get(name, []):
            info.host_defs.add(d)

    def mark(fn_node, reason):
        if fn_node in info.host_defs or fn_node in info.traced:
            return
        info.traced[fn_node] = reason
        # nested defs run under the same trace when called
        for sub in ast.walk(fn_node):
            if (sub is not fn_node
                    and isinstance(sub, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                    and sub not in info.host_defs
                    and sub not in info.traced):
                info.traced[sub] = f"defined inside traced '{_name(fn_node)}'"

    # 1) decorators
    for fns in defs_by_name.values():
        for fn in fns:
            for dec in getattr(fn, "decorator_list", ()):
                expr = dec
                if isinstance(expr, ast.Call):
                    # @jax.jit(...) or @functools.partial(jax.jit, ...)
                    if ctx.dotted(expr.func).split(".")[-1] == "partial" \
                            and expr.args:
                        expr = expr.args[0]
                    else:
                        expr = expr.func
                kind = _wrapper_kind(ctx, expr)
                if kind:
                    mark(fn, f"decorated with {kind}")

    # alias map: `kern = functools.partial(_kernel, ...)` / `g = f` — the
    # idiom every Pallas call site here uses to bind static kernel params
    alias_of: Dict[str, Set[str]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            src = _unwrap_partial(ctx, node.value)
            if src:
                alias_of.setdefault(tgt, set()).add(src)

    def resolve(arg) -> List[ast.AST]:
        """FunctionDefs an argument expression may refer to (through
        partial() wrapping and simple name aliasing; an alias reused at
        several call sites resolves to every aliased kernel)."""
        if isinstance(arg, ast.Lambda):
            return [arg]
        name = _unwrap_partial(ctx, arg)
        if not name:
            return []
        out: List[ast.AST] = []
        seen: Set[str] = set()
        frontier = {name}
        while frontier:
            nm = frontier.pop()
            seen.add(nm)
            out.extend(defs_by_name.get(nm, ()))
            frontier |= alias_of.get(nm, set()) - seen
        return out

    # 2) call sites: jax.jit(f), shard_map(f, ...), pl.pallas_call(kernel)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _wrapper_kind(ctx, node.func)
        if not kind:
            continue
        cands = list(node.args) + [kw.value for kw in node.keywords
                                   if kw.arg in ("f", "fun", "kernel",
                                                 "body_fn", "body")]
        for i, arg in enumerate(cands):
            for fn in resolve(arg):
                mark(fn, f"passed to {kind}")
                if kind == "pallas_call" and i == 0:
                    info.kernels[fn] = _name(fn)

    # 3) transitive closure: functions called from traced bodies are traced
    changed = True
    while changed:
        changed = False
        for fn in list(info.traced):
            for call in _walk_skipping(fn, info.host_subtrees):
                if not isinstance(call, ast.Call):
                    continue
                if _is_callback_call(ctx, call):
                    continue
                if isinstance(call.func, ast.Name):
                    for cand in defs_by_name.get(call.func.id, []):
                        if cand not in info.traced \
                                and cand not in info.host_defs:
                            mark(cand, f"called from traced '{_name(fn)}'")
                            changed = True
    return info


def _name(fn) -> str:
    return getattr(fn, "name", "<lambda>")


def _unwrap_partial(ctx, expr) -> str:
    """Name referenced by ``expr``, seeing through functools.partial(f, …)
    (returns '' when the expression is not a name/partial-of-name)."""
    while isinstance(expr, ast.Call) \
            and ctx.dotted(expr.func).split(".")[-1] == "partial" \
            and expr.args:
        expr = expr.args[0]
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _walk_skipping(root, skip_subtrees):
    """ast.walk that does not descend into any of ``skip_subtrees``
    (host-callback bodies live inside traced functions but run on host)."""
    skip = set(map(id, skip_subtrees))
    stack = [root]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if id(child) in skip:
                continue
            stack.append(child)
        yield node


# ---------------------------------------------------------------------------
# R2 — trace-time capture hazards
# ---------------------------------------------------------------------------

_MUTABLE_CTORS = {"dict", "list", "set"}


def _hazard_of_call(ctx, call: ast.Call) -> str:
    d = ctx.dotted(call.func)
    if not d:
        return ""
    root = d.split(".")[0]
    mod = ctx.mod_aliases.get(root, "")
    if mod == "time":
        return f"'{d}()' captures host wall-clock at trace time"
    if (mod == "numpy" and ".random" in d) \
            or mod.startswith("numpy.random"):
        # covers `np.random.x()` and `import numpy.random as npr`
        return (f"'{d}()' draws host randomness at trace time (use "
                "jax.random with a threaded key)")
    if mod == "random":
        return f"'{d}()' draws host randomness at trace time"
    if mod == "os" and (d.endswith("getenv") or ".environ" in d):
        return f"'{d}()' reads the environment at trace time"
    if mod == "datetime" and d.split(".")[-1] in ("now", "utcnow", "today"):
        return f"'{d}()' captures host wall-clock at trace time"
    src = ctx.from_imports.get(d, "")
    if src.startswith("time."):
        return f"'{d}()' (= {src}) captures host wall-clock at trace time"
    if src.startswith("random.") or src.startswith("numpy.random"):
        return f"'{d}()' (= {src}) draws host randomness at trace time"
    if src == "os.getenv":
        return f"'{d}()' (= os.getenv) reads the environment at trace time"
    return ""


def rule_r2_trace_capture(ctx) -> Iterable[Finding]:
    info = ctx.traced
    seen: Set[Tuple[int, str]] = set()
    for fn, reason in info.traced.items():
        # mutable default arguments on the traced function itself: the
        # default is captured ONCE and aliased across every trace
        args = getattr(fn, "args", None)
        if args is not None:
            for dflt in list(args.defaults) + [d for d in args.kw_defaults
                                               if d is not None]:
                bad = isinstance(dflt, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(dflt, ast.Call)
                    and ctx.dotted(dflt.func) in _MUTABLE_CTORS)
                if bad:
                    key = (fn.lineno, "default")
                    if key not in seen:
                        seen.add(key)
                        yield Finding(
                            "R2", ctx.path, fn.lineno,
                            f"traced function '{_name(fn)}' ({reason}) has "
                            "a mutable default argument — captured once, "
                            "shared across traces")
        for node in _direct_body(fn, info):
            msg = ""
            if isinstance(node, ast.Call):
                msg = _hazard_of_call(ctx, node)
            elif isinstance(node, ast.Attribute) \
                    and ctx.dotted(node) == "os.environ" \
                    and ctx.mod_aliases.get("os") == "os":
                msg = "'os.environ' read at trace time"
            if msg:
                key = (node.lineno, msg)
                if key not in seen:
                    seen.add(key)
                    yield Finding(
                        "R2", ctx.path, node.lineno,
                        f"{msg} inside traced function '{_name(fn)}' "
                        f"({reason}) — the captured value is frozen into "
                        "the compiled program")


def _direct_body(fn, info: TracedInfo):
    """Nodes of ``fn``'s body, excluding nested host-callback defs and
    nested traced defs (they are scanned as their own entries)."""
    skip = list(info.host_subtrees)
    for sub in ast.walk(fn):
        if sub is not fn and isinstance(sub, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)) \
                and sub in info.host_defs:
            skip.append(sub)
    yield from _walk_skipping(fn, skip)


# ---------------------------------------------------------------------------
# R3 — Pallas tiling discipline
# ---------------------------------------------------------------------------

LANE = 128
SUBLANE = 8


def _module_uses_pallas(ctx) -> bool:
    for v in list(ctx.mod_aliases.values()) + list(ctx.from_imports.values()):
        if "pallas" in v:
            return True
    return False


def _check_block_tuple(ctx, tup: ast.Tuple, what: str):
    elems = tup.elts
    if not elems:
        return
    lane = elems[-1]
    if isinstance(lane, ast.Constant) and isinstance(lane.value, int) \
            and lane.value % LANE != 0:
        yield Finding(
            "R3", ctx.path, lane.lineno,
            f"{what}: literal lane dimension {lane.value} is not a "
            f"multiple of {LANE} — use the module's LANES constant or a "
            "lane-tileable size (Mosaic will reject or relayout this on "
            "real hardware)")
    if len(elems) >= 2:
        sub = elems[-2]
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int) \
                and sub.value != 1 and sub.value % SUBLANE != 0:
            yield Finding(
                "R3", ctx.path, sub.lineno,
                f"{what}: literal sublane dimension {sub.value} is not a "
                f"multiple of {SUBLANE} (or 1) — use SUBLANES-derived "
                "sizes")


def rule_r3_pallas_tiling(ctx) -> Iterable[Finding]:
    if not _module_uses_pallas(ctx):
        return
    # (a) literal block shapes in BlockSpec / VMEM scratch
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        last = ctx.dotted(node.func).split(".")[-1]
        if last in ("BlockSpec", "VMEM") and node.args \
                and isinstance(node.args[0], ast.Tuple):
            yield from _check_block_tuple(ctx, node.args[0],
                                          f"{last} block shape")
    # (b) Python branches on traced values inside kernel bodies
    info = ctx.traced
    for fn in info.kernels:
        params = {a.arg for a in fn.args.args}
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                continue
            reason = _traced_test(ctx, node.test, params)
            if reason:
                yield Finding(
                    "R3", ctx.path, node.lineno,
                    f"kernel '{_name(fn)}' Python-branches on a traced "
                    f"value ({reason}) — this silently bakes one branch "
                    "into the kernel at trace time; use pl.when or "
                    "lax.cond/select")


def _traced_test(ctx, test, params: Set[str]) -> str:
    for node in ast.walk(test):
        if isinstance(node, ast.Subscript):
            root = ctx.dotted(node.value).split(".")[0]
            if root in params:
                return f"ref load '{root}[...]'"
        if isinstance(node, ast.Call):
            d = ctx.dotted(node.func)
            last = d.split(".")[-1]
            if last == "program_id":
                return "pl.program_id(...)"
            if last == "load" and node.args:
                root = ctx.dotted(node.args[0]).split(".")[0]
                if root in params:
                    return f"pl.load({root}, ...)"
    return ""


# ---------------------------------------------------------------------------
# R4 — callback gating in hot paths
# ---------------------------------------------------------------------------

def _is_hot_path(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "ops" in parts[:-1] or "parallel" in parts[:-1]


def _gate_ancestor(ctx, node) -> bool:
    fn = ctx.enclosing_function(node)
    for anc in ctx.ancestors(node):
        if anc is fn:
            break
        if isinstance(anc, (ast.If, ast.IfExp)):
            return True
    if fn is None or isinstance(fn, ast.Lambda):
        return False
    # early-return guard: a DIRECT `if <gate>: return/raise` statement of
    # the enclosing function, lexically before the call.  Walking nested
    # defs or deeper branches here would let any unrelated guard anywhere
    # in the function count as a gate (round-review finding).
    for stmt in fn.body:
        if stmt.lineno >= node.lineno:
            break
        if isinstance(stmt, ast.If) \
                and any(isinstance(s, (ast.Return, ast.Raise))
                        for s in stmt.body):
            return True
    return False


def rule_r4_callback_gating(ctx) -> Iterable[Finding]:
    if not _is_hot_path(ctx.path):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = ctx.dotted(node.func)
        last = d.split(".")[-1] if d else ""
        is_cb = (last in _CALLBACK_FUNCS or d.endswith("debug.callback")
                 or (last == "tap" and ("metrics" in d or "obs" in d)))
        if not is_cb:
            continue
        if _gate_ancestor(ctx, node):
            continue
        yield Finding(
            "R4", ctx.path, node.lineno,
            f"'{d}' in a hot path is not dominated by a trace-time config "
            "gate (obs_metrics / chaos plan) — an unconditional callback "
            "serializes every step on a host round-trip")


# ---------------------------------------------------------------------------
# R5 — artifact honesty in bench writers
# ---------------------------------------------------------------------------

def _is_bench_writer(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "tools" in parts[:-1] or parts[-1].startswith("bench")


def _bad_fallback(node) -> str:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            last = sub.func.attr if isinstance(sub.func, ast.Attribute) \
                else getattr(sub.func, "id", "")
            if last in ("max", "min"):
                for kw in sub.keywords:
                    if kw.arg == "default" and isinstance(kw.value,
                                                          ast.Constant):
                        return (f"{last}(..., default="
                                f"{kw.value.value!r})")
                # max(r.get(k, 0) for r in rows): the fallback hides as
                # the .get default instead of max's — same fake headline
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Call) \
                            and isinstance(inner.func, ast.Attribute) \
                            and inner.func.attr == "get" \
                            and len(inner.args) >= 2 \
                            and isinstance(inner.args[1], ast.Constant) \
                            and inner.args[1].value in (0, 0.0):
                        return (f"{last}(... .get(k, "
                                f"{inner.args[1].value!r}) ...)")
        if isinstance(sub, ast.BoolOp) and isinstance(sub.op, ast.Or):
            tail = sub.values[-1]
            if isinstance(tail, ast.Constant) and tail.value in (0, 0.0):
                return f"'... or {tail.value!r}' fallback"
    return ""


def rule_r5_artifact_honesty(ctx) -> Iterable[Finding]:
    if not _is_bench_writer(ctx.path):
        return
    sites: List[Tuple[ast.AST, ast.AST]] = []   # (key-ish node, rhs)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.slice, ast.Constant) \
                        and t.slice.value in ("value", "unit"):
                    sites.append((t, node.value))
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value in ("value",
                                                               "unit"):
                    sites.append((k, v))
        elif isinstance(node, ast.Call) and ctx.dotted(node.func) == "dict":
            for kw in node.keywords:
                if kw.arg in ("value", "unit"):
                    sites.append((kw.value, kw.value))
    for key_node, rhs in sites:
        why = _bad_fallback(rhs)
        if why:
            yield Finding(
                "R5", ctx.path, key_node.lineno,
                f"artifact headline banked from a {why} — a missing "
                "measurement must surface as an explicit *_error field, "
                "never a fake default (the multichip 0.0 GB/s class)")


# ---------------------------------------------------------------------------
# R6 — chaos site tuples must be DERIVED from their point maps
# ---------------------------------------------------------------------------

def rule_r6_site_derivation(ctx) -> Iterable[Finding]:
    """A public module-level ``*_SITES`` constant assigned a literal
    tuple of strings is a hand transcription: the chaos matrix/soak
    sweeps iterate these tuples, so a fire point added to the code but
    not the literal silently drops out of every sweep.  PR 12 caught
    exactly this by review ("serve.handoff" missing from WIRE_SITES);
    the fix was to derive the exported tuple from the point map
    (``tuple(dict.fromkeys(_X_POINT_SITES.values()))``) — this rule
    freezes that shape.  Private ``_*`` names (the point-map plumbing
    itself) and any computed form (calls, concatenation) stay legal."""
    for node in ctx.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name) or t.id.startswith("_"):
            continue
        if not (t.id == "SITES" or t.id.endswith("_SITES")):
            continue
        v = node.value
        if isinstance(v, ast.Tuple) and v.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in v.elts):
            yield Finding(
                "R6", ctx.path, node.lineno,
                f"chaos site tuple {t.id} is hand-written string "
                "literals — derive it from its fire-point map "
                "(tuple(dict.fromkeys(_*_POINT_SITES.values()))) so a "
                "new fire point can never silently drop out of the "
                "chaos sweep (the WIRE_SITES drift class)")
