"""graftlint — JAX/Pallas-aware static analysis for this repo.

Two planes (docs/LINT.md is the rule catalog):

Plane 1 — AST rules over the package source (`lint.engine` + `lint.rules`):
  R1  lock discipline: CollectiveStats/RecoveryStats counters mutate only
      inside their locked ``record_*`` methods (the PR-4 race class).
  R2  trace-time capture hazards: ``time.*`` / ``np.random.*`` /
      ``os.environ`` reads / mutable default args inside jitted,
      shard_map'd or Pallas-kernel bodies.
  R3  Pallas tiling: integer literals feeding BlockSpec / scratch shapes
      must be lane/sublane multiples (or named LANES/SUBLANES math), and
      kernel bodies must not Python-branch on traced values.
  R4  callback gating: pure_callback/io_callback (and the obs metrics
      tap) in ops/ and parallel/ hot paths must sit under a trace-time
      config gate, never unconditional.
  R5  artifact honesty: bench writers must not bank a headline
      ``value``/``unit`` from a ``max(..., default=0)``-style fallback.
  R0  suppression hygiene: ``# graftlint: disable=RN`` requires a
      ``-- reason``; unknown codes are errors.

Plane 2 — jaxpr invariant sweep (`lint.jaxpr_sweep`, CPU-only):
  J1  obs_metrics=False compiles to zero callback primitives.
  J2  no f64 avals anywhere in the step jaxpr.
  J3  donated buffers are actually donated (pjit donated_invars).
  J4  declared Codec.wire_bytes matches the bytes implied by the
      jaxpr's ppermute operands (with static trip counts).
  J5  every ppermute/psum axis name exists on the mesh.

The sweep is registry-driven: every codec in ``compress.available_codecs``
is covered automatically, and the run fails loudly if one is missed.
"""

from .findings import Finding, RULE_DOCS
from .engine import lint_paths, lint_source, default_targets

__all__ = ["Finding", "RULE_DOCS", "lint_paths", "lint_source",
           "default_targets"]
