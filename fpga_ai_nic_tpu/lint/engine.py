"""AST-plane driver: file discovery, parsing, rule dispatch, suppression.

The engine is deliberately free of jax imports so `make lint-fixtures`
stays a sub-second pure-Python pass; the jaxpr plane lives in
`lint.jaxpr_sweep` and is imported only when requested.
"""

from __future__ import annotations

import ast
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from . import rules as rules_mod
from .findings import Finding
from .suppress import scan as scan_suppressions

RuleFn = Callable[["ModuleCtx"], Iterable[Finding]]


class ModuleCtx:
    """Parsed module + shared derived facts handed to every rule."""

    def __init__(self, path: str, text: str, tree: ast.Module):
        self.path = path
        self.text = text
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # module aliases: local name -> dotted module (import time as t,
        # import numpy as np, from os import environ, ...)
        self.mod_aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, str] = {}   # local name -> "mod.attr"
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        # `import numpy.random as npr` binds the full
                        # dotted module to the alias
                        self.mod_aliases[a.asname] = a.name
                    else:
                        # `import os.path` binds only `os` — recording
                        # 'os.path' under key 'os' would shadow the root
                        # module and blind R2 to os.environ reads
                        root = a.name.split(".")[0]
                        self.mod_aliases[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        self._traced = None   # lazy (rules.R2/R3 both need it)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return anc
        return None

    def dotted(self, node: ast.AST) -> str:
        """Best-effort dotted name of a Name/Attribute chain ('' if not)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return ""

    @property
    def traced(self):
        if self._traced is None:
            self._traced = rules_mod.find_traced_functions(self)
        return self._traced


RULES: Sequence[RuleFn] = (
    rules_mod.rule_r1_lock_discipline,
    rules_mod.rule_r2_trace_capture,
    rules_mod.rule_r3_pallas_tiling,
    rules_mod.rule_r4_callback_gating,
    rules_mod.rule_r5_artifact_honesty,
    rules_mod.rule_r6_site_derivation,
)


def lint_source(path: str, text: str,
                rules: Sequence[RuleFn] = RULES,
                _depth: int = 0) -> List[Finding]:
    """Lint one module's source.  Syntax errors are findings, not crashes
    (a half-written file must not take CI down with a traceback)."""
    sup = scan_suppressions(path, text)
    out: List[Finding] = list(sup.errors)
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        out.append(Finding("R0", path, e.lineno or 1,
                           f"syntax error: {e.msg}"))
        return out
    ctx = ModuleCtx(path, text, tree)
    for rule in rules:
        for f in rule(ctx):
            hit, reason = sup.lookup(f.code, f.line)
            if hit:
                f = Finding(f.code, f.path, f.line, f.message,
                            suppressed=True, suppress_reason=reason)
            out.append(f)
    if _depth == 0:
        # child-script templates (first_contact/multichip bank headline
        # artifacts from `python -c <SRC>` strings) are shipped code too:
        # lint any module-level string that parses as a Python script
        for name, start, src in _embedded_sources(tree):
            for f in lint_source(path, src, rules, _depth=1):
                out.append(Finding(
                    # embedded line 1 IS the string's start line, so the
                    # file line is start + line - 1 (off-by-one found by
                    # the round review)
                    f.code, f.path, start + f.line - 1,
                    f"[embedded {name}] {f.message}",
                    suppressed=f.suppressed,
                    suppress_reason=f.suppress_reason))
    return sorted(out, key=lambda f: (f.path, f.line, f.code))


def _embedded_sources(tree: ast.Module):
    """(name, start_line, source) for module-level string constants that
    look like embedded Python child scripts."""
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        src = node.value.value
        if len(src) < 80 or "\n" not in src:
            continue
        try:
            sub = ast.parse(src)
        except (SyntaxError, ValueError):
            continue
        # a docstring-like constant parses to a bare Expr; a script has
        # real statements
        if any(not isinstance(s, ast.Expr) for s in sub.body):
            yield node.targets[0].id, node.value.lineno, src


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as fh:
            text = fh.read()
        out.extend(lint_source(p, text))
    return out


def default_targets(repo_root: str) -> List[str]:
    """The lintable tree: the package, tools/, the bench drivers and the
    examples — NOT tests/ (fixtures there are deliberately bad, and test
    bodies poke stats internals on purpose)."""
    targets: List[str] = []
    for sub in ("fpga_ai_nic_tpu", "tools", "examples"):
        base = os.path.join(repo_root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "csrc")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    targets.append(os.path.join(dirpath, fn))
    for fn in ("bench.py", "bench_collective.py", "bench_common.py"):
        p = os.path.join(repo_root, fn)
        if os.path.exists(p):
            targets.append(p)
    return targets
