"""Finding record + rule catalog shared by both analysis planes."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple

# One-line docs keyed by code; docs/LINT.md carries the full rationale.
RULE_DOCS: Dict[str, str] = {
    "R0": "suppression hygiene: disable= needs a '-- reason' and a known code",
    "R1": "lock discipline: stats counters mutate only in locked record_* "
          "methods",
    "R2": "trace-time capture hazard inside a jit/shard_map/Pallas body",
    "R3": "Pallas tiling: literal block dims must be lane/sublane multiples; "
          "no Python branch on traced values in kernel bodies",
    "R4": "callback gating: pure_callback/io_callback in ops//parallel/ must "
          "be dominated by a trace-time config gate",
    "R5": "artifact honesty: never bank value/unit from a "
          "max(..., default=0)-style fallback",
    "R6": "chaos site tuples (*_SITES) must be derived from their "
          "fire-point maps, never hand-written string literals",
    "J1": "jaxpr: obs off must compile to zero callback primitives",
    "J2": "jaxpr: no f64 avals may leak into the step",
    "J3": "jaxpr: donated state buffers must actually be donated",
    "J4": "jaxpr: declared Codec.wire_bytes must match ppermute operand "
          "bytes",
    "J5": "jaxpr: every collective axis name must exist on the mesh",
    "J6": "jaxpr sweep coverage: every registered codec must be swept",
    "J7": "per-replica gradient must be invariant to n_dp on a fixed "
          "batch (no collective on a loss head's gradient path)",
    "J8": "reshard program: callback-free, sources donated, and ppermute "
          "operand bytes == exactly the bytes that change owner per the "
          "intersection table",
    "J9": "hierarchical collective: intra-hop ppermutes must be codec-free "
          "f32 and each hop class must move exactly the bytes the "
          "HierarchicalPlan declares",
    "J10": "serving decode plane: the jitted prefill/decode steps must "
           "trace exactly once across any admit/evict schedule — slot "
           "occupancy and page assignment are VALUES, never shapes",
    "J11": "KV handoff program: callback-free, source pools donated, and "
           "ppermute operand bytes == exactly HandoffPlan.wire_bytes() — "
           "the migrated pages and nothing else cross the pair wire",
    "J12": "wire-integrity coverage: every ppermute-bearing program must "
           "carry its exact frame checksum when integrity is requested "
           "(u32 arithmetic + boolean verdict), with ppermute bytes "
           "IDENTICAL to the integrity-off twin (no checksum rides the "
           "wire) — or an explicit J12_WAIVERS entry",
    "J13": "adaptive candidate set: every pre-compiled plan must trace "
           "exactly once, up front at construction, and a runtime plan "
           "switch must cause ZERO new traces — the J10 counted-trace "
           "discipline applied to training (tune.adapt)",
    "J14": "durable-state integrity: every checkpoint restore path must "
           "AUDIT (a single flipped stored bit refuses or peer-repairs "
           "bit-exactly, never restores silently), the walk-back must "
           "land on the previous verified step, and the peer-repair "
           "pair program must move exactly the shard bytes callback-"
           "free with the source donated — or an explicit J14_WAIVERS "
           "entry (pinned empty; the J12 discipline applied to disk)",
    "H1": "happens-before/lockset: an instance attribute written from two "
          "threads (trainer / watchdog worker / callback) needs a common "
          "lock — R1 generalized to cross-thread order",
    "M1": "graftmc: a protocol model-check cell (or fixture) violated — "
          "deadlock, slot overwrite, ordering, credit safety, "
          "termination or DMA discipline",
}

AST_CODES: Tuple[str, ...] = ("R0", "R1", "R2", "R3", "R4", "R5", "R6",
                              "H1")
JAXPR_CODES: Tuple[str, ...] = ("J1", "J2", "J3", "J4", "J5", "J6", "J7",
                                "J8", "J9", "J10", "J11", "J12", "J13",
                                "J14")


@dataclass(frozen=True)
class Finding:
    """One diagnostic.  ``suppressed`` findings are reported but do not fail
    the run; a suppression must carry a reason (else the engine emits R0)."""

    code: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: str = field(default="", compare=False)

    def format(self) -> str:
        tag = " (suppressed: %s)" % self.suppress_reason if self.suppressed \
            else ""
        return f"{self.path}:{self.line}: {self.code}: {self.message}{tag}"
