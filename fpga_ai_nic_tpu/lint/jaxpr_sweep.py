"""Plane 2 — jaxpr invariant sweep (J1–J13), CPU-only.

EQuARX (arXiv:2506.17615) and the weight-update sharding work
(arXiv:2004.13336) both rest on compiler-level invariants of the lowered
program.  We check the same class of invariants *statically* on our own
jaxprs: every registered compression codec x every trainer x obs on/off
is traced abstractly (``jax.make_jaxpr`` over ShapeDtypeStructs on the
8-device virtual CPU mesh — zero device compute beyond tracing) and the
jaxpr is asserted to satisfy:

  J1  obs_metrics=False  =>  ZERO callback primitives (the generalization
      of tests/test_obs.py's jaxpr-identity test to the whole grid); on
      the fused trainers obs=True must show the tap, so J1 cannot rot
      into vacuity.
  J2  no float64 aval anywhere (an f64 leak doubles wire bytes and trips
      TPU lowering).
  J3  the step's donated buffers are actually donated: the pjit eqn's
      ``donated_invars`` must cover every state leaf (DP/FSDP donate the
      whole state; QueuedDDP's update_fn donates master + opt state).
  J4  declared ``Codec.wire_bytes`` == bytes implied by the jaxpr's
      ppermute operands x their static trip counts (scan lengths).
  J5  every collective axis name appearing in the jaxpr exists on the
      mesh.
  J6  sweep coverage: every codec in ``compress.available_codecs()`` was
      swept (a newly registered codec is auto-covered; a cell that fails
      to trace is a loud error, never a silent skip).

No TPU is required or touched: round 5's wedged tunnel is exactly why
these invariants are checked on CPU jaxprs instead of hardware runs.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

from .findings import Finding

# grid constants: a model just big enough that every codec's padding
# rules engage (bfp blocks, int8 block*LANES tiles, top-k buckets)
_LAYERS = (64, 64, 32)
_BATCH = 64
_NDEV = 8


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _iter_eqns(jaxpr, mult: Optional[int] = 1):
    """Yield (eqn, static_trip_multiplier) over nested jaxprs.  ``mult``
    is how many times the eqn executes per step (scan lengths compose);
    None = statically unknown (while_loop)."""
    for eqn in jaxpr.eqns:
        yield eqn, mult
        sub_mult = mult
        if eqn.primitive.name == "scan":
            length = eqn.params.get("length")
            sub_mult = None if (mult is None or length is None) \
                else mult * int(length)
        elif eqn.primitive.name in ("while", "cond"):
            # while: trip count unknown; cond: exactly ONE branch runs,
            # so summing over branch jaxprs would double-count (round
            # review) — both are statically unaccountable for J4
            sub_mult = None
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield from _iter_eqns(inner, sub_mult)
                elif hasattr(sub, "eqns"):
                    yield from _iter_eqns(sub, sub_mult)


def _aval_bytes(aval) -> int:
    return int(math.prod(aval.shape)) * aval.dtype.itemsize


def _collect(jaxpr) -> Dict[str, Any]:
    """One pass: callback count, f64 leaks, ppermute wire bytes, axis
    names, top-level pjit donation mask."""
    import numpy as np

    out: Dict[str, Any] = {"callbacks": 0, "f64": [], "wire_bytes": 0,
                           "wire_unknown": False, "axes": set(),
                           "donated": None}
    for eqn in jaxpr.eqns:
        # first top-level pjit = the jitted step call whose donation
        # mask J3 inspects (leading convert/broadcast eqns are fine)
        if eqn.primitive.name == "pjit":
            out["donated"] = tuple(eqn.params.get("donated_invars", ()))
            break
    for eqn, mult in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if "callback" in name:
            out["callbacks"] += 1
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and getattr(aval, "dtype", None) is not None \
                    and aval.dtype == np.float64:
                out["f64"].append(f"{name}: {aval.str_short()}")
        if name == "ppermute":
            if mult is None:
                out["wire_unknown"] = True
            else:
                out["wire_bytes"] += mult * sum(
                    _aval_bytes(v.aval) for v in eqn.invars)
            ax = eqn.params.get("axis_name")
            axes = ax if isinstance(ax, (tuple, list)) else (ax,)
            out["axes"].update(a for a in axes if isinstance(a, str))
        else:
            for key in ("axes", "axis_name"):
                ax = eqn.params.get(key)
                if ax is None:
                    continue
                axes = ax if isinstance(ax, (tuple, list)) else (ax,)
                out["axes"].update(a for a in axes if isinstance(a, str))
    return out


# ---------------------------------------------------------------------------
# cell construction
# ---------------------------------------------------------------------------

def _require_cpu_mesh():
    import jax
    devs = jax.devices()
    if devs[0].platform != "cpu" or len(devs) < _NDEV:
        raise RuntimeError(
            "graftlint jaxpr sweep needs the 8-device virtual CPU mesh; "
            "run via tools/graftlint.py (it pins JAX_PLATFORMS=cpu and "
            "--xla_force_host_platform_device_count=8 before jax loads), "
            f"got platform={devs[0].platform!r} n={len(devs)}")


def _mlp_pieces():
    import jax
    import jax.numpy as jnp
    from ..models import mlp
    from ..utils.config import MLPConfig

    mcfg = MLPConfig(layer_sizes=_LAYERS, dtype="float32")
    params = jax.eval_shape(lambda: mlp.init(jax.random.PRNGKey(0), mcfg))
    batch = (jax.ShapeDtypeStruct((_BATCH, _LAYERS[0]), jnp.float32),
             jax.ShapeDtypeStruct((_BATCH,), jnp.int32))

    def loss(p, b):
        return mlp.loss_fn(p, b, mcfg)

    return params, batch, loss


def _sds(shape, dtype):
    import jax
    return jax.ShapeDtypeStruct(shape, dtype)


def _trace_dp(cfg, axis="dp"):
    import jax
    import jax.numpy as jnp
    from .. import optim
    from ..parallel import mesh as mesh_lib
    from ..parallel.train import DPTrainer, TrainState

    params, batch, loss = _mlp_pieces()
    tr = DPTrainer(loss, mesh_lib.make_mesh(cfg.mesh), cfg, axis_name=axis)
    tr._ensure_meta(params)
    L = tr._meta.padded_len
    state = TrainState(
        params=params, w_own=_sds((L,), jnp.float32),
        opt_state=jax.eval_shape(lambda: optim.init_state(cfg.optimizer, L)),
        step=_sds((), jnp.int32),
        codec_state=_sds((tr.n * L,), jnp.float32) if tr._ef else None)
    jx = jax.make_jaxpr(lambda s, b: tr.step_fn(s, b))(state, batch)
    n_state = len(jax.tree_util.tree_leaves(state))
    return [("step", jx, {"n_donate": n_state})], L, tr.n


def _trace_fsdp(cfg, axis="fsdp"):
    import jax
    import jax.numpy as jnp
    from .. import optim
    from ..parallel import mesh as mesh_lib
    from ..parallel.fsdp import FSDPTrainer, FSDPState

    params, batch, loss = _mlp_pieces()
    tr = FSDPTrainer(loss, mesh_lib.make_mesh(cfg.mesh), cfg,
                     axis_name=axis)
    tr._ensure_meta(params)
    L = tr._meta.padded_len
    state = FSDPState(
        w_own=_sds((L,), jnp.float32),
        opt_state=jax.eval_shape(lambda: optim.init_state(cfg.optimizer, L)),
        step=_sds((), jnp.int32),
        codec_state=_sds((tr.n * L,), jnp.float32) if tr._ef else None)
    jx = jax.make_jaxpr(lambda s, b: tr.step_fn(s, b))(state, batch)
    n_state = len(jax.tree_util.tree_leaves(state))
    return [("step", jx, {"n_donate": n_state})], L, tr.n


def _trace_queued(cfg, axis="dp"):
    import jax
    import jax.numpy as jnp
    from .. import optim
    from ..parallel import mesh as mesh_lib
    from ..parallel.queued import QueuedDDPTrainer

    params, batch, loss = _mlp_pieces()
    tr = QueuedDDPTrainer(loss, mesh_lib.make_mesh(cfg.mesh), cfg,
                          axis_name=axis)
    tr._ensure_meta(params)
    bucket_sds, _loss_sds = jax.eval_shape(
        lambda p, b: tr.grads_fn(p, b), params, batch)
    jx_g = jax.make_jaxpr(lambda p, b: tr.grads_fn(p, b))(params, batch)
    phases = [("grads", jx_g, {})]
    # one reduce collective per bucket; wire accounting is per bucket
    for i, (b, g_sds) in enumerate(zip(tr._plan.buckets, bucket_sds)):
        jx_r = jax.make_jaxpr(lambda g: tr.reduce_fn(g))(g_sds)
        phases.append((f"reduce[{i}]", jx_r,
                       {"wire_len": b.padded_len}))
    Lm = tr._meta.padded_len
    w_sds = _sds((Lm,), jnp.float32)
    opt_sds = jax.eval_shape(lambda: optim.init_state(cfg.optimizer, Lm))
    jx_u = jax.make_jaxpr(
        lambda m, w, o, s: tr.update_fn(m, w, o, s))(
        tuple(bucket_sds), w_sds, opt_sds, _sds((), jnp.int32))
    n_donate = 1 + len(jax.tree_util.tree_leaves(opt_sds))
    phases.append(("update", jx_u, {"n_donate": n_donate}))
    return phases, None, tr.n


_TRAINERS: Dict[str, Tuple[Callable, str]] = {
    "DPTrainer": (_trace_dp, "dp"),
    "FSDPTrainer": (_trace_fsdp, "fsdp"),
    "QueuedDDPTrainer": (_trace_queued, "dp"),
}


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------

def _check_cell(cell: str, trainer: str, codec_name: Optional[str],
                obs: bool, phases, L: Optional[int], n: int,
                mesh_axes: Tuple[str, ...]) -> List[Finding]:
    from ..compress import get_codec
    from ..ops import ring as ring_ops

    findings: List[Finding] = []
    codec = get_codec(codec_name) if codec_name else None
    total_callbacks = 0
    wire_implied = 0
    wire_declared = 0
    wire_checked = False
    for phase_name, jx, info in phases:
        c = _collect(jx.jaxpr)
        total_callbacks += c["callbacks"]
        if c["f64"]:
            findings.append(Finding(
                "J2", cell, 0,
                f"f64 leak in {phase_name}: {c['f64'][:3]}"))
        bad_axes = c["axes"] - set(mesh_axes)
        if bad_axes:
            findings.append(Finding(
                "J5", cell, 0,
                f"{phase_name}: collective axis name(s) "
                f"{sorted(bad_axes)} not on mesh {mesh_axes}"))
        n_donate = info.get("n_donate")
        if n_donate is not None:
            donated = c["donated"] or ()
            if sum(donated) < n_donate:
                findings.append(Finding(
                    "J3", cell, 0,
                    f"{phase_name}: expected >= {n_donate} donated "
                    f"invars (the state), pjit donated_invars shows "
                    f"{sum(donated)}/{len(donated)} — donation lost "
                    "(peak memory doubles)"))
        if c["wire_unknown"]:
            findings.append(Finding(
                "J4", cell, 0,
                f"{phase_name}: ppermute under a while_loop — wire "
                "bytes not statically checkable (use fori_loop/scan "
                "with a static trip count)"))
        wire_implied += c["wire_bytes"]
        wire_len = info.get("wire_len", L if phase_name == "step" else None)
        if wire_len is not None:
            wire_checked = True
            wire_declared += ring_ops.wire_bytes_per_device(
                wire_len, n, codec)
    if not obs and total_callbacks:
        findings.append(Finding(
            "J1", cell, 0,
            f"obs_metrics=False but {total_callbacks} callback "
            "primitive(s) in the step — the trace-time gate leaks a "
            "host round-trip into every hot step"))
    if obs and trainer in ("DPTrainer", "FSDPTrainer") \
            and total_callbacks == 0:
        findings.append(Finding(
            "J1", cell, 0,
            "obs_metrics=True produced zero callbacks — the metrics tap "
            "vanished, so the obs-off check is vacuous"))
    if wire_checked:
        if wire_implied != wire_declared:
            findings.append(Finding(
                "J4", cell, 0,
                f"declared Codec.wire_bytes implies {wire_declared} "
                f"bytes/device/step on the ring, but the jaxpr's "
                f"ppermute operands move {wire_implied} — the wire "
                "accounting (obs counters, bench ratios) is lying"))
    return findings


# ---------------------------------------------------------------------------
# J7 — per-replica gradient invariant to n_dp (the psum-transpose
# gradient-scale class: docs/KNOWN_FAILURES.md #1-16, all root-caused to
# collectives sitting on a loss head's gradient path, whose transpose
# convention moved between jaxlibs and silently scaled every update by
# the axis size).  Unlike J1-J6 this rule evaluates tiny CONCRETE
# gradients (a jaxpr alone cannot prove a value-level invariant): a fixed
# global batch with UNEVENLY masked labels is sharded over n_dp in
# {2, 4}; the trainer-effective update (psum/n of the per-replica grads)
# must match the single-device gradient of the same objective — and each
# other — to f32 tolerance.  An n_dp-proportional mismatch is exactly
# the 8x-learning-rate bug class.
# ---------------------------------------------------------------------------

_J7_NDPS = (2, 4)
_J7_RTOL = 2e-3


def _j7_bert_build():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models import bert

    cfg = bert.BertConfig(vocab=64, dim=32, n_layers=1, n_heads=2,
                          ffn_dim=64, max_pos=16, dtype="float32",
                          attn_impl="xla")
    params = bert.init(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(0)
    tokens = jnp.asarray(r.integers(1, 64, (8, 8)).astype(np.int32))
    labels = np.asarray(r.integers(0, 64, (8, 8)), np.int32)
    # uneven masking: shard token counts differ, so uniform-mean vs
    # token-weighted gradients genuinely disagree (the correction term
    # carries weight)
    labels[:4, :6] = -100
    labels[4:, :2] = -100

    def loss(p, batch, dp_axis):
        return bert.loss_fn(p, batch, cfg, dp_axis=dp_axis)

    return params, (tokens, jnp.asarray(labels)), loss


def _j7_llama_build():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models import llama

    cfg = llama.LlamaConfig.tiny(vocab=64, dim=32, n_layers=1, n_heads=2,
                                 n_kv_heads=1, ffn_dim=64)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    r = np.random.default_rng(1)
    tokens = jnp.asarray(r.integers(1, 64, (8, 8)).astype(np.int32))
    labels = np.asarray(r.integers(0, 64, (8, 8)), np.int32)
    labels[:4, :6] = -100
    labels[4:, :2] = -100

    def loss(p, batch, dp_axis):
        return llama.loss_fn(p, batch, cfg, dp_axis=dp_axis)

    return params, (tokens, jnp.asarray(labels)), loss


def j7_surfaces() -> List[Tuple[str, Callable]]:
    """The dp-axis-correcting loss heads under guard.  The
    GRAFTLINT_J7_FIXTURE env var appends a surface from a module path
    exposing ``build()`` — the bad-fixture / exit-code hook
    (tests/test_lint.py)."""
    surfaces: List[Tuple[str, Callable]] = [
        ("models.bert.loss_fn", _j7_bert_build),
        ("models.llama.loss_fn", _j7_llama_build),
    ]
    import os
    fixture = os.environ.get("GRAFTLINT_J7_FIXTURE")
    if fixture:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_j7_fixture",
                                                      fixture)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surfaces.append((f"fixture:{os.path.basename(fixture)}",
                         mod.build))
    return surfaces


def check_grad_scale(name: str, build: Callable,
                     ndps: Tuple[int, ...] = _J7_NDPS,
                     rtol: float = _J7_RTOL) -> List[Finding]:
    """Evaluate one J7 surface: trainer-effective gradient at each n_dp
    vs the single-device gradient of the identical objective."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import lax

    findings: List[Finding] = []
    params, batch, loss = build()
    ref = jax.jit(jax.grad(lambda p: loss(p, batch, None)))(params)
    ref_flat = np.concatenate([np.asarray(l, np.float32).ravel()
                               for l in jax.tree_util.tree_leaves(ref)])
    scale = float(np.abs(ref_flat).max()) or 1.0
    for ndp in ndps:
        mesh = Mesh(np.array(jax.devices()[:ndp]), ("dp",))

        def shard(p, b):
            p = jax.tree_util.tree_map(
                lambda x: lax.pcast(x, "dp", to="varying"), p)
            g = jax.grad(lambda pp: loss(pp, b, "dp"))(p)
            # the trainer-effective update: sum over replicas / n_dp
            return jax.tree_util.tree_map(
                lambda x: lax.psum(x, "dp") / ndp, g)

        got = jax.jit(jax.shard_map(
            shard, mesh=mesh, in_specs=(P(), P("dp")), out_specs=P(),
            check_vma=False))(params, batch)
        got_flat = np.concatenate([
            np.asarray(l, np.float32).ravel()
            for l in jax.tree_util.tree_leaves(got)])
        err = float(np.abs(got_flat - ref_flat).max()) / scale
        if not np.isfinite(err) or err > rtol:
            with np.errstate(divide="ignore", invalid="ignore"):
                ratio = float(np.nanmedian(got_flat / ref_flat))
            findings.append(Finding(
                "J7", f"jaxpr[grad-scale {name}]", 0,
                f"per-replica gradient is NOT invariant to n_dp: at "
                f"n_dp={ndp} the trainer-effective update deviates from "
                f"the single-device gradient by rel {err:.3g} (median "
                f"elementwise ratio {ratio:.3g}; a ratio ~= n_dp is the "
                f"psum-transpose gradient-scale class — keep collectives "
                f"off the loss head's gradient path)"))
    return findings


def run_j7(verbose: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    for name, build in j7_surfaces():
        try:
            fs = check_grad_scale(name, build)
        except Exception as e:  # noqa: BLE001 — a surface must fail LOUDLY
            fs = [Finding("J7", f"jaxpr[grad-scale {name}]", 0,
                          f"surface failed to evaluate: "
                          f"{type(e).__name__}: {str(e)[:300]}")]
        findings.extend(fs)
        if verbose:
            print(f"[graftlint:jaxpr] grad-scale {name}: "
                  f"{'FAIL' if fs else 'ok'}")
    return findings


# ---------------------------------------------------------------------------
# J8 — the live-reshard transfer program (parallel.reshard).  The MTTR
# claim of the reshard recovery tier rests on the program moving EXACTLY
# the bytes the intersection table says change owner — no padding waste,
# no hidden host round-trips, and the source buffers actually donated
# (the transfer must run in ~one state's footprint).  Checked statically
# the same way J4 checks the ring: trace the lowered program abstractly,
# sum ppermute operand bytes x static trip counts, and compare against
# the plan's declared wire_bytes; any callback primitive or lost
# donation is a finding.  Surfaces cover a shrink (dp8->dp4, divisor), a
# NON-divisor shrink (dp8->dp3 — the boundary-splitting segments), and
# an EF-residual move (topk-padded layout).
# ---------------------------------------------------------------------------

def _j8_build(n_src: int, n_tgt: int, codec_name: Optional[str],
              n_flat_leaves: int, residual: bool):
    def build():
        import jax
        from jax.sharding import Mesh
        import numpy as np
        from ..compress import get_codec
        from ..parallel import reshard as reshard_lib

        live = 5000                    # deliberately non-round
        unit = 1 if codec_name is None else get_codec(codec_name).pad_elems
        pad_src = live + (-live) % (n_src * unit)
        pad_tgt = live + (-live) % (n_tgt * unit)
        plan = reshard_lib.make_plan(
            live, n_src, pad_src, n_tgt, pad_tgt,
            n_flat_leaves=n_flat_leaves, residual=residual)
        mesh = Mesh(np.array(jax.devices()[:plan.flat.n_union]), ("dp",))
        fn = reshard_lib.lower_apply(plan, mesh, "dp", donate=True)
        jx = jax.make_jaxpr(fn)(*reshard_lib.abstract_operands(plan))
        n_ops = plan.n_flat_leaves + (1 if plan.residual else 0)
        return jx, plan.wire_bytes(), n_ops
    return build


def j8_surfaces() -> List[Tuple[str, Callable]]:
    """(name, build) pairs; build() -> (closed jaxpr, declared wire
    bytes, donated operand count).  GRAFTLINT_J8_FIXTURE appends a
    surface from a module path exposing ``build()`` — the bad-fixture /
    exit-code hook, same contract as J7's."""
    surfaces: List[Tuple[str, Callable]] = [
        ("reshard dp8->dp4 adamw", _j8_build(8, 4, None, 3, False)),
        ("reshard dp8->dp3 non-divisor", _j8_build(8, 3, None, 1, False)),
        ("reshard dp8->dp4 topk+EF", _j8_build(8, 4, "topk", 2, True)),
    ]
    import os
    fixture = os.environ.get("GRAFTLINT_J8_FIXTURE")
    if fixture:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_j8_fixture",
                                                      fixture)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surfaces.append((f"fixture:{os.path.basename(fixture)}",
                         mod.build))
    return surfaces


def check_reshard_program(name: str, build: Callable) -> List[Finding]:
    """Evaluate one J8 surface against the three invariants."""
    findings: List[Finding] = []
    jx, declared, n_ops = build()
    c = _collect(jx.jaxpr)
    cell = f"jaxpr[reshard {name}]"
    if c["callbacks"]:
        findings.append(Finding(
            "J8", cell, 0,
            f"{c['callbacks']} callback primitive(s) in the transfer "
            "program — a reshard that round-trips the host is a "
            "checkpoint restore wearing a costume"))
    if c["wire_unknown"]:
        findings.append(Finding(
            "J8", cell, 0,
            "ppermute under a while_loop — transfer bytes not statically "
            "accountable (lower with a static table, not a data-"
            "dependent loop)"))
    elif c["wire_bytes"] != declared:
        findings.append(Finding(
            "J8", cell, 0,
            f"the lowered program's ppermute operands move "
            f"{c['wire_bytes']} bytes but the intersection table "
            f"declares {declared} changing owner — the reshard wire "
            "accounting (MTTR claims, obs counters) is lying"))
    donated = c["donated"] or ()
    if sum(donated) < n_ops:
        findings.append(Finding(
            "J8", cell, 0,
            f"expected all {n_ops} source operands donated, pjit "
            f"donated_invars shows {sum(donated)}/{len(donated)} — the "
            "transfer holds two full states in memory"))
    return findings


def run_j8(verbose: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    for name, build in j8_surfaces():
        try:
            fs = check_reshard_program(name, build)
        except Exception as e:  # noqa: BLE001 — a surface must fail LOUDLY
            fs = [Finding("J8", f"jaxpr[reshard {name}]", 0,
                          f"surface failed to evaluate: "
                          f"{type(e).__name__}: {str(e)[:300]}")]
        findings.extend(fs)
        if verbose:
            print(f"[graftlint:jaxpr] reshard {name}: "
                  f"{'FAIL' if fs else 'ok'}")
    return findings


# ---------------------------------------------------------------------------
# J9 — hierarchical (intra x inter) collectives (ops.ring_hier).  The
# EQuARX-style claim — codec only on the SLOW hop — is a program
# property, so it is checked on the program: every ppermute in the
# lowered collective is classified by its permutation (intra = pairs
# stay inside a group of n_intra consecutive ranks; inter = pairs keep
# their intra position), and per class the operand bytes x static trip
# counts must equal the HierarchicalPlan's declaration EXACTLY, with
# every intra-hop operand a 4-byte float (a codec payload on the fast
# hop is the regression this rule freezes out).  Permutations that are
# neither class are findings: a flat collective smuggled into a
# "hierarchical" program breaks the accounting the tuner banks.
# ---------------------------------------------------------------------------

def _collect_ppermutes(jaxpr) -> List[Dict[str, Any]]:
    """Per-ppermute records: perm pairs, static trip multiplier (None =
    unaccountable), operand bytes per execution, operand dtypes."""
    out: List[Dict[str, Any]] = []
    for eqn, mult in _iter_eqns(jaxpr):
        if eqn.primitive.name != "ppermute":
            continue
        perm = tuple((int(s), int(d)) for s, d in eqn.params.get("perm", ()))
        out.append({
            "perm": perm,
            "mult": mult,
            "bytes": sum(_aval_bytes(v.aval) for v in eqn.invars),
            "dtypes": sorted({str(v.aval.dtype) for v in eqn.invars
                              if getattr(v, "aval", None) is not None}),
            "f32_only": all(
                getattr(v.aval.dtype, "kind", "") == "f"
                and v.aval.dtype.itemsize == 4
                for v in eqn.invars if getattr(v, "aval", None) is not None),
        })
    return out


def _classify_perm(perm, n_intra: int) -> str:
    if not perm:
        return "other"
    if all(s // n_intra == d // n_intra for s, d in perm):
        return "intra"
    if all(s % n_intra == d % n_intra for s, d in perm):
        return "inter"
    return "other"


def check_hier_program(name: str, build: Callable) -> List[Finding]:
    """Evaluate one J9 surface.  build() -> (closed jaxpr, plan, which)
    where plan is an ops.ring_hier.HierarchicalPlan and which names the
    collective ("reduce_scatter" / "all_gather" / "all_reduce")."""
    findings: List[Finding] = []
    jx, plan, which = build()
    cell = f"jaxpr[hier {name}]"
    perms = _collect_ppermutes(jx.jaxpr)
    got = {"intra": 0, "inter": 0}
    for p in perms:
        klass = _classify_perm(p["perm"], plan.n_intra)
        if klass == "other":
            findings.append(Finding(
                "J9", cell, 0,
                f"ppermute whose permutation is neither intra nor inter "
                f"for n_intra={plan.n_intra} (first pairs "
                f"{p['perm'][:4]}) — a non-hierarchical collective inside "
                "a declared-hierarchical program breaks the banked "
                "accounting"))
            continue
        if p["mult"] is None:
            findings.append(Finding(
                "J9", cell, 0,
                f"{klass} ppermute under a while_loop — hop bytes not "
                "statically accountable (use fori_loop/scan with a "
                "static trip count)"))
            continue
        got[klass] += p["mult"] * p["bytes"]
        if klass == "intra" and not p["f32_only"]:
            findings.append(Finding(
                "J9", cell, 0,
                f"intra-hop ppermute carries non-f32 operands "
                f"{p['dtypes']} — the FAST hop must be codec-free (full "
                "precision is free there; that is the whole point of the "
                "hierarchical split)"))
    declared = {"intra": plan.intra_bytes(which),
                "inter": plan.inter_bytes(which)}
    for klass in ("intra", "inter"):
        if got[klass] != declared[klass]:
            findings.append(Finding(
                "J9", cell, 0,
                f"{klass}-hop ppermute operands move {got[klass]} bytes "
                f"but the HierarchicalPlan declares {declared[klass]} "
                f"for {which} — the hierarchical wire accounting (tuner "
                "scores, obs counters, bench ratios) is lying"))
    return findings


def _j9_build(codec_name: Optional[str], n_intra: int, which: str,
              L: int = 8192):
    def build():
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        from ..compress import get_codec
        from ..ops import ring_hier

        codec = get_codec(codec_name) if codec_name else None
        unit = _NDEV * (codec.pad_elems if codec else 1)
        Lp = L + (-L) % unit
        plan = ring_hier.plan_hier(Lp, _NDEV, n_intra, codec)
        mesh = Mesh(np.array(jax.devices()[:_NDEV]), ("dp",))

        def prog(x):
            if which == "reduce_scatter":
                return ring_hier.hier_reduce_scatter(
                    x, "dp", n_intra, compression=codec)
            if which == "all_gather":
                return ring_hier.hier_all_gather(
                    x, "dp", n_intra, compression=codec)
            return ring_hier.hier_all_reduce(
                x, "dp", n_intra, compression=codec)

        shape = (Lp // _NDEV,) if which == "all_gather" else (Lp,)
        jx = jax.make_jaxpr(jax.jit(jax.shard_map(
            prog, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False)))(
            jax.ShapeDtypeStruct((_NDEV * shape[0],), jnp.float32))
        return jx, plan, which
    return build


def j9_surfaces() -> List[Tuple[str, Callable]]:
    """(name, build) pairs covering codec x factorization x collective.
    GRAFTLINT_J9_FIXTURE appends a surface from a module path exposing
    ``build()`` — the bad-fixture / exit-code hook, same contract as
    J7/J8's."""
    surfaces: List[Tuple[str, Callable]] = [
        ("rs ni=2 bfp", _j9_build("bfp", 2, "reduce_scatter")),
        ("ag ni=2 bfp", _j9_build("bfp", 2, "all_gather")),
        ("rs ni=4 topk", _j9_build("topk", 4, "reduce_scatter")),
        ("ar ni=2 int8", _j9_build("int8", 2, "all_reduce")),
        ("ar ni=4 none", _j9_build(None, 4, "all_reduce")),
    ]
    import os
    fixture = os.environ.get("GRAFTLINT_J9_FIXTURE")
    if fixture:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_j9_fixture",
                                                      fixture)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surfaces.append((f"fixture:{os.path.basename(fixture)}",
                         mod.build))
    return surfaces


def run_j9(verbose: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    for name, build in j9_surfaces():
        try:
            fs = check_hier_program(name, build)
        except Exception as e:  # noqa: BLE001 — a surface must fail LOUDLY
            fs = [Finding("J9", f"jaxpr[hier {name}]", 0,
                          f"surface failed to evaluate: "
                          f"{type(e).__name__}: {str(e)[:300]}")]
        findings.extend(fs)
        if verbose:
            print(f"[graftlint:jaxpr] hier {name}: "
                  f"{'FAIL' if fs else 'ok'}")
    return findings


# ---------------------------------------------------------------------------
# J10 — the serving decode plane (serve.engine) must be recompile-free
# across (active-set, page-assignment) changes.  The continuous-batching
# contract is that admissions, evictions, slot churn and page recycling
# change operand VALUES only; a step whose jaxpr depends on scheduler
# state (e.g. batching only the active slots, so the batch dim tracks
# the active count) retraces on every transition and the serving tail
# latency grows a compile spike.  Like J7, this rule runs CONCRETELY: a
# tiny engine serves a scripted two-wave schedule sized to force
# eviction + readmission + page recycling, and each jitted program's
# counted traces (serve.engine.counted_jit) must equal exactly 1.  A
# schedule that fails to exercise eviction is itself a finding — the
# check must not rot into vacuity.
# ---------------------------------------------------------------------------

def _j10_engine_build() -> Callable:
    def run() -> Dict[str, int]:
        import jax
        import numpy as np
        from ..models import llama
        from ..serve import ServeConfig, ServeEngine

        cfg = llama.LlamaConfig.tiny(vocab=64, dim=32, n_layers=1,
                                     n_heads=2, n_kv_heads=1, ffn_dim=64)
        params = llama.init(jax.random.PRNGKey(0), cfg)
        scfg = ServeConfig(max_reqs=3, page_size=4, n_pages=5,
                           max_pages_per_seq=4, prefill_chunk=4)
        eng = ServeEngine(params, cfg, scfg)
        rng = np.random.default_rng(11)
        for _ in range(5):
            eng.submit(rng.integers(0, cfg.vocab,
                                    int(rng.integers(3, 10))).astype(
                np.int32), max_new=int(rng.integers(2, 6)))
        eng.run()
        for i in range(4):
            eng.submit(rng.integers(0, cfg.vocab,
                                    int(rng.integers(3, 10))).astype(
                np.int32), max_new=3, not_before_s=0.01 * i)
        eng.run()
        counts = dict(eng.trace_counts())
        counts["_exercised"] = int(eng.batcher.evictions > 0
                                   and eng.stats.as_dict()["completed"] == 9)
        return counts
    return run


def _j10_engine_tp_build() -> Callable:
    """The same scripted schedule over the TP-SHARDED tick: one replica
    spanning a 2-way mesh via shard_map (pool kv-sharded, kernel attend
    path on).  shard_map must not add a trace axis of its own — page
    reassignment, slot churn and the mesh wrapper together still leave
    exactly one trace per program."""
    def run() -> Dict[str, int]:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from ..models import llama
        from ..serve import ServeConfig, ServeEngine

        cfg = llama.LlamaConfig.tiny(vocab=64, dim=32, n_layers=1,
                                     n_heads=2, n_kv_heads=1, ffn_dim=64)
        params = llama.init(jax.random.PRNGKey(0), cfg)
        # page_integrity off: the checksum ledger is global-pool-only
        # and the tp tick rejects it at construction
        scfg = ServeConfig(max_reqs=3, page_size=4, n_pages=5,
                           max_pages_per_seq=4, prefill_chunk=4,
                           page_integrity=False)
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
        # reference attend keeps this surface ~3s cheaper per sweep on
        # the 1-core CI box; the pallas-impl tp tick's trace count is
        # asserted by tests/test_paged_attend.py (TestTpParity
        # test_tp_engine_tick_tokens_and_traces), so the kernel axis
        # stays covered without paying its interpret-mode compile here
        eng = ServeEngine(params, cfg, scfg, tp_mesh=mesh,
                          attend_impl="reference")
        rng = np.random.default_rng(11)
        for _ in range(5):
            eng.submit(rng.integers(0, cfg.vocab,
                                    int(rng.integers(3, 10))).astype(
                np.int32), max_new=int(rng.integers(2, 6)))
        eng.run()
        for i in range(4):
            eng.submit(rng.integers(0, cfg.vocab,
                                    int(rng.integers(3, 10))).astype(
                np.int32), max_new=3, not_before_s=0.01 * i)
        eng.run()
        counts = dict(eng.trace_counts())
        counts["_exercised"] = int(eng.batcher.evictions > 0
                                   and eng.stats.as_dict()["completed"] == 9)
        return counts
    return run


def check_serve_trace(name: str, build: Callable) -> List[Finding]:
    """Evaluate one J10 surface.  ``build()`` returns a zero-arg runner
    executing the scripted schedule and returning {phase: traces}
    (optionally ``_exercised``: falsy = the schedule proved nothing)."""
    findings: List[Finding] = []
    cell = f"jaxpr[serve {name}]"
    counts = dict(build()())
    exercised = counts.pop("_exercised", 1)
    if not exercised:
        findings.append(Finding(
            "J10", cell, 0,
            "the scripted admit/evict schedule exercised no eviction/"
            "readmission (or lost requests) — the recompile check is "
            "vacuous; widen the schedule"))
    for phase, n in sorted(counts.items()):
        if n > 1:
            findings.append(Finding(
                "J10", cell, 0,
                f"serving '{phase}' step traced {n}x across the scripted "
                "admit/evict schedule — the decode plane's jaxpr depends "
                "on scheduler state (slot occupancy / page assignment / "
                "active-set size); those must be operand VALUES under "
                "static ServeConfig shapes so steady-state serving "
                "records 0 recompiles"))
    return findings


def j10_surfaces() -> List[Tuple[str, Callable]]:
    """(name, build) pairs.  GRAFTLINT_J10_FIXTURE appends a surface from
    a module path exposing ``build()`` — the bad-fixture / exit-code
    hook, same contract as J7/J8/J9's."""
    surfaces: List[Tuple[str, Callable]] = [
        ("engine admit/evict schedule", _j10_engine_build),
        ("tp-sharded engine admit/evict schedule", _j10_engine_tp_build),
    ]
    import os
    fixture = os.environ.get("GRAFTLINT_J10_FIXTURE")
    if fixture:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_j10_fixture",
                                                      fixture)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surfaces.append((f"fixture:{os.path.basename(fixture)}",
                         mod.build))
    return surfaces


def run_j10(verbose: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    for name, build in j10_surfaces():
        try:
            fs = check_serve_trace(name, build)
        except Exception as e:  # noqa: BLE001 — a surface must fail LOUDLY
            fs = [Finding("J10", f"jaxpr[serve {name}]", 0,
                          f"surface failed to evaluate: "
                          f"{type(e).__name__}: {str(e)[:300]}")]
        findings.extend(fs)
        if verbose:
            print(f"[graftlint:jaxpr] serve {name}: "
                  f"{'FAIL' if fs else 'ok'}")
    return findings


# ---------------------------------------------------------------------------
# J11 — the serving-plane KV handoff program (serve.handoff).  The
# fleet's zero-replay claim rests on the migration being a pure
# device-side transfer that moves EXACTLY the migrated pages: like J8
# for the training reshard, the lowered pair-ppermute program is traced
# abstractly and must be callback-free, donate every pool operand, and
# move ppermute operand bytes == HandoffPlan.wire_bytes() precisely
# (page ids / table rows / host tokens are declared as host_bytes,
# never smuggled into the wire accounting).  Surfaces cover a single
# page, a multi-page multi-layer move, and a GQA (kv_local > 1) pool.
# ---------------------------------------------------------------------------

def _j11_build(n_layers: int, kv_local: int, page_size: int,
               head_dim: int, n_pages: int, n_move: int):
    def build():
        import jax
        import numpy as np
        from jax.sharding import Mesh
        from ..serve import handoff as handoff_lib

        plan = handoff_lib.make_plan(
            n_layers=n_layers, kv_local=kv_local, page_size=page_size,
            head_dim=head_dim, n_pages=n_pages, n_move=n_move)
        mesh = Mesh(np.array(jax.devices()[:2]), ("rep",))
        fn = handoff_lib.lower_apply(plan, mesh, "rep", donate=True)
        jx = jax.make_jaxpr(fn)(*handoff_lib.abstract_operands(plan))
        return jx, plan.wire_bytes(), 2 * n_layers
    return build


def check_handoff_program(name: str, build: Callable) -> List[Finding]:
    """Evaluate one J11 surface: build() -> (closed jaxpr, declared wire
    bytes, donated pool-operand count)."""
    findings: List[Finding] = []
    jx, declared, n_pool = build()
    c = _collect(jx.jaxpr)
    cell = f"jaxpr[handoff {name}]"
    if c["callbacks"]:
        findings.append(Finding(
            "J11", cell, 0,
            f"{c['callbacks']} callback primitive(s) in the handoff "
            "program — a migration that round-trips the host is "
            "replay-from-prompt wearing a costume"))
    if c["wire_unknown"]:
        findings.append(Finding(
            "J11", cell, 0,
            "ppermute under a while_loop — handoff bytes not statically "
            "accountable (lower with static page counts, dynamic page "
            "IDS as operands)"))
    elif c["wire_bytes"] != declared:
        findings.append(Finding(
            "J11", cell, 0,
            f"the lowered program's ppermute operands move "
            f"{c['wire_bytes']} bytes but the HandoffPlan declares "
            f"{declared} — the fleet's handoff wire accounting (MTTR "
            "claims, FLEET_BENCH gate) is lying"))
    donated = c["donated"] or ()
    if sum(donated) < n_pool:
        findings.append(Finding(
            "J11", cell, 0,
            f"expected all {n_pool} pool operands donated, pjit "
            f"donated_invars shows {sum(donated)}/{len(donated)} — the "
            "transfer holds two full pools in memory"))
    return findings


def j11_surfaces() -> List[Tuple[str, Callable]]:
    """(name, build) pairs; GRAFTLINT_J11_FIXTURE appends a surface from
    a module path exposing ``build()`` — the bad-fixture / exit-code
    hook, same contract as J7–J10's."""
    surfaces: List[Tuple[str, Callable]] = [
        ("1 page 2 layers", _j11_build(2, 2, 4, 8, 8, 1)),
        ("5 pages 3 layers", _j11_build(3, 1, 8, 16, 12, 5)),
        ("gqa kv=4 3 pages", _j11_build(2, 4, 4, 8, 10, 3)),
    ]
    import os
    fixture = os.environ.get("GRAFTLINT_J11_FIXTURE")
    if fixture:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_j11_fixture",
                                                      fixture)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surfaces.append((f"fixture:{os.path.basename(fixture)}",
                         mod.build))
    return surfaces


def run_j11(verbose: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    for name, build in j11_surfaces():
        try:
            fs = check_handoff_program(name, build)
        except Exception as e:  # noqa: BLE001 — a surface must fail LOUDLY
            fs = [Finding("J11", f"jaxpr[handoff {name}]", 0,
                          f"surface failed to evaluate: "
                          f"{type(e).__name__}: {str(e)[:300]}")]
        findings.extend(fs)
        if verbose:
            print(f"[graftlint:jaxpr] handoff {name}: "
                  f"{'FAIL' if fs else 'ok'}")
    return findings


# ---------------------------------------------------------------------------
# J12 — wire-integrity coverage (ops.integrity).  PR 12's contract: every
# ppermute-bearing transfer program must CARRY its exact checksum check
# when integrity is requested — and carrying it must not change what
# rides the wire.  Each surface traces the shipped program twice
# (integrity on / off) and asserts, statically on the jaxprs:
#
#   guarded    the integrity=True trace contains uint32 checksum
#              arithmetic (the odd-weighted word sums) and emits a
#              boolean verdict output — an integrity flag that lowers to
#              nothing is coverage theater;
#   invisible  the ppermute operand bytes x static trip counts are
#              IDENTICAL between the two traces — no checksum ever rides
#              the wire, so the exact byte accounting frozen by
#              J4/J8/J9/J11 holds with integrity on (checksums travel as
#              psum'd scalars, never payload);
#   non-vacuous  the program has at least one ppermute to guard (except
#              the decode-tick surface, whose wire is the KV pool's
#              write-to-read window — it must emit the [n_pages] uint32
#              ledger and the checksum arithmetic instead).
#
# A surface may be waived ONLY through J12_WAIVERS (name -> reason) —
# the explicit, greppable escape hatch; the shipped tree must keep it
# EMPTY (tests/test_lint.py pins that), so any future ppermute program
# either carries its checksum or carries a visible waiver in review.
# ---------------------------------------------------------------------------

# name -> reason.  SHIPPED TREE: EMPTY — every surface is guarded.
J12_WAIVERS: Dict[str, str] = {}


def _u32_eqn_count(jaxpr) -> int:
    """# of eqns (nested) producing a uint32 output — the static
    signature of the ops.integrity word-sum arithmetic."""
    import numpy as np
    n = 0
    for eqn, _ in _iter_eqns(jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and getattr(aval, "dtype", None) is not None \
                    and aval.dtype == np.uint32:
                n += 1
                break
    return n


def _ppermute_count(jaxpr) -> int:
    return sum(1 for eqn, _ in _iter_eqns(jaxpr)
               if eqn.primitive.name == "ppermute")


def _has_bool_output(jaxpr) -> bool:
    import numpy as np
    return any(getattr(getattr(v, "aval", None), "dtype", None) == np.bool_
               for v in jaxpr.outvars)


def check_integrity_program(name: str, build: Callable) -> List[Finding]:
    """Evaluate one J12 surface.  ``build()`` returns a dict:
    kind='wire' with jx_on/jx_off (the integrity on/off twin traces of
    the same program), or kind='page' with jx + n_pages (the decode-tick
    ledger surface, whose guard is page checksums, not hop carries)."""
    import numpy as np
    findings: List[Finding] = []
    cell = f"jaxpr[integrity {name}]"
    spec = build()

    if spec["kind"] == "page":
        jx, n_pages = spec["jx"], spec["n_pages"]
        if _u32_eqn_count(jx.jaxpr) == 0:
            findings.append(Finding(
                "J12", cell, 0,
                "the decode-tick program carries NO exact checksum "
                "arithmetic — the per-page KV ledger (the tier that "
                "closes the finite wrong-KEY class the logit guard "
                "cannot see) has vanished from the traced program"))
        has_ledger = any(
            getattr(getattr(v, "aval", None), "dtype", None) == np.uint32
            and tuple(getattr(v.aval, "shape", ())) == (n_pages,)
            for v in jx.jaxpr.outvars)
        if not has_ledger:
            findings.append(Finding(
                "J12", cell, 0,
                f"the decode-tick program emits no [n_pages={n_pages}] "
                "uint32 ledger output — the next tick would have nothing "
                "to verify its input pool against (write-time -> "
                "read-time coverage broken)"))
        return findings

    jx_on, jx_off = spec["jx_on"], spec["jx_off"]
    n_pp = _ppermute_count(jx_on.jaxpr)
    if n_pp == 0:
        findings.append(Finding(
            "J12", cell, 0,
            "surface has no ppermute to guard — the integrity check is "
            "vacuous here; fix the surface (or waive it explicitly via "
            "J12_WAIVERS with a reason)"))
    if _u32_eqn_count(jx_on.jaxpr) == 0:
        findings.append(Finding(
            "J12", cell, 0,
            "integrity=True traced to a program with NO uint32 checksum "
            "arithmetic — the wire is unguarded; every ppermute program "
            "must carry its exact frame checksums (ops.integrity) or an "
            "explicit J12_WAIVERS entry"))
    if not _has_bool_output(jx_on.jaxpr):
        findings.append(Finding(
            "J12", cell, 0,
            "integrity=True program emits no boolean verdict output — a "
            "checksum nobody can act on guards nothing (return wire_ok "
            "so the recovery machinery can gate/invalidate the step)"))
    c_on, c_off = _collect(jx_on.jaxpr), _collect(jx_off.jaxpr)
    if c_on["wire_unknown"] or c_off["wire_unknown"]:
        findings.append(Finding(
            "J12", cell, 0,
            "ppermute under a while_loop — integrity-on/off wire bytes "
            "not statically comparable (use fori_loop/scan with static "
            "trip counts)"))
    elif c_on["wire_bytes"] != c_off["wire_bytes"]:
        findings.append(Finding(
            "J12", cell, 0,
            f"integrity=True moves {c_on['wire_bytes']} ppermute bytes "
            f"but the same program with integrity off moves "
            f"{c_off['wire_bytes']} — the checksum rides the wire.  The "
            "exact byte accounting (J4/J8/J9/J11, obs counters, banked "
            "ratios) must be IDENTICAL with integrity on: checksums "
            "travel as psum'd scalars, never as payload"))
    return findings


def _j12_ring_build(codec_name: Optional[str], which: str,
                    topology: str = "flat", n_intra: int = 2,
                    sliced: bool = False, L: int = 8192):
    def build():
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        from ..compress import get_codec
        from ..ops import ring as ring_ops, ring_hier

        codec = get_codec(codec_name) if codec_name else None
        unit = _NDEV * (codec.pad_elems if codec else 1)
        Lp = L + (-L) % unit
        slice_elems = (Lp // _NDEV) // 2 if sliced else None
        mesh = Mesh(np.array(jax.devices()[:_NDEV]), ("dp",))

        def trace(integrity: bool):
            def f(x):
                kw: Dict[str, Any] = dict(compression=codec,
                                          integrity=integrity)
                if topology == "hier":
                    if which == "reduce_scatter":
                        return ring_hier.hier_reduce_scatter(
                            x, "dp", n_intra, slice_elems=slice_elems,
                            **kw)
                    if which == "all_gather":
                        return ring_hier.hier_all_gather(x, "dp",
                                                         n_intra, **kw)
                    return ring_hier.hier_all_reduce(
                        x, "dp", n_intra, slice_elems=slice_elems, **kw)
                if which == "reduce_scatter":
                    return ring_ops.ring_reduce_scatter(
                        x, "dp", slice_elems=slice_elems, **kw)
                if which == "all_gather":
                    return ring_ops.ring_all_gather(x, "dp", **kw)
                return ring_ops.ring_all_reduce(
                    x, "dp", slice_elems=slice_elems, **kw)

            C = Lp // _NDEV
            per_dev = C if which == "all_gather" else Lp
            out_specs = (P("dp"), P()) if integrity else P("dp")
            return jax.make_jaxpr(jax.jit(jax.shard_map(
                f, mesh=mesh, in_specs=P("dp"), out_specs=out_specs,
                check_vma=False)))(
                jax.ShapeDtypeStruct((_NDEV * per_dev,), jnp.float32))

        return {"kind": "wire", "jx_on": trace(True),
                "jx_off": trace(False)}
    return build


def _j12_train_build(codec_name: Optional[str], fused: bool):
    def build():
        from ..utils.config import (CollectiveConfig, MeshConfig,
                                    OptimizerConfig, TrainConfig)

        def trace(integrity: bool):
            cfg = TrainConfig(
                mesh=MeshConfig(dp=_NDEV),
                collective=CollectiveConfig(impl="ring", codec=codec_name,
                                            fused_optimizer=fused,
                                            integrity_check=integrity),
                optimizer=OptimizerConfig(kind="adamw"),
                global_batch=_BATCH, obs_metrics=False)
            phases, _, _ = _trace_dp(cfg, "dp")
            return phases[0][1]

        return {"kind": "wire", "jx_on": trace(True),
                "jx_off": trace(False)}
    return build


def _j12_reshard_build(n_src: int, n_tgt: int, codec_name: Optional[str],
                       n_flat_leaves: int, residual: bool):
    def build():
        import jax
        from jax.sharding import Mesh
        import numpy as np
        from ..compress import get_codec
        from ..parallel import reshard as reshard_lib

        live = 5000
        unit = 1 if codec_name is None else get_codec(codec_name).pad_elems
        pad_src = live + (-live) % (n_src * unit)
        pad_tgt = live + (-live) % (n_tgt * unit)
        plan = reshard_lib.make_plan(
            live, n_src, pad_src, n_tgt, pad_tgt,
            n_flat_leaves=n_flat_leaves, residual=residual)
        mesh = Mesh(np.array(jax.devices()[:plan.flat.n_union]), ("dp",))
        ops = reshard_lib.abstract_operands(plan)

        def trace(integrity: bool):
            fn = reshard_lib.lower_apply(plan, mesh, "dp", donate=True,
                                         integrity=integrity)
            return jax.make_jaxpr(fn)(*ops)

        return {"kind": "wire", "jx_on": trace(True),
                "jx_off": trace(False)}
    return build


def _j12_handoff_build(n_layers: int, kv_local: int, page_size: int,
                       head_dim: int, n_pages: int, n_move: int):
    def build():
        import jax
        import numpy as np
        from jax.sharding import Mesh
        from ..serve import handoff as handoff_lib

        plan = handoff_lib.make_plan(
            n_layers=n_layers, kv_local=kv_local, page_size=page_size,
            head_dim=head_dim, n_pages=n_pages, n_move=n_move)
        mesh = Mesh(np.array(jax.devices()[:2]), ("rep",))

        def trace(integrity: bool):
            fn = handoff_lib.lower_apply(plan, mesh, "rep", donate=True,
                                         integrity=integrity)
            return jax.make_jaxpr(fn)(
                *handoff_lib.abstract_operands(plan, integrity=integrity))

        return {"kind": "wire", "jx_on": trace(True),
                "jx_off": trace(False)}
    return build


def _j12_decode_build():
    def build():
        import jax
        import jax.numpy as jnp
        from ..models import llama
        from ..serve import ServeConfig, ServeEngine

        cfg = llama.LlamaConfig.tiny(vocab=64, dim=32, n_layers=1,
                                     n_heads=2, n_kv_heads=1, ffn_dim=64)
        params = llama.init(jax.random.PRNGKey(0), cfg)
        scfg = ServeConfig(max_reqs=2, page_size=4, n_pages=4,
                           max_pages_per_seq=2, prefill_chunk=4,
                           page_integrity=True)
        eng = ServeEngine(params, cfg, scfg)
        toks = jnp.zeros((scfg.max_reqs, 1), jnp.int32)
        table = jnp.zeros((scfg.max_reqs, scfg.max_pages_per_seq),
                          jnp.int32)
        pos = jnp.zeros((scfg.max_reqs,), jnp.int32)
        act = jnp.zeros((scfg.max_reqs,), bool)
        jx = jax.make_jaxpr(eng._decode_impl)(
            eng.pool, eng.params, toks, table, pos, act, eng.ledger)
        return {"kind": "page", "jx": jx, "n_pages": scfg.n_pages}
    return build


def j12_surfaces() -> List[Tuple[str, Callable]]:
    """(name, build) pairs — one per ppermute-bearing program family x
    route shape (flat/hier/sliced, trainer step incl. the fused route,
    reshard, handoff) plus the decode-tick ledger surface.
    GRAFTLINT_J12_FIXTURE appends a surface from a module path exposing
    ``build()`` — the bad-fixture / exit-code hook, same contract as
    J7–J11's."""
    surfaces: List[Tuple[str, Callable]] = [
        ("ring rs bfp", _j12_ring_build("bfp", "reduce_scatter")),
        ("ring rs bfp sliced", _j12_ring_build("bfp", "reduce_scatter",
                                               sliced=True)),
        ("ring ag int8", _j12_ring_build("int8", "all_gather")),
        ("ring ar none", _j12_ring_build(None, "all_reduce")),
        ("hier rs ni=2 bfp", _j12_ring_build("bfp", "reduce_scatter",
                                             topology="hier", n_intra=2)),
        ("hier ar ni=4 int8", _j12_ring_build("int8", "all_reduce",
                                              topology="hier", n_intra=4)),
        ("train step adamw bfp", _j12_train_build("bfp", False)),
        ("train step fused-opt bfp", _j12_train_build("bfp", True)),
        ("reshard dp8->dp4 adamw", _j12_reshard_build(8, 4, None, 3,
                                                      False)),
        ("reshard dp8->dp3 topk+EF", _j12_reshard_build(8, 3, "topk", 2,
                                                        True)),
        ("handoff gqa 3 pages", _j12_handoff_build(2, 4, 4, 8, 10, 3)),
        ("decode tick page ledger", _j12_decode_build()),
    ]
    import os
    fixture = os.environ.get("GRAFTLINT_J12_FIXTURE")
    if fixture:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_j12_fixture",
                                                      fixture)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surfaces.append((f"fixture:{os.path.basename(fixture)}",
                         mod.build))
    return surfaces


def run_j12(verbose: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    for name, build in j12_surfaces():
        waiver = J12_WAIVERS.get(name)
        if waiver:
            # an explicit waiver is the ONLY sanctioned skip — loud in
            # the sweep output, greppable in review, and pinned EMPTY
            # for the shipped tree by tests/test_lint.py
            if verbose:
                print(f"[graftlint:jaxpr] integrity {name}: WAIVED "
                      f"({waiver})")
            continue
        try:
            fs = check_integrity_program(name, build)
        except Exception as e:  # noqa: BLE001 — a surface must fail LOUDLY
            fs = [Finding("J12", f"jaxpr[integrity {name}]", 0,
                          f"surface failed to evaluate: "
                          f"{type(e).__name__}: {str(e)[:300]}")]
        findings.extend(fs)
        if verbose:
            print(f"[graftlint:jaxpr] integrity {name}: "
                  f"{'FAIL' if fs else 'ok'}")
    return findings


# ---------------------------------------------------------------------------
# J13 — the adaptive-training candidate set (tune.adapt) must be traced
# UP FRONT and a runtime plan switch must cause ZERO new traces — the
# J10 counted-trace discipline applied to training.  The tempting-but-
# wrong implementation compiles the target plan lazily "when we need
# it": the switch then pays a compile spike exactly when the job is
# already degraded (the regime shift that triggered it), and every
# switch after that retraces again.  Like J10 this rule runs CONCRETELY:
# a tiny AdaptiveTrainer (fixture calibration — zero banked-artifact
# dependence) is built, prewarmed, stepped, forced through a plan switch
# (the deterministic inject_shift seam; the chaos `slowdown@collective`
# cell proves the measured detection path), and stepped again; every
# candidate's step must have traced EXACTLY once and the total trace
# count must not move across the switch.  A run that performs no switch
# (or has a one-plan "set") proves nothing and is itself a finding.
# ---------------------------------------------------------------------------

def _j13_adaptive_build() -> Callable:
    def run() -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from ..models import mlp
        from ..parallel import mesh as mesh_lib
        from ..tune import adapt as adapt_lib
        from ..tune.calibration import fixture_calibration
        from ..utils.config import (AdaptConfig, CollectiveConfig,
                                    MeshConfig, MLPConfig,
                                    OptimizerConfig, TrainConfig)

        mcfg = MLPConfig(layer_sizes=_LAYERS, dtype="float32")
        # THE shared fixture regime (tune.calibration.fixture_calibration
        # — also the adapt chaos cells'): a fast wire so plan 0 is the
        # uncompressed ring and the injected regime shift has a cheaper
        # wire format to move to — deterministic, no banked artifacts
        calib = fixture_calibration()
        cfg = TrainConfig(
            iters=8, global_batch=_BATCH, mesh=MeshConfig(dp=_NDEV),
            collective=CollectiveConfig(impl="ring", codec="auto"),
            optimizer=OptimizerConfig(),
            adapt=AdaptConfig(enabled=True, n_candidates=2,
                              live_calibration=False, warmup_steps=2,
                              cooldown_steps=2))
        at = adapt_lib.AdaptiveTrainer(
            lambda p, b: mlp.loss_fn(p, b, mcfg),
            mesh_lib.make_mesh(cfg.mesh), cfg, calibration=calib)
        params = mlp.init(jax.random.PRNGKey(0), mcfg)
        state = at.init_state(params)
        r = np.random.default_rng(0)
        batch = at.shard_batch((
            jnp.asarray(r.standard_normal((_BATCH, _LAYERS[0]))
                        .astype(np.float32)),
            jnp.asarray(r.integers(0, _LAYERS[-1], _BATCH)
                        .astype(np.int32))))
        for _ in range(3):
            state, _loss = at.step(state, batch)
        # the forced regime shift: the wire now behaves ~dead-slow, the
        # re-priced argmin moves to a compressed candidate
        at.controller.inject_shift(1e-4, step=3)
        for _ in range(3):
            state, _loss = at.step(state, batch)
        return {
            "candidates": at.trace_counts(),
            "switches": at.switches,
            "recompiles_across_switch": at.recompiles_across_switch,
            "_exercised": int(at.switches >= 1 and len(at.plans) >= 2),
        }
    return run


def check_adaptive_traces(name: str, build: Callable) -> List[Finding]:
    """Evaluate one J13 surface.  ``build()`` returns a zero-arg runner
    executing a scripted adaptive run and returning ``candidates``
    ({plan label: step trace count}), ``switches``,
    ``recompiles_across_switch`` and optionally ``_exercised`` (falsy =
    the run proved nothing)."""
    findings: List[Finding] = []
    cell = f"jaxpr[adapt {name}]"
    out = dict(build()())
    if not out.pop("_exercised", 1):
        findings.append(Finding(
            "J13", cell, 0,
            "the scripted adaptive run performed no plan switch (or the "
            "candidate set has fewer than 2 plans) — the counted-trace "
            "check is vacuous; widen the scenario"))
    for label, n in sorted(out.get("candidates", {}).items()):
        if n == 0:
            findings.append(Finding(
                "J13", cell, 0,
                f"candidate plan '{label}' was NEVER traced — the "
                "candidate set must be compiled up front at "
                "construction; a lazily-compiled plan pays its compile "
                "spike at the switch, exactly when the job is already "
                "degraded by the regime shift"))
        elif n > 1:
            findings.append(Finding(
                "J13", cell, 0,
                f"candidate plan '{label}' traced {n}x across the "
                "scripted run — a plan switch must replay the "
                "pre-compiled program, never retrace it (slot the "
                "switch-shaped state into the prewarm, or the jit cache "
                "misses on sharding/weak-type drift)"))
    rec = out.get("recompiles_across_switch", 0)
    if rec:
        findings.append(Finding(
            "J13", cell, 0,
            f"{rec} new trace(s) appeared across the plan switch — the "
            "switch must cause ZERO new traces (the J10 counted-trace "
            "discipline applied to training); trace every candidate's "
            "step AND gather programs at construction"))
    return findings


def j13_surfaces() -> List[Tuple[str, Callable]]:
    """(name, build) pairs.  GRAFTLINT_J13_FIXTURE appends a surface
    from a module path exposing ``build()`` — the bad-fixture /
    exit-code hook, same contract as J7–J12's."""
    surfaces: List[Tuple[str, Callable]] = [
        ("candidate-set switch schedule", _j13_adaptive_build),
    ]
    import os
    fixture = os.environ.get("GRAFTLINT_J13_FIXTURE")
    if fixture:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_j13_fixture",
                                                      fixture)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surfaces.append((f"fixture:{os.path.basename(fixture)}",
                         mod.build))
    return surfaces


def run_j13(verbose: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    for name, build in j13_surfaces():
        try:
            fs = check_adaptive_traces(name, build)
        except Exception as e:  # noqa: BLE001 — a surface must fail LOUDLY
            fs = [Finding("J13", f"jaxpr[adapt {name}]", 0,
                          f"surface failed to evaluate: "
                          f"{type(e).__name__}: {str(e)[:300]}")]
        findings.extend(fs)
        if verbose:
            print(f"[graftlint:jaxpr] adapt {name}: "
                  f"{'FAIL' if fs else 'ok'}")
    return findings


# ---------------------------------------------------------------------------
# J14 — durable-state integrity (utils.checkpoint).  The J12 discipline
# applied to disk: every restore path must AUDIT the stored bytes
# against the manifest's exact checksums, so a single flipped stored
# bit either REFUSES (CheckpointIntegrityError) or peer-repairs
# bit-exactly — never restores silently; the walk-back
# (restore_latest_verified / latest_step(verified=True)) must land on
# the previous verified step past a torn one; and the peer-repair pair
# transfer program must be callback-free, donate its source operand and
# move EXACTLY the shard bytes (the J8/J11 accounting applied to the
# repair wire).  Like J10/J13 the rule runs CONCRETELY: each surface
# saves a checkpoint into a temp dir, damages one stored bit, and
# drives the real restore path; a surface whose damage provably landed
# nowhere proves nothing and is itself a finding.  J14_WAIVERS is the
# only sanctioned skip and the shipped tree keeps it EMPTY
# (tests/test_lint.py pins that).
# ---------------------------------------------------------------------------

# name -> reason.  SHIPPED TREE: EMPTY — every restore path is audited.
J14_WAIVERS: Dict[str, str] = {}


def _j14_refuse_build() -> Callable:
    def run() -> Dict[str, Any]:
        import os
        import tempfile
        import numpy as np
        from ..utils import checkpoint as ckpt_lib
        with tempfile.TemporaryDirectory(prefix="j14_refuse_") as d:
            c = ckpt_lib.Checkpointer(d)      # no mirror: refusal path
            golden = np.random.default_rng(0).standard_normal(256) \
                .astype(np.float32)
            c.save(1, {"w": golden})
            ckpt_lib.flip_stored_bit(
                os.path.join(c._path(1), "leaf_00000.npy"))
            out: Dict[str, Any] = {"surface": "Checkpointer.restore",
                                   "detected": 0, "silently_restored": 0,
                                   "_exercised": 1}
            try:
                tree = c.restore(1)
                # a byte flipped on disk and restore handed bytes back:
                # silent restore whether or not they happen to differ
                out["silently_restored"] = 1
                out["_exercised"] = int(
                    not np.array_equal(tree["w"], golden))
            except ckpt_lib.CheckpointIntegrityError:
                out["detected"] = 1
            return out
    return run


def _j14_repair_build() -> Callable:
    def run() -> Dict[str, Any]:
        import os
        import tempfile
        import numpy as np
        from ..utils import checkpoint as ckpt_lib
        with tempfile.TemporaryDirectory(prefix="j14_repair_") as d:
            c = ckpt_lib.Checkpointer(d, shards=4, mirror=True)
            golden = np.random.default_rng(1).standard_normal(1024) \
                .astype(np.float32)
            c.save(1, {"w": golden})
            ckpt_lib.flip_stored_bit(
                os.path.join(c._path(1), "leaf_00000.s01.npy"))
            rep = c.audit_step(1, repair=True)
            shard_bytes = golden[256:512].nbytes
            out: Dict[str, Any] = {
                "surface": "Checkpointer.restore(repair)",
                "detected": int(bool(rep.repaired or rep.failures)),
                "silently_restored": int(not rep.repaired
                                         and not rep.failures),
                "repaired": len(rep.repaired),
                "bit_exact": int(rep.restorable
                                 and np.array_equal(rep.tree["w"],
                                                    golden)),
                "runtime_wire_bytes": rep.repair_wire_bytes,
                "declared_bytes": shard_bytes,
                "_exercised": 1,
            }
        # static half: the pair transfer program itself (J8/J11-style
        # accounting on the repair wire)
        import jax
        fn, _mesh = ckpt_lib.pair_transfer_fn(shard_bytes)
        if fn is None:
            out["_exercised"] = 0       # single-device runtime
            return out
        jx = jax.make_jaxpr(fn)(
            jax.ShapeDtypeStruct((2, shard_bytes), np.uint8))
        co = _collect(jx.jaxpr)
        out["callbacks"] = co["callbacks"]
        out["wire_bytes"] = co["wire_bytes"]
        donated = co["donated"] or ()
        out["donated"] = int(sum(donated)) if donated else 0
        return out
    return run


def _j14_walkback_build() -> Callable:
    def run() -> Dict[str, Any]:
        import os
        import tempfile
        import numpy as np
        from ..utils import checkpoint as ckpt_lib
        with tempfile.TemporaryDirectory(prefix="j14_walk_") as d:
            c = ckpt_lib.Checkpointer(d)
            g1 = np.random.default_rng(2).standard_normal(128) \
                .astype(np.float32)
            c.save(1, {"w": g1})
            c.save(2, {"w": g1 + 1.0})
            # tear the newest step's manifest (the kill-during-save
            # shape)
            with open(os.path.join(c._path(2), ckpt_lib.MANIFEST_FILE),
                      "w") as f:
                f.write("{\"format\": 2, \"truncat")
            step, tree = c.restore_latest_verified()
            return {
                "surface": "Checkpointer.restore_latest_verified",
                "detected": int(step == 1),
                "silently_restored": int(step == 2),
                "bit_exact": int(np.array_equal(tree["w"], g1)),
                "verified_step": int(c.latest_step(verified=True) or -1),
                "_exercised": int(c.latest_step() == 2),
            }
    return run


def check_restore_audit(name: str, build: Callable) -> List[Finding]:
    """Evaluate one J14 surface.  ``build()`` returns a zero-arg runner
    that saves/damages/restores a real checkpoint and reports:
    ``detected`` (the damage refused, repaired or walked past),
    ``silently_restored`` (damaged bytes handed to the caller — THE
    violation), optional ``repaired``/``bit_exact``/``wire_bytes``/
    ``declared_bytes``/``callbacks``/``donated`` for the repair
    program, and ``_exercised`` (falsy = the damage provably landed
    nowhere, which proves nothing)."""
    findings: List[Finding] = []
    cell = f"jaxpr[ckpt {name}]"
    out = dict(build()())
    if not out.pop("_exercised", 1):
        findings.append(Finding(
            "J14", cell, 0,
            "the scripted damage landed nowhere (or the runtime cannot "
            "exercise the surface) — the audit check is vacuous; widen "
            "the scenario"))
        return findings
    if out.get("silently_restored"):
        findings.append(Finding(
            "J14", cell, 0,
            f"{out.get('surface', name)} handed back bytes from a "
            "checkpoint with a flipped stored bit without refusing or "
            "repairing — the disk-corruption blind spot (a corrupt "
            "master silently becomes the restore target); every restore "
            "path must audit against the manifest checksums"))
    elif not out.get("detected"):
        findings.append(Finding(
            "J14", cell, 0,
            f"{out.get('surface', name)} neither detected nor survived "
            "the stored-bit damage — the audit/walk-back contract is "
            "broken"))
    if "bit_exact" in out and not out["bit_exact"]:
        findings.append(Finding(
            "J14", cell, 0,
            "the repaired/walked-back state is not bit-identical to the "
            "uncorrupted golden — repair must hand back EXACTLY the "
            "bytes the manifest describes"))
    if "repaired" in out and out["repaired"] < 1:
        findings.append(Finding(
            "J14", cell, 0,
            "a corrupt primary with a clean peer mirror was not "
            "repaired — the peer-repair tier never fired"))
    if "wire_bytes" in out and out["wire_bytes"] != out["declared_bytes"]:
        findings.append(Finding(
            "J14", cell, 0,
            f"the pair repair program's ppermute operands move "
            f"{out['wire_bytes']} bytes but the shard is "
            f"{out['declared_bytes']} — the repair wire accounting "
            "(CKPT_BENCH repair_wire_bytes) is lying"))
    if "runtime_wire_bytes" in out and \
            out["runtime_wire_bytes"] != out["declared_bytes"]:
        findings.append(Finding(
            "J14", cell, 0,
            f"the executed repair recorded {out['runtime_wire_bytes']} "
            f"wire bytes for a {out['declared_bytes']}-byte shard"))
    if out.get("callbacks"):
        findings.append(Finding(
            "J14", cell, 0,
            f"{out['callbacks']} callback primitive(s) inside the pair "
            "repair program — the transfer must be pure device code"))
    if "donated" in out and out["donated"] < 1:
        findings.append(Finding(
            "J14", cell, 0,
            "the pair repair program does not donate its source operand "
            "— repair would hold two copies of the shard in memory"))
    return findings


def j14_surfaces() -> List[Tuple[str, Callable]]:
    """(name, build) pairs.  GRAFTLINT_J14_FIXTURE appends a surface
    from a module path exposing ``build()`` — the bad-fixture /
    exit-code hook, same contract as J7–J13's."""
    surfaces: List[Tuple[str, Callable]] = [
        ("refuse unmirrored bit flip", _j14_refuse_build),
        ("peer-repair mirrored shard", _j14_repair_build),
        ("walk back past torn step", _j14_walkback_build),
    ]
    import os
    fixture = os.environ.get("GRAFTLINT_J14_FIXTURE")
    if fixture:
        import importlib.util
        spec = importlib.util.spec_from_file_location("_j14_fixture",
                                                      fixture)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        surfaces.append((f"fixture:{os.path.basename(fixture)}",
                         mod.build))
    return surfaces


def run_j14(verbose: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    for name, build in j14_surfaces():
        waiver = J14_WAIVERS.get(name)
        if waiver:
            if verbose:
                print(f"[graftlint:jaxpr] ckpt {name}: WAIVED ({waiver})")
            continue
        try:
            fs = check_restore_audit(name, build)
        except Exception as e:  # noqa: BLE001 — a surface must fail LOUDLY
            fs = [Finding("J14", f"jaxpr[ckpt {name}]", 0,
                          f"surface failed to evaluate: "
                          f"{type(e).__name__}: {str(e)[:300]}")]
        findings.extend(fs)
        if verbose:
            print(f"[graftlint:jaxpr] ckpt {name}: "
                  f"{'FAIL' if fs else 'ok'}")
    return findings


def sweep_grid() -> List[Tuple[Optional[str], str, bool]]:
    """(codec, trainer, obs) cells — registry-driven, so a future codec
    is auto-covered; None = uncompressed ring baseline."""
    from ..compress import available_codecs
    cells = []
    for codec in (None,) + tuple(available_codecs()):
        for trainer in _TRAINERS:
            for obs in (False, True):
                cells.append((codec, trainer, obs))
    return cells


# fused-optimizer donation cells (the acceptance gate of the fused-ring
# issue: moments + master params must stay donated on the fused
# TrainState/FSDPState) — a focused extra sweep rather than a fourth grid
# axis, so the grid's public (codec, trainer, obs) triple shape is stable
_FUSED_OPT_CELLS = ((None, "DPTrainer"), ("bfp", "DPTrainer"),
                    ("topk", "DPTrainer"), (None, "FSDPTrainer"),
                    ("bfp", "FSDPTrainer"))


def run_fused_opt_cells(verbose: bool = False) -> List[Finding]:
    from ..utils.config import (CollectiveConfig, MeshConfig,
                                OptimizerConfig, TrainConfig)
    findings: List[Finding] = []
    for codec_name, trainer in _FUSED_OPT_CELLS:
        cell = f"jaxpr[fused-opt {codec_name or 'none'} x {trainer}]"
        trace_fn, axis = _TRAINERS[trainer]
        try:
            cfg = TrainConfig(
                mesh=MeshConfig(**{axis: _NDEV}),
                collective=CollectiveConfig(impl="ring", codec=codec_name,
                                            fused_optimizer=True),
                optimizer=OptimizerConfig(kind="adamw"),
                global_batch=_BATCH, obs_metrics=False)
            phases, L, n = trace_fn(cfg, axis)
            cell_findings = _check_cell(cell, trainer, codec_name, False,
                                        phases, L, n, mesh_axes=(axis,))
        except Exception as e:  # noqa: BLE001 — a cell must fail LOUDLY
            cell_findings = [Finding(
                "J6", cell, 0, f"cell failed to trace: {type(e).__name__}: "
                f"{str(e)[:300]}")]
        findings.extend(cell_findings)
        if verbose:
            status = "FAIL" if cell_findings else "ok"
            print(f"[graftlint:jaxpr] {cell}: {status}")
    return findings


def run_sweep(verbose: bool = False) -> List[Finding]:
    _require_cpu_mesh()
    from ..compress import available_codecs
    from ..utils.config import (CollectiveConfig, MeshConfig, TrainConfig)

    findings: List[Finding] = []
    grid = sweep_grid()
    grid_codecs = {c for c, _, _ in grid}
    for codec_name, trainer, obs in grid:
        cell = (f"jaxpr[{codec_name or 'none'} x {trainer} x "
                f"obs={'on' if obs else 'off'}]")
        trace_fn, axis = _TRAINERS[trainer]
        mesh_kwargs = {axis: _NDEV}
        try:
            # config construction is inside the try: an unconstructible
            # registered codec must fail as a LOUD J6 cell, not a crash
            cfg = TrainConfig(
                mesh=MeshConfig(**mesh_kwargs),
                collective=CollectiveConfig(impl="ring", codec=codec_name),
                global_batch=_BATCH, obs_metrics=obs)
            phases, L, n = trace_fn(cfg, axis)
            cell_findings = _check_cell(
                cell, trainer, codec_name, obs, phases, L, n,
                mesh_axes=(axis,))
        except Exception as e:  # noqa: BLE001 — a cell must fail LOUDLY
            cell_findings = [Finding(
                "J6", cell, 0, f"cell failed to trace: {type(e).__name__}: "
                f"{str(e)[:300]}")]
        findings.extend(cell_findings)
        if verbose:
            status = "FAIL" if cell_findings else "ok"
            print(f"[graftlint:jaxpr] {cell}: {status}")
    # coverage: the grid snapshot was taken from the registry BEFORE any
    # cell traced; a codec registered during the sweep (e.g. by an import
    # a trainer pulls in) would otherwise be silently missed.  Same-set
    # coverage of the snapshot itself is asserted by tests/test_lint.py.
    missing = set(available_codecs()) - grid_codecs
    if missing:
        findings.append(Finding(
            "J6", "jaxpr[coverage]", 0,
            f"codec(s) registered after the grid snapshot, never swept: "
            f"{sorted(missing)} — re-run the sweep"))
    findings.extend(run_fused_opt_cells(verbose=verbose))
    findings.extend(run_j7(verbose=verbose))
    findings.extend(run_j8(verbose=verbose))
    findings.extend(run_j9(verbose=verbose))
    findings.extend(run_j10(verbose=verbose))
    findings.extend(run_j11(verbose=verbose))
    findings.extend(run_j12(verbose=verbose))
    findings.extend(run_j13(verbose=verbose))
    findings.extend(run_j14(verbose=verbose))
    return findings
