"""fpga_ai_nic_tpu — a TPU-native reimagination of libxsmm/fpga_ai_nic.

The reference (an Intel Arria-10 FPGA "AI smart NIC") offloads the gradient
all-reduce *and* the SGD weight update of data-parallel training onto the NIC,
optionally compressing ring traffic with block-floating-point (BFP).  This
package rebuilds every capability of that system TPU-first:

- ``ops.bfp``          — BFP codec (ref: hw/bf16_to_bfp_core.sv, hw/bfp_to_bf16_core.sv)
- ``compress``         — pluggable gradient-compression codec subsystem: the
                         Codec protocol + registry with bfp / top-k (error
                         feedback, SparCML-style) / int8 (stochastic rounding,
                         EQuARX-style) — the generalization of the single
                         wire trick the reference hard-wires (docs/COMPRESSION.md)
- ``ops.ring``         — sliced ring reduce-scatter / all-gather over ``lax.ppermute``
                         (ref: hw/all_reduce.sv st_eth_t FSM)
- ``ops.fused_update`` — fused scatter → SGD → all-gather-of-updated-weights
                         (ref: hw/weight_update.sv + hw/all_reduce.sv)
- ``parallel``         — mesh / sharding / DP / ZeRO-1 / TP / SP train steps
                         (ref: sw/mlp_mpi_example_f32.cpp training driver)
- ``runtime``          — async collective queue with bounded in-flight window and
                         done-flag futures (ref: sw/mlp_mpi_example_f32.cpp:114-180),
                         native C++ host codec (csrc/)
- ``models``           — MLP / ResNet-50 / BERT / Llama model zoo (BASELINE.json configs)
- ``utils``            — unified config system, observability, checkpointing

Nothing here is a translation: the compute path is JAX/XLA/Pallas over a
``jax.sharding.Mesh``; collectives ride ICI via ``psum_scatter``/``ppermute``.
"""

from . import compat as _compat

_compat.install()

__version__ = "0.1.0"
