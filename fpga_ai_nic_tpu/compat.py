"""JAX version compatibility — one place where API drift is absorbed.

The codebase is written against the current JAX surface (``jax.shard_map``
with varying-manual-axes checking, ``lax.pcast``, ``lax.axis_size``,
``jax.typeof``).  Containers in the fleet pin older jaxlibs (the tunnel
plugin lags upstream), where the same capabilities exist under older names
(``jax.experimental.shard_map.shard_map`` with ``check_rep``) or not at all
(the vma type system).  ``install()`` polyfills the missing names onto the
``jax``/``jax.lax`` modules so the rest of the repo — and its tests and
tools — run unchanged on both:

- ``jax.shard_map(f, mesh=, in_specs=, out_specs=, check_vma=)`` →
  ``jax.experimental.shard_map.shard_map(..., check_rep=False)``.
  check_rep stays OFF on old JAX: its replication checker predates the
  pbroadcast/pvary autodiff rules and rejects valid grad-inside-shard_map
  programs (the vma checker that replaced it is a new-JAX concept).  The
  numerics do not depend on the checker; the parity tests
  (tests/test_train.py golden comparisons) hold under either.
- ``lax.pcast(x, axis, to=...)`` → identity.  pcast only adjusts the vma
  *type*; without the vma system there is nothing to adjust.
- ``lax.axis_size(name)`` → ``lax.psum(1, name)``, which JAX evaluates
  statically to a python int inside shard_map.
- ``jax.typeof(x)`` → the concrete aval wrapped with an empty ``.vma``.

On a JAX that already provides a name, that name is left untouched —
install() is a strict no-op there, so new-JAX behavior (including real vma
checking) is preserved.  Helpers that cannot be expressed as module
attributes (``ShapeDtypeStruct(..., vma=)``, Pallas ``CompilerParams``)
are exposed as functions for the kernel files to call directly.

Caveat, on purpose: on an old jaxlib install() mutates the global
``jax``/``jax.lax`` namespaces (it runs from the package __init__, so the
whole repo and its tests see one consistent surface).  A co-resident
library that feature-detects ``hasattr(jax, "shard_map")`` in the same
process will see the polyfill — whose check_rep stays False — rather than
a missing attribute.  If that ever bites, the alternative is routing
every call site through compat helpers like the two above; until then the
single-switch patch is what keeps the diff against upstream JAX usage
zero.
"""

from __future__ import annotations

import functools
from types import SimpleNamespace
from typing import Any, Optional

import jax
from jax import lax

# True when this JAX has the varying-manual-axes type system (and therefore
# the real shard_map/pcast/typeof); False when the polyfills are active.
HAS_VMA = hasattr(lax, "pcast")


def _shard_map_compat(f=None, *, mesh, in_specs, out_specs,
                      check_vma: Optional[bool] = None, **kw):
    from jax.experimental.shard_map import shard_map as _sm
    if f is None:                     # decorator style: jax.shard_map(mesh=...)
        return functools.partial(_shard_map_compat, mesh=mesh,
                                 in_specs=in_specs, out_specs=out_specs,
                                 check_vma=check_vma, **kw)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False, **kw)


def _pcast_compat(x, axis_name, *, to=None):
    del axis_name, to
    return x


def _axis_size_compat(axis_name) -> int:
    return lax.psum(1, axis_name)


def _typeof_compat(x):
    aval = jax.core.get_aval(x)
    return SimpleNamespace(shape=getattr(aval, "shape", ()),
                           dtype=getattr(aval, "dtype", None),
                           vma=frozenset())


_installed = False


def install() -> None:
    """Idempotently polyfill missing new-JAX names onto jax/jax.lax."""
    global _installed
    if _installed:
        return
    _installed = True
    # hasattr probes go through jax's deprecation __getattr__, which raises
    # AttributeError for unknown names — exactly the signal we want
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_compat
    if not hasattr(lax, "pcast"):
        lax.pcast = _pcast_compat
    if not hasattr(lax, "axis_size"):
        lax.axis_size = _axis_size_compat
    if not hasattr(jax, "typeof"):
        jax.typeof = _typeof_compat


def shape_dtype_struct(shape, dtype, vma=None) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct carrying vma only where the constructor takes it."""
    if HAS_VMA and vma is not None:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def mesh_axis_sizes() -> dict:
    """{axis_name: size} of the ambient manual mesh at trace time —
    ``jax.sharding.get_abstract_mesh()`` where it exists, the tracing axis
    env on older JAX (shard_map pushes its mesh axes there)."""
    try:
        from jax.sharding import get_abstract_mesh
        return dict(get_abstract_mesh().shape)
    except ImportError:
        from jax._src.core import get_axis_env
        return dict(get_axis_env().axis_sizes)


# params safe to drop when the installed CompilerParams predates them:
# pure scheduling hints whose absence cannot change results (the kernels
# that pass has_side_effects always have their outputs consumed, so
# dropping it cannot DCE them).  Correctness-bearing params — collective_id
# (cross-chip DMA/barrier matching), dimension_semantics — are NOT here:
# silently dropping those would compile a kernel that hangs or reduces
# wrongly on a real mesh with nothing pointing at compat.
_DROPPABLE_COMPILER_PARAMS = frozenset({"has_side_effects"})


def tpu_compiler_params(**kwargs) -> Any:
    """pltpu.CompilerParams across its rename (TPUCompilerParams before).

    Hint-only fields the older dataclass lacks are dropped; a missing
    correctness-bearing field raises instead of silently miscompiling."""
    import dataclasses
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    known = {f.name for f in dataclasses.fields(cls)}
    missing = sorted(set(kwargs) - known - _DROPPABLE_COMPILER_PARAMS)
    if missing:
        raise NotImplementedError(
            f"this jaxlib's {cls.__name__} has no {missing} — these "
            "affect kernel correctness (collective matching / grid "
            "semantics), so the fused kernels cannot run here; use "
            "the non-fused paths or a newer jaxlib")
    return cls(**{k: v for k, v in kwargs.items() if k in known})


# ---------------------------------------------------------------------------
# jax.profiler.ProfileData across jaxlibs (utils.trace_analysis's loader)
# ---------------------------------------------------------------------------

class _XEvent:
    __slots__ = ("name", "start_ns", "duration_ns")

    def __init__(self, name, start_ns, duration_ns):
        self.name = name
        self.start_ns = start_ns
        self.duration_ns = duration_ns


class _XLine:
    __slots__ = ("name", "events")

    def __init__(self, name, events):
        self.name = name
        self.events = events


class _XPlane:
    __slots__ = ("name", "lines")

    def __init__(self, name, lines):
        self.name = name
        self.lines = lines


class _XSpaceData:
    """ProfileData-shaped view over a raw xplane.pb parsed with the tsl
    XSpace proto (ships inside tensorflow; present on the fleet containers
    whose jaxlib predates jax.profiler.ProfileData).  Only the surface
    utils.trace_analysis walks: planes -> lines -> events with
    name/start_ns/duration_ns."""

    def __init__(self, planes):
        self.planes = planes

    @classmethod
    def from_file(cls, path):
        import os
        os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        planes = []
        for p in xs.planes:
            lines = []
            for l in p.lines:
                evs = []
                for e in l.events:
                    md = p.event_metadata[e.metadata_id]
                    # same convention as ProfileData: event start is the
                    # line timestamp plus the ps offset
                    evs.append(_XEvent(
                        md.name or md.display_name,
                        l.timestamp_ns + e.offset_ps // 1000,
                        e.duration_ps // 1000))
                lines.append(_XLine(l.name, evs))
            planes.append(_XPlane(p.name, lines))
        return cls(planes)


def load_profile_data(path: str):
    """ProfileData.from_file across jaxlibs: the native loader when this
    jax ships one, the tsl-proto shim otherwise.  Raises ImportError with
    both reasons when neither exists (no silent empty report)."""
    try:
        from jax.profiler import ProfileData
    except ImportError as e:
        jax_reason = str(e)
        ProfileData = None
    if ProfileData is not None:
        return ProfileData.from_file(path)
    try:
        return _XSpaceData.from_file(path)
    except ImportError as e:
        raise ImportError(
            "trace analysis needs jax.profiler.ProfileData (jax >= 0.5; "
            f"unavailable here: {jax_reason}) or the tensorflow tsl "
            f"xplane proto (unavailable here: {e})") from e
