"""Input pipeline: sharded host->device loading with async prefetch.

The reference's data plane is one MPI_Scatter of activations and an
MPI_Bcast of weights at startup (sw/mlp_mpi_example_f32.cpp:452-470) — the
training data never changes across iterations.  A real framework needs a
streaming analogue: this loader places each host batch onto the mesh with
the training sharding (the per-step MPI_Scatter) and keeps ``prefetch``
batches in flight, riding JAX's async dispatch so host->HBM copies overlap
the previous step's compute — the same overlap discipline the reference
applies to its gradient DMA (readme.pdf §2.1 4-CL read bursts while the
ring runs).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding


class ShardedLoader:
    """Wrap an iterable of host batches (pytrees of numpy/jax arrays) into
    an iterator of device batches sharded per ``spec``, with bounded
    prefetch.  spec: one PartitionSpec applied to every leaf (the trainers'
    ``shard_batch`` sharding, e.g. P(("dp","ep"), "sp"))."""

    def __init__(self, source: Iterable, mesh: Mesh, spec,
                 prefetch: int = 2):
        assert prefetch >= 1
        self._source = source
        self._sharding = NamedSharding(mesh, spec)
        self._prefetch = prefetch

    def _put(self, batch):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._sharding), batch)

    def __iter__(self) -> Iterator[Any]:
        window: deque = deque()
        it = iter(self._source)
        try:
            while len(window) < self._prefetch:
                window.append(self._put(next(it)))
        except StopIteration:
            pass
        while window:
            out = window.popleft()
            try:
                window.append(self._put(next(it)))
            except StopIteration:
                pass
            yield out


def synthetic_batches(make_batch: Callable[[np.random.Generator], Any],
                      *, seed: int = 0,
                      num_batches: Optional[int] = None) -> Iterator[Any]:
    """Deterministic synthetic stream (the reference fills its activations
    with host randoms once, sw/mlp_mpi_example_f32.cpp:414-424; we
    regenerate per step so data actually streams)."""
    rng = np.random.default_rng(seed)
    n = 0
    while num_batches is None or n < num_batches:
        yield make_batch(rng)
        n += 1


def epochs_of(arrays: Any, batch_size: int, *, seed: int = 0,
              epochs: Optional[int] = None,
              drop_remainder: bool = True,
              native: bool = False) -> Iterator[Any]:
    """Shuffled minibatch epochs over in-memory arrays (pytree with a
    shared leading example axis).

    ``drop_remainder=False`` yields a ragged final batch per epoch — fine
    for host-side eval loops, but INCOMPATIBLE with the sharded trainers:
    their batch size must divide the dp(*ep)/sp mesh axes and a new shape
    forces an XLA recompile.  Keep the default for training.

    ``native=True`` stages batches through the C++ gather engine
    (runtime/staging.py): the row gather runs on an OpenMP team in a
    background thread and the NEXT batch stages while the caller consumes
    the current one.  Requires drop_remainder (fixed slot sizes); falls
    back to numpy when the native library is unavailable.  Yielded leaves
    are OWNED arrays (copied out of the pool on yield — pool buffers are
    freed when the generator closes, so views would dangle); the parallel
    gather + copy still beats the single-threaded numpy fancy-index, and
    the gather overlaps the consumer."""
    leaves, treedef = jax.tree_util.tree_flatten(arrays)
    n = leaves[0].shape[0]
    assert all(l.shape[0] == n for l in leaves), "ragged leading axis"
    rng = np.random.default_rng(seed)

    if native and drop_remainder:
        from .runtime import staging
        if staging.available():
            yield from _epochs_native(leaves, treedef, n, batch_size, rng,
                                      epochs)
            return

    e = 0
    while epochs is None or e < epochs:
        order = rng.permutation(n)
        stop = (n // batch_size) * batch_size if drop_remainder else n
        for lo in range(0, stop, batch_size):
            idx = order[lo:lo + batch_size]
            yield jax.tree_util.tree_map(lambda x: np.asarray(x)[idx],
                                         arrays)
        e += 1


def _epochs_native(leaves, treedef, n, batch_size, rng, epochs):
    """Double-buffered native staging: submit batch k+1's gathers before
    yielding batch k, so the OpenMP copy overlaps the consumer.  ONE pool
    (one worker thread — each gather is internally OpenMP-parallel) with
    2 slot generations x n_leaves uniform max-size slots."""
    from .runtime.staging import Stager
    np_leaves = [np.ascontiguousarray(np.asarray(l)) for l in leaves]
    leaf_bytes = [batch_size * l.dtype.itemsize
                  * int(np.prod(l.shape[1:], dtype=np.int64))
                  for l in np_leaves]
    # two right-sized slots per leaf (a uniform max-size pool would waste
    # image-sized buffers on label-sized leaves)
    pool = Stager.sized(sorted(leaf_bytes * 2))
    try:
        def submit(idx):
            return [pool.submit(l, idx) for l in np_leaves]

        def materialize(slots):
            # copy out of the pool buffer: the generator's close() frees
            # the native buffers, so a yielded VIEW would dangle for any
            # batch kept past the loop (e.g. list(epochs_of(...))); the
            # expensive shuffled gather already happened natively
            out = [np.array(pool.wait(s)) for s in slots]
            for s in slots:
                pool.release(s)
            return jax.tree_util.tree_unflatten(treedef, out)

        def index_stream():
            e = 0
            while epochs is None or e < epochs:
                order = rng.permutation(n)
                for lo in range(0, (n // batch_size) * batch_size,
                                batch_size):
                    yield order[lo:lo + batch_size]
                e += 1

        pending = None
        for idx in index_stream():
            slots = submit(idx)
            if pending is not None:
                yield materialize(pending)
            pending = slots
        if pending is not None:
            yield materialize(pending)
    finally:
        pool.close()
