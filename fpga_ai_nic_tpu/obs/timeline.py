"""Single-timebase Perfetto/Chrome-trace export of the whole stack.

The reference answers "was the wire hidden by compute?" with stall-cause
CSR counters read over MMIO (stall_host_in/out, stall_eth_in/out,
hw/all_reduce.sv:94-97).  The TPU answer is a *timeline*: host spans
(Profiler buckets, elastic attempts), the collective queue's issue/wait
ticket intervals, and the device plane's sync/async op intervals
(utils.trace_analysis), all merged onto one time axis and emitted as
Chrome-trace JSON — load the file in https://ui.perfetto.dev (or
chrome://tracing) and the stall attribution is visible instead of argued:
a ticket span with no sync compute under it IS exposed wire time.

Timebase: host events carry absolute unix-epoch ns (obs.events anchors
perf_counter to time.time at stream construction).  Device-plane
intervals come from the profiler's xplane, whose epoch is backend-
defined — so they are aligned by ANCHOR: the host span wrapping the
``jax.profiler.trace`` capture (name ``jax_profile`` by convention,
overridable) pins the device plane's earliest event to its start.  The
chosen offset is recorded in the output's ``otherData`` so the alignment
is auditable, never silent.

Output format: the Chrome trace-event JSON object form —
``{"traceEvents": [...], "displayTimeUnit": "ms", "otherData": {...}}``
with complete ("X") events for spans/intervals, counter ("C") events for
metric series, instant ("i") events, and metadata ("M") rows naming the
process/thread lanes.  Perfetto and chrome://tracing both load it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import events as events_lib

# process ids (chrome trace "pid" lanes)
_PID_HOST = 1
_PID_QUEUE = 2
_PID_DEVICE = 3
_PID_ATTRIB = 4     # drift attribution: modeled-vs-measured per stage

DEFAULT_ANCHOR_SPAN = "jax_profile"


def _meta(pid: int, name: str, tid: Optional[int] = None,
          thread_name: Optional[str] = None) -> List[Dict]:
    out = [{"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": name}}]
    if tid is not None:
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": thread_name}})
    return out


def _host_trace_events(host_events: Sequence[Dict[str, Any]],
                       t0_ns: int) -> List[Dict]:
    """Host stream -> chrome events.  Spans whose attrs carry
    ``lane='queue'`` (the CollectiveQueue's ticket intervals) get their
    own process so ticket overlap reads at a glance; spans/instants with
    ``lane='attribution'`` (the drift observatory's modeled-vs-measured
    stage residuals, tune.adapt) get the attribution process with one
    thread per stage, so the excess over the roofline model — and every
    ``adapt.switch`` it triggers — reads directly off the timeline;
    other spans lane by emitting thread."""
    out: List[Dict] = []
    tids: Dict[int, int] = {}
    attrib_tids: Dict[str, int] = {}
    queue_meta_done = False
    attrib_meta_done = False
    for ev in host_events:
        ts_us = (ev["t_unix_ns"] - t0_ns) / 1e3
        attrs = ev.get("attrs") or {}
        is_queue = attrs.get("lane") == "queue"
        if is_queue:
            pid, tid = _PID_QUEUE, int(attrs.get("uid", 0)) % 64
            if not queue_meta_done:
                out.extend(_meta(_PID_QUEUE, "collective queue (tickets)"))
                queue_meta_done = True
        elif attrs.get("lane") == "attribution":
            pid = _PID_ATTRIB
            if not attrib_meta_done:
                out.extend(_meta(_PID_ATTRIB,
                                 "drift attribution (modeled vs measured)"))
                attrib_meta_done = True
            stage = str(attrs.get("stage", "step"))
            if stage not in attrib_tids:        # first sighting
                attrib_tids[stage] = len(attrib_tids) + 1
                out.append({"ph": "M", "pid": _PID_ATTRIB,
                            "tid": attrib_tids[stage],
                            "name": "thread_name",
                            "args": {"name": stage}})
            tid = attrib_tids[stage]
        else:
            pid = _PID_HOST
            raw_tid = ev.get("tid", 0)
            if raw_tid not in tids:             # first sighting
                tids[raw_tid] = len(tids) + 1
                out.extend(_meta(_PID_HOST, "host", tid=tids[raw_tid],
                                 thread_name=f"thread-{tids[raw_tid]}"))
            tid = tids[raw_tid]
        kind = ev.get("kind")
        if kind == events_lib.SPAN:
            out.append({"ph": "X", "pid": pid, "tid": tid,
                        "name": ev["name"], "ts": ts_us,
                        "dur": ev.get("dur_ns", 0) / 1e3,
                        "args": attrs or {}})
        elif kind == events_lib.COUNTER:
            out.append({"ph": "C", "pid": _PID_HOST, "tid": 0,
                        "name": ev["name"], "ts": ts_us,
                        "args": {"value": ev.get("value", 0.0)}})
        elif kind == events_lib.INSTANT:
            out.append({"ph": "i", "pid": pid, "tid": tid, "s": "g",
                        "name": ev["name"], "ts": ts_us,
                        "args": attrs or {}})
    return out


def _device_offset_ns(device_intervals: Sequence[Dict[str, Any]],
                      host_events: Sequence[Dict[str, Any]],
                      anchor_span: str) -> Tuple[int, str]:
    """(shift, alignment) applied to device timestamps.  With the anchor
    span present (the host span wrapping the profiler capture) the
    earliest device event pins to its start: alignment ``anchored``.
    With device events but NO anchor span, the fallback to the earliest
    host event is a GUESS — the device epoch is backend-defined, so the
    merge may be misaligned by an arbitrary constant; that state is
    reported as ``offset_unknown`` (and chrome_trace plants an explicit
    marker in the device lane) instead of silently rendering a timeline
    whose cross-plane overlap claims mean nothing."""
    if not device_intervals:
        return 0, "n/a"
    dev_min = min(iv["start_ns"] for iv in device_intervals)
    for ev in host_events:
        if ev.get("kind") == events_lib.SPAN and ev["name"] == anchor_span:
            return int(ev["t_unix_ns"] - dev_min), "anchored"
    if host_events:
        anchor = min(ev["t_unix_ns"] for ev in host_events)
        return int(anchor - dev_min), "offset_unknown"
    return 0, "offset_unknown"


def _device_trace_events(device_intervals: Sequence[Dict[str, Any]],
                         offset_ns: int, t0_ns: int) -> List[Dict]:
    out: List[Dict] = []
    lanes: Dict[str, int] = {}
    for iv in device_intervals:
        lane = f"{iv.get('plane', 'device')} / {iv.get('line', 'ops')}"
        if lane not in lanes:                   # first sighting
            lanes[lane] = len(lanes) + 1
            out.extend(_meta(_PID_DEVICE, "device planes",
                             tid=lanes[lane], thread_name=lane))
        tid = lanes[lane]
        ts_us = (iv["start_ns"] + offset_ns - t0_ns) / 1e3
        out.append({"ph": "X", "pid": _PID_DEVICE, "tid": tid,
                    "name": iv["name"], "ts": ts_us,
                    "dur": (iv["end_ns"] - iv["start_ns"]) / 1e3,
                    "args": {"cls": iv.get("cls", "sync")}})
    return out


def chrome_trace(host_events: Sequence[Dict[str, Any]],
                 device_intervals: Optional[Sequence[Dict[str, Any]]] = None,
                 anchor_span: str = DEFAULT_ANCHOR_SPAN,
                 header: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Merge host events (obs.events snapshot/JSONL shape) and optional
    device intervals (utils.trace_analysis.device_intervals shape) into
    one Chrome-trace JSON object.  All timestamps are rebased to the
    earliest host event so the trace opens at t=0."""
    device_intervals = list(device_intervals or [])
    host_events = list(host_events)
    offset, alignment = _device_offset_ns(device_intervals, host_events,
                                          anchor_span)
    starts = [ev["t_unix_ns"] for ev in host_events]
    starts += [iv["start_ns"] + offset for iv in device_intervals]
    t0_ns = min(starts) if starts else 0
    trace_events: List[Dict] = []
    trace_events.extend(_meta(_PID_HOST, "host"))
    trace_events.extend(_host_trace_events(host_events, t0_ns))
    trace_events.extend(_device_trace_events(device_intervals, offset,
                                             t0_ns))
    if alignment == "offset_unknown":
        # the anchor span is missing: the device plane is placed by a
        # guess, and anyone reading cross-plane overlap must see that IN
        # the trace, not only in metadata nobody opens
        trace_events.append({
            "ph": "i", "pid": _PID_DEVICE, "tid": 0, "s": "p",
            "name": "offset_unknown", "ts": 0.0,
            "args": {"why": f"no '{anchor_span}' anchor span in the host "
                            "stream — device timestamps aligned to the "
                            "earliest host event, which may be off by an "
                            "arbitrary constant"}})
    other: Dict[str, Any] = {
        "schema_version": events_lib.SCHEMA_VERSION,
        "t0_unix_ns": t0_ns,
        "n_host_events": len(host_events),
        "n_device_intervals": len(device_intervals),
        "device_offset_ns": offset,
        "device_alignment": alignment,
    }
    if header:
        other["stream_header"] = header
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": other}


def build(events_jsonl: Optional[str] = None,
          stream: Optional[events_lib.EventStream] = None,
          trace_dir: Optional[str] = None,
          anchor_span: str = DEFAULT_ANCHOR_SPAN) -> Dict[str, Any]:
    """One-call export: host events from a JSONL dump or a live stream,
    device intervals from a jax profiler trace directory when given."""
    if (events_jsonl is None) == (stream is None):
        raise ValueError("pass exactly one of events_jsonl / stream")
    if stream is not None:
        header, host_events = stream.header(), stream.snapshot()
    else:
        header, host_events = events_lib.read_jsonl(events_jsonl)
    device_intervals = None
    if trace_dir is not None:
        from ..utils import trace_analysis
        device_intervals = trace_analysis.device_intervals(trace_dir)
    return chrome_trace(host_events, device_intervals,
                        anchor_span=anchor_span, header=header)


def write(path: str, trace: Dict[str, Any]) -> str:
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fpga_ai_nic_tpu.obs.timeline",
        description="Merge an obs event stream (+ optional jax profiler "
                    "trace) into Perfetto-loadable Chrome-trace JSON.")
    ap.add_argument("events_jsonl", help="EventStream.dump_jsonl file")
    ap.add_argument("--trace-dir", default=None,
                    help="jax.profiler.trace output dir (device intervals)")
    ap.add_argument("--anchor-span", default=DEFAULT_ANCHOR_SPAN,
                    help="host span name pinning the device timebase "
                         f"(default: {DEFAULT_ANCHOR_SPAN})")
    ap.add_argument("-o", "--out", default="timeline.json")
    args = ap.parse_args(argv)
    trace = build(events_jsonl=args.events_jsonl, trace_dir=args.trace_dir,
                  anchor_span=args.anchor_span)
    write(args.out, trace)
    od = trace["otherData"]
    print(f"wrote {args.out}: {od['n_host_events']} host events, "
          f"{od['n_device_intervals']} device intervals "
          f"(offset {od['device_offset_ns']} ns) — load in "
          "https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
