"""Unified telemetry plane: structured event stream, in-graph training
metrics, and single-timebase Perfetto export.

Three layers, one timebase:

  obs.events     schema-versioned span/counter stream (bounded ring,
                 O(1) hot path, JSONL sink, honest ``events_dropped``).
                 ``utils.observability.Profiler`` is a thin facade over
                 it — its buckets/collective/recovery aggregates remain
                 the O(1)-memory summary; the stream carries the
                 individual events underneath.
  obs.metrics    in-graph metrics (grad norm, codec declared-vs-observed
                 error, EF residual mass, integrity drift) tapped to a
                 host MetricsSink via pure_callback; compiled out
                 entirely when ``TrainConfig.obs_metrics`` is False.
  obs.timeline   host spans + queue issue/wait tickets + device-plane
                 trace intervals merged into Chrome-trace/Perfetto JSON.

Gate: ``tools/obs_gate.py`` (``make obs-gate``) diffs a run's telemetry
summary against the banked benchmark artifacts.  Docs:
docs/OBSERVABILITY.md.
"""

from .events import SCHEMA_VERSION, EventStream, read_jsonl  # noqa: F401
from .metrics import (MetricsSink, active_sink, host_observe,  # noqa: F401
                      tap, use_sink)
from .slo import SloAggregator, SloWindow  # noqa: F401
from . import timeline  # noqa: F401

__all__ = ["SCHEMA_VERSION", "EventStream", "read_jsonl", "MetricsSink",
           "active_sink", "host_observe", "tap", "use_sink", "timeline",
           "SloAggregator", "SloWindow"]
