"""Structured telemetry event stream — spans + counters on one timebase.

The reference NIC is observable *by construction*: per-collective active
cycles (`lpbk_latency`, hw/all_reduce.sv:92), stall attribution by cause
(`stall_host_in/out`, `stall_eth_in/out`, hw/all_reduce.sv:94-97), flit
counters (hw/bfp_adapter.sv:705-729), and a DETAILED_PROFILE wall-clock
breakdown in the driver (sw/mlp_mpi_example_f32.cpp:236-244).  Our port's
`utils.observability.Profiler` mirrored only the *aggregates*; this module
is the stream underneath them — every span, counter and instant event,
individually timestamped, so per-phase accounting (what EQuARX-style
compressed-collective evaluation needs) and the Perfetto timeline
(`obs.timeline`) both read from one source of truth.

Contract:

  - **Schema-versioned**: every JSONL dump leads with a header line
    carrying ``SCHEMA_VERSION`` plus the stream's timebase anchors;
    consumers reject versions they don't know.
  - **O(1) hot path**: ``emit`` appends one fixed-shape tuple under a
    plain lock into a bounded ring.  No string formatting, no dict
    merging, no IO on the hot path; rendering happens at dump time.
  - **Bounded, with honest overflow**: the ring keeps the newest
    ``capacity`` events; every evicted event increments
    ``events_dropped``, which rides the summary and the JSONL header so
    a truncated stream can never read as "covered everything"
    (the same rule as RecoveryStats.events_dropped).
  - **Single timebase**: event timestamps are ``time.perf_counter_ns()``
    (monotonic, cheap); the stream records a paired
    (``time.time_ns``, ``perf_counter_ns``) anchor at construction so
    any event converts to absolute unix-epoch ns (``to_unix_ns``) —
    the common axis host spans, queue tickets and device-plane trace
    intervals are merged on.
  - Thread-safe: the elastic watchdog worker, XLA callback threads and
    the trainer thread all emit into one stream.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, Iterator, List, Optional, Tuple

SCHEMA_VERSION = 1

# event kinds (the "ph" analogue of the chrome trace format)
SPAN = "span"          # has dur_ns
INSTANT = "instant"    # point event
COUNTER = "counter"    # has value

_EVENT_KINDS = (SPAN, INSTANT, COUNTER)


class EventStream:
    """Bounded ring of structured telemetry events (see module docstring).

    One instance per Profiler (trainers and queues share their profiler's
    stream); capacity defaults generous enough for ~10k steps of span +
    ticket traffic while bounding memory for million-step runs.
    """

    def __init__(self, capacity: int = 1 << 16) -> None:
        assert capacity > 0
        self.capacity = int(capacity)
        # ring slots: (t_ns, dur_ns, kind, name, value, attrs, tid)
        self._buf: Deque[Tuple] = deque()
        self._lock = threading.Lock()
        self.events_dropped = 0
        self._emitted = 0
        # single-timebase anchor pair (see module docstring)
        self.t0_unix_ns = time.time_ns()
        self.t0_perf_ns = time.perf_counter_ns()

    # -- timebase -----------------------------------------------------------

    @staticmethod
    def now_ns() -> int:
        return time.perf_counter_ns()

    def to_unix_ns(self, t_perf_ns: float) -> int:
        """perf_counter timestamp -> absolute unix-epoch ns (the merge
        axis shared with device-plane trace intervals)."""
        return int(self.t0_unix_ns + (t_perf_ns - self.t0_perf_ns))

    # -- hot path -----------------------------------------------------------

    def emit(self, kind: str, name: str, t_ns: Optional[int] = None,
             dur_ns: Optional[int] = None, value: Optional[float] = None,
             attrs: Optional[Dict[str, Any]] = None) -> None:
        """Append one event.  O(1): a tuple append (plus one eviction when
        the ring is full) under a plain lock."""
        if t_ns is None:
            t_ns = time.perf_counter_ns()
        tid = threading.get_ident()
        with self._lock:
            self._emitted += 1
            if len(self._buf) >= self.capacity:
                self._buf.popleft()
                self.events_dropped += 1
            self._buf.append((t_ns, dur_ns, kind, name, value, attrs, tid))

    def instant(self, name: str, **attrs: Any) -> None:
        self.emit(INSTANT, name, attrs=attrs or None)

    def counter(self, name: str, value: float,
                **attrs: Any) -> None:
        self.emit(COUNTER, name, value=float(value), attrs=attrs or None)

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[None]:
        """Timed span; records on exit (exceptions still record — a span
        that died is exactly the span the timeline must show)."""
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            t1 = time.perf_counter_ns()
            self.emit(SPAN, name, t_ns=t0, dur_ns=t1 - t0,
                      attrs=attrs or None)

    # -- rendering (cold path) ----------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """Events as dicts, oldest first, timestamps in absolute unix ns
        (the JSONL / timeline shape)."""
        with self._lock:
            raw = list(self._buf)
        out = []
        for t_ns, dur_ns, kind, name, value, attrs, tid in raw:
            ev: Dict[str, Any] = {"t_unix_ns": self.to_unix_ns(t_ns),
                                  "kind": kind, "name": name, "tid": tid}
            if dur_ns is not None:
                ev["dur_ns"] = int(dur_ns)
            if value is not None:
                ev["value"] = value
            if attrs:
                ev["attrs"] = attrs
            out.append(ev)
        return out

    def summary(self) -> Dict[str, Any]:
        """Aggregate view: per-span-name wall-clock totals (the
        DETAILED_PROFILE breakdown), latest counter values, and the
        recorded/dropped accounting.  Cheap enough to embed in every
        bench artifact."""
        with self._lock:
            raw = list(self._buf)
            emitted, dropped = self._emitted, self.events_dropped
        spans: Dict[str, Dict[str, float]] = {}
        counters: Dict[str, float] = {}
        kinds: Dict[str, int] = {}
        for t_ns, dur_ns, kind, name, value, attrs, tid in raw:
            kinds[kind] = kinds.get(kind, 0) + 1
            if kind == SPAN and dur_ns is not None:
                agg = spans.setdefault(name, {"count": 0, "total_s": 0.0,
                                              "max_s": 0.0})
                agg["count"] += 1
                agg["total_s"] += dur_ns / 1e9
                agg["max_s"] = max(agg["max_s"], dur_ns / 1e9)
            elif kind == COUNTER and value is not None:
                counters[name] = value       # latest wins (time-ordered)
        for agg in spans.values():
            agg["total_s"] = round(agg["total_s"], 6)
            agg["max_s"] = round(agg["max_s"], 6)
        return {"schema_version": SCHEMA_VERSION,
                "emitted": emitted, "recorded": len(raw),
                "events_dropped": dropped,
                "by_kind": kinds, "spans": spans, "counters": counters}

    # -- JSONL sink ---------------------------------------------------------

    def header(self) -> Dict[str, Any]:
        with self._lock:
            emitted, dropped = self._emitted, self.events_dropped
        return {"schema_version": SCHEMA_VERSION,
                "t0_unix_ns": self.t0_unix_ns,
                "emitted": emitted, "events_dropped": dropped,
                "capacity": self.capacity}

    def dump_jsonl(self, path: str) -> str:
        """Write header line + one JSON line per event (absolute unix-ns
        timestamps — streams from different processes merge directly)."""
        events = self.snapshot()       # render before opening (no IO races)
        with open(path, "w") as f:
            f.write(json.dumps(self.header()) + "\n")
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        return path


def read_jsonl(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """(header, events) from a dump_jsonl file.  Rejects unknown schema
    versions — the versioning contract that lets the timeline/gate tools
    evolve without silently misreading old dumps."""
    with open(path) as f:
        lines = [json.loads(l) for l in f if l.strip()]
    if not lines:
        raise ValueError(f"{path}: empty event stream")
    header, events = lines[0], lines[1:]
    ver = header.get("schema_version")
    if ver != SCHEMA_VERSION:
        raise ValueError(
            f"{path}: event schema v{ver!r} != supported v{SCHEMA_VERSION}")
    return header, events
