"""In-graph training metrics, tapped to the host via ``jax.pure_callback``.

The hard part of training telemetry on TPU is that the numbers live
*inside* a jitted, donated-state step: gradient norms, codec error, EF
residual mass.  This module reuses the mechanism `runtime.chaos` already
proved for fault injection — route a value through a ``pure_callback``
whose host half reads an ambient object — but for metrics instead of
faults: the step's loss is passed through the callback together with the
metric scalars, so the callback is consumed (never DCE'd) and costs one
host hop per step.

Zero-cost-when-off contract: the tap is gated by a TRACE-TIME Python bool
(``TrainConfig.obs_metrics``).  Disabled, ``tap`` returns its input object
untouched and the metric thunks are never traced — the step's jaxpr/HLO is
bit-identical to a build without any obs plumbing (asserted by
tests/test_obs.py's abstract-eval test).

Metric definitions (docs/OBSERVABILITY.md):

  grad_norm           global L2 of the mean-reduced gradient (post-
                      collective, pre-clip) — psum'd across the axis.
  codec_obs_rel_err   observed per-unit relative roundtrip error of the
                      configured codec on this step's gradient: max over
                      compression units of |x - roundtrip(x)| / max|unit|.
                      Compare against the codec's DECLARED error_bound
                      (`declared_error_bound` in the sink's statics): the
                      EQuARX-style honesty check that the wire format does
                      what it promises, every step, on real gradients.
  ef_resid_norm       L2 of the error-feedback residual AFTER this step's
                      carry update — the unsent gradient mass in flight.
  integrity_err       worst relative chunk-sum discrepancy from the
                      collective integrity checksums (runtime.chaos),
                      when integrity_check is on.
  loss_ewma /         host-side EWMAs maintained by the sink (loss from
  step_time_ewma_s    the tapped value, step time from tap arrival
                      spacing) — the training-health dashboard pair.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .events import EventStream

__all__ = ["Ewma", "MetricsSink", "use_sink", "active_sink", "tap",
           "codec_static_metrics", "codec_observed_error"]


class Ewma:
    """Exponentially-weighted moving average SEEDED WITH THE FIRST
    OBSERVATION: ``value`` is exactly the first sample until the second
    arrives, never a decay up from an arbitrary zero.  A zero-seeded
    EWMA under-reports every early sample by (1-a)^k — harmless for a
    dashboard, poisonous for drift detection, where the warm-up bias
    reads as a fake downward regime shift and the modeled-vs-measured
    residuals (tune.adapt) inherit it.  Shared by MetricsSink and the
    drift plane so there is ONE seeding rule (pinned by test_obs)."""

    def __init__(self, alpha: float) -> None:
        assert 0.0 < alpha <= 1.0, alpha
        self.alpha = float(alpha)
        self.value: Optional[float] = None
        # leaf lock: updates arrive from XLA callback threads (the sink)
        # and the trainer thread (the drift plane) — the H1 cross-thread
        # ordering rule, same discipline as the stats record_* methods
        self._lock = threading.Lock()

    def update(self, v: float) -> float:
        v = float(v)
        with self._lock:
            self.value = v if self.value is None \
                else (1.0 - self.alpha) * self.value + self.alpha * v
            return self.value


# ---------------------------------------------------------------------------
# host side: the sink
# ---------------------------------------------------------------------------

class MetricsSink:
    """Ambient receiver of tapped step metrics (one per run/trainer).

    Thread-safe (XLA callback threads deliver); keeps latest values, EWMA
    aggregates for loss and inter-tap step time, and mirrors every update
    into an EventStream as counter events when one is attached — so the
    Perfetto timeline carries the metric series next to the spans."""

    def __init__(self, ewma_alpha: float = 0.1,
                 events: Optional[EventStream] = None,
                 static: Optional[Dict[str, Any]] = None) -> None:
        assert 0.0 < ewma_alpha <= 1.0
        self.ewma_alpha = ewma_alpha
        self.events = events
        self.static = dict(static or {})
        self.latest: Dict[str, float] = {}
        self._ewma: Dict[str, Ewma] = {}
        self.n_updates = 0
        self._last_t: Optional[float] = None
        self._lock = threading.Lock()

    def _ewma_update(self, name: str, value: float) -> None:
        # first-observation seeding (Ewma contract): no decay-from-zero
        # warm-up bias in the series drift residuals are built on.
        # get-then-create, not setdefault: this runs per step on the
        # XLA-callback path, and setdefault would allocate a throwaway
        # Ewma (and its lock) on every call
        e = self._ewma.get(name)
        if e is None:
            e = self._ewma[name] = Ewma(self.ewma_alpha)
        e.update(value)

    def ewma_value(self, name: str) -> Optional[float]:
        e = self._ewma.get(name)
        return None if e is None else e.value

    def update(self, values: Dict[str, float]) -> None:
        now = time.perf_counter()
        ev = self.events
        with self._lock:
            self.n_updates += 1
            for name, v in values.items():
                v = float(v)
                self.latest[name] = v
                if name == "loss":
                    self._ewma_update("loss", v)
            if self._last_t is not None:
                self._ewma_update("step_time_s", now - self._last_t)
            self._last_t = now
        if ev is not None:
            for name, v in values.items():
                ev.counter(f"metric.{name}", float(v))

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "n_updates": self.n_updates,
                "latest": dict(self.latest),
                "loss_ewma": self.ewma_value("loss"),
                "step_time_ewma_s": self.ewma_value("step_time_s"),
            }
            if self.static:
                out["static"] = dict(self.static)
        return out


_ACTIVE_SINK: Optional[MetricsSink] = None


def active_sink() -> Optional[MetricsSink]:
    return _ACTIVE_SINK


class use_sink:
    """Context manager binding the ambient sink the tap callbacks deliver
    to — same ambient-object pattern (and the same async-dispatch caveat)
    as ``runtime.chaos.activate``: any step that should be observed must
    complete before the context exits."""

    def __init__(self, sink: Optional[MetricsSink]) -> None:
        self.sink = sink

    def __enter__(self) -> Optional[MetricsSink]:
        global _ACTIVE_SINK
        self._prev = _ACTIVE_SINK
        _ACTIVE_SINK = self.sink
        return self.sink

    def __exit__(self, *exc: Any) -> None:
        global _ACTIVE_SINK
        _ACTIVE_SINK = self._prev


def host_observe(values: Dict[str, float]) -> None:
    """Host-side metric delivery for values that never lived in a jitted
    program (e.g. the queued trainer's per-bucket wire accounting) —
    no-op without an active sink, same as the tap."""
    sink = _ACTIVE_SINK
    if sink is not None:
        sink.update(values)


# ---------------------------------------------------------------------------
# in-graph side: the tap
# ---------------------------------------------------------------------------

def tap(out: Any, metrics: Any, enabled: bool = True) -> Any:
    """Route ``out`` (any array, typically the step's loss) through a
    pure_callback that delivers ``metrics`` (name -> scalar array, or a
    zero-arg thunk returning that dict) to the ambient sink.  Returns
    ``out`` unchanged numerically.

    ``enabled`` must be a Python (trace-time) bool: False returns ``out``
    THE SAME OBJECT — no callback, no metric computation (a thunk is
    never invoked), nothing in the jaxpr (the compiled-out-entirely
    contract; pass a thunk when the metric computation itself would
    otherwise be traced dead at the call site)."""
    if not enabled:
        return out
    if callable(metrics):
        metrics = metrics()
    if not metrics:
        return out
    import jax

    names: Tuple[str, ...] = tuple(sorted(metrics))
    vals = [metrics[k] for k in names]

    def host(o: Any, *vs: Any) -> np.ndarray:
        sink = _ACTIVE_SINK
        if sink is not None:
            sink.update({k: float(np.asarray(v)) for k, v in zip(names, vs)})
        return np.asarray(o)

    return jax.pure_callback(
        host, jax.ShapeDtypeStruct(np.shape(out), out.dtype), out, *vals)


# ---------------------------------------------------------------------------
# metric builders (called inside shard_map, only when enabled)
# ---------------------------------------------------------------------------

def codec_static_metrics(codec: Any,
                         n_elems: int) -> Dict[str, Any]:
    """Trace-time-constant codec facts for the sink's ``static`` dict:
    declared compression ratio, declared error bound, wire bytes per
    all-reduce pass of an [n_elems] gradient."""
    if codec is None:
        return {}
    return {"codec": codec.name,
            "compression_ratio_vs_f32":
                round(float(codec.compression_ratio_vs_f32), 4),
            "declared_error_bound": float(codec.error_bound),
            "wire_bytes_per_pass": int(codec.wire_bytes(n_elems))}


def codec_observed_error(codec: Any, x: Any,
                         quantized: Any = None) -> Any:
    """Observed per-unit relative roundtrip error of ``codec`` on the flat
    vector ``x`` — the in-graph half of the declared-vs-observed check.

    ``quantized`` (optional) is roundtrip(x) when the caller already has
    it (the EF path's wire vector); otherwise one extra roundtrip is spent
    — acceptable for an opt-in telemetry path, and the only way to measure
    the REAL error instead of re-asserting the declared bound."""
    import jax.numpy as jnp
    if quantized is None:
        quantized = codec.roundtrip(x)
    pe = codec.pad_elems
    units = x.reshape(-1, pe).astype(jnp.float32)
    err = jnp.abs(units - quantized.reshape(-1, pe).astype(jnp.float32))
    unit_max = jnp.max(jnp.abs(units), axis=1)
    rel = jnp.max(jnp.max(err, axis=1) / jnp.maximum(unit_max, 1e-20))
    return rel


def l2_norm(x: Any, axis_name: Optional[str] = None) -> Any:
    """Global L2 norm of a (possibly axis-sharded) flat vector — psum'd
    when ``axis_name`` is given (call inside shard_map)."""
    import jax.numpy as jnp
    from jax import lax
    sq = jnp.sum(x.astype(jnp.float32) ** 2)
    if axis_name is not None:
        sq = lax.psum(sq, axis_name)
    return jnp.sqrt(sq)


# ---------------------------------------------------------------------------
# serving-plane request telemetry (serve.engine)
# ---------------------------------------------------------------------------

def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an ALREADY-SORTED list — tiny and
    dependency-free so the gate tooling can share it.  An EMPTY series
    returns NaN: the caller gets an explicitly not-a-number answer it
    can flag (RequestSpans.summary's ``*_empty``) instead of an assert
    that turns "no requests completed yet" into a crash in whatever
    thread asked for a summary."""
    assert 0.0 <= q <= 100.0, q
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[idx])


class RequestSpans:
    """Per-request serving telemetry: bounded sample series for queue
    wait, TTFT (submit -> first new token), TPOT (mean inter-token time
    after the first) and total latency, plus one ``serve.request`` span
    per completion on the event stream (lane="serve", ticket uid) so the
    Perfetto timeline shows request lifetimes beside the queue-lane
    collective tickets.

    Bounded with honest overflow, same contract as the event ring: at
    most ``max_samples`` per series, every further completion counted in
    ``samples_dropped`` so a truncated summary can never read as
    complete.  Thread-safe (the engine loop records; summaries may be
    read from anywhere)."""

    SERIES: Tuple[str, ...] = ("queue_wait_s", "ttft_s", "tpot_s",
                               "latency_s")

    def __init__(self, events: Optional[EventStream] = None,
                 max_samples: int = 4096) -> None:
        assert max_samples > 0
        self.events = events
        self.max_samples = int(max_samples)
        self._series: Dict[str, List[float]] = {k: [] for k in self.SERIES}
        self.completed = 0
        self.samples_dropped = 0
        self._lock = threading.Lock()

    def record(self, uid: int, *, t_submit: float, t_admit: float,
               t_first: float, t_done: float, n_tokens: int) -> None:
        """One completed request (timestamps in perf_counter seconds)."""
        vals = {"queue_wait_s": t_admit - t_submit,
                "ttft_s": t_first - t_submit,
                "tpot_s": ((t_done - t_first) / (n_tokens - 1)
                           if n_tokens > 1 else 0.0),
                "latency_s": t_done - t_submit}
        with self._lock:
            self.completed += 1
            if len(self._series["latency_s"]) >= self.max_samples:
                self.samples_dropped += 1
            else:
                for k, v in vals.items():
                    self._series[k].append(float(v))
        if self.events is not None:
            self.events.emit(
                "span", "serve.request", t_ns=int(t_submit * 1e9),
                dur_ns=int((t_done - t_submit) * 1e9),
                attrs={"lane": "serve", "uid": uid, "tokens": n_tokens,
                       "ttft_s": round(vals["ttft_s"], 6),
                       "tpot_s": round(vals["tpot_s"], 6),
                       "queue_wait_s": round(vals["queue_wait_s"], 6)})

    def summary(self) -> Dict[str, Any]:
        """mean / p50 / p95 / p99 per series + completion/drop
        accounting.
        An empty series reports not-a-number stats WITH an explicit
        ``<series>_empty: True`` flag — "no samples" must read as no
        samples, never as a silently absent (or zero) latency row.  The
        not-a-number spelling here is ``None`` (JSON null), NOT float
        NaN: summaries land verbatim in banked JSON artifacts, and
        ``json.dump`` would serialize NaN as a bare token strict
        parsers reject."""
        with self._lock:
            series = {k: sorted(v) for k, v in self._series.items()}
            completed, dropped = self.completed, self.samples_dropped
        out: Dict[str, Any] = {"completed": completed,
                               "samples_dropped": dropped}
        for name, vals in series.items():
            base = name[:-2] if name.endswith("_s") else name
            if not vals:
                out[f"{base}_empty"] = True
                out[f"{base}_mean_s"] = None
                out[f"{base}_p50_s"] = None
                out[f"{base}_p95_s"] = None
                out[f"{base}_p99_s"] = None
                continue
            out[f"{base}_mean_s"] = round(sum(vals) / len(vals), 6)
            out[f"{base}_p50_s"] = round(percentile(vals, 50.0), 6)
            out[f"{base}_p95_s"] = round(percentile(vals, 95.0), 6)
            out[f"{base}_p99_s"] = round(percentile(vals, 99.0), 6)
        return out
