"""Serving SLO observatory: windowed live metrics over the request plane.

`obs.metrics.RequestSpans` summarizes a whole run after the fact; an
autoscaler needs the LIVE view — "what is p99 TTFT over the last N
completions, right now" — plus the per-tick pressure gauges (queue
depth, pages in use, free pages, per-replica batch occupancy) the
reference NIC exposes as CSR counters (stall_host_in/out,
hw/all_reduce.sv:94-97).  This module is that substrate:

  SloWindow      one bounded sliding window: O(1) insert (deque with
                 maxlen — overflow evicts the oldest sample, counted),
                 nearest-rank p50/p95/p99 computed at snapshot time via
                 the one shared `obs.metrics.percentile` implementation.
  SloAggregator  the per-fleet collection: named windows (TTFT / TPOT /
                 queue-wait by default), per-tick gauges with latest +
                 peak tracking, every gauge mirrored as a ``counter``
                 event (``slo.<name>``) on the attached EventStream so
                 the series lands in the Perfetto timeline and the JSONL
                 sink next to the serve/fleet spans.

Units are the CALLER's: the fleet feeds tick-domain values (TTFT in
fleet ticks) so a seeded run snapshots bit-identically on any machine —
the determinism the `fleet.slo.*` obs-gate keys rely on — while a
wall-clock caller can feed seconds through the same windows.

Thread-safety follows the Profiler/ServeStats locked-mutation contract:
every mutation and every snapshot takes the aggregator lock (graftlint
R1 territory — a bench thread may snapshot while the drive loop
records); EventStream mirroring happens outside the lock (the stream
has its own).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, Optional, Tuple

from .events import EventStream
from .metrics import percentile

__all__ = ["SloWindow", "SloAggregator", "DEFAULT_SERIES"]

DEFAULT_SERIES: Tuple[str, ...] = ("ttft", "tpot", "queue_wait")

# the percentile set every window reports — the p99 the after-the-fact
# summaries lacked is first-class here
QUANTILES: Tuple[float, ...] = (50.0, 95.0, 99.0)


class SloWindow:
    """One bounded sliding window: O(1) insert, snapshot-time sort.

    NOT thread-safe on its own — the owning SloAggregator serializes
    access under one lock (a per-window lock would invite lock-order
    inversions between snapshot-all and record)."""

    def __init__(self, maxlen: int) -> None:
        assert maxlen > 0
        self.maxlen = int(maxlen)
        self._buf: Deque[float] = deque(maxlen=self.maxlen)
        self.total = 0               # lifetime inserts (evictions implied)

    def push(self, value: float) -> None:
        self._buf.append(float(value))
        self.total += 1

    @property
    def evicted(self) -> int:
        return self.total - len(self._buf)

    def snapshot(self) -> Dict[str, Any]:
        """count/total/mean + nearest-rank p50/p95/p99 over the CURRENT
        window.  Empty windows report ``None`` (JSON null, the
        RequestSpans convention — never float NaN, which json.dump
        serializes as a token strict parsers reject) plus an explicit
        ``empty`` flag."""
        vals = sorted(self._buf)
        out: Dict[str, Any] = {"count": len(vals), "total": self.total,
                               "window": self.maxlen}
        if not vals:
            out["empty"] = True
            out["mean"] = None
            for q in QUANTILES:
                out[f"p{int(q)}"] = None
            return out
        out["mean"] = round(sum(vals) / len(vals), 6)
        for q in QUANTILES:
            out[f"p{int(q)}"] = round(percentile(vals, q), 6)
        return out


class SloAggregator:
    """Streaming windowed SLO metrics + per-tick gauges for one fleet.

    ``observe(series, value)`` is the O(1) hot path (one lock, one deque
    append); ``gauge(name, value)`` records a per-tick level (latest +
    peak kept) and mirrors it to the event stream as ``slo.<name>``;
    ``snapshot()`` renders the whole live view — the autoscaler's input
    and the bench's banked ``slo`` row."""

    def __init__(self, events: Optional[EventStream] = None, *,
                 window: int = 256,
                 series: Tuple[str, ...] = DEFAULT_SERIES) -> None:
        assert window > 0
        self.events = events
        self.window = int(window)
        self._windows: Dict[str, SloWindow] = {
            name: SloWindow(self.window) for name in series}
        self._gauge_latest: Dict[str, float] = {}
        self._gauge_peak: Dict[str, float] = {}
        self.observations = 0
        self._lock = threading.Lock()

    # -- recording (the drive loop / engine side) ---------------------------

    def observe(self, series: str, value: float) -> None:
        """One sample into a named window (O(1)); unknown series raise —
        a typo'd series name must not silently open a window nothing
        ever snapshots."""
        with self._lock:
            win = self._windows.get(series)
            if win is None:
                raise KeyError(
                    f"unknown SLO series {series!r} (have "
                    f"{sorted(self._windows)}; declare extra series at "
                    "construction)")
            win.push(value)
            self.observations += 1

    def gauge(self, name: str, value: float, *,
              replica: Optional[int] = None) -> None:
        """One per-tick level sample.  ``replica`` scopes per-replica
        gauges (batch occupancy) without colliding across replicas; the
        event-stream mirror carries it as an attr so the Perfetto
        counter track splits per replica."""
        v = float(value)
        key = name if replica is None else f"{name}.r{replica}"
        with self._lock:
            self._gauge_latest[key] = v
            peak = self._gauge_peak.get(key)
            self._gauge_peak[key] = v if peak is None else max(peak, v)
        if self.events is not None:
            if replica is None:
                self.events.counter(f"slo.{name}", v)
            else:
                self.events.counter(f"slo.{name}", v, replica=replica)

    # -- reading (the autoscaler / bench side) ------------------------------

    def window_stat(self, series: str, stat: str) -> Optional[float]:
        """One windowed statistic (e.g. ``("ttft", "p99")``) — the
        autoscaler's per-tick read; None while the window is empty."""
        snap = self.snapshot()["windows"].get(series)
        if snap is None:
            return None
        v = snap.get(stat)
        return None if v is None else float(v)

    def gauge_value(self, name: str, *,
                    peak: bool = False) -> Optional[float]:
        with self._lock:
            d = self._gauge_peak if peak else self._gauge_latest
            v = d.get(name)
            return None if v is None else float(v)

    def snapshot(self) -> Dict[str, Any]:
        """The live view: per-series window stats + gauge latest/peak +
        total observation accounting.  Safe to call from any thread
        while the drive loop records."""
        with self._lock:
            windows = {name: win.snapshot()
                       for name, win in self._windows.items()}
            gauges = {name: {"latest": self._gauge_latest[name],
                             "peak": self._gauge_peak[name]}
                      for name in sorted(self._gauge_latest)}
            n = self.observations
        return {"window": self.window, "observations": n,
                "windows": windows, "gauges": gauges}
