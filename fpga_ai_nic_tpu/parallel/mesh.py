"""Device mesh construction.

The reference's topology layer is a static unidirectional ring of FPGAs
configured by shell script (sw/setup_route.sh:12-40, node n -> (n+1)%N).
On TPU the topology is the ICI fabric; we only choose the logical mesh.
Axes: dp (data), fsdp (ZeRO), tp (tensor), sp (sequence/ring-attention),
pp (pipeline), ep (expert) — the reference has only dp (SURVEY.md §2), the
rest are the north-star generalizations from BASELINE.json.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..utils.config import MeshConfig

AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = cfg.nproc
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    sizes = [cfg.dp, cfg.fsdp, cfg.tp, cfg.sp, cfg.pp, cfg.ep]
    arr = np.array(devices[:n]).reshape(sizes)
    return Mesh(arr, AXES)


def shard_host_batch(batch, mesh: Mesh, spec) -> object:
    """Place a host batch pytree onto the mesh with one PartitionSpec for
    every leaf (the MPI_Scatter analogue, sw/mlp_mpi_example_f32.cpp:
    452-460).  Shared by all trainers."""
    ns = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, ns), batch)


def single_axis_mesh(axis: str = "dp", n: Optional[int] = None,
                     devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or n) devices — the reference's shape."""
    devices = list(devices if devices is not None else jax.devices())
    if n is not None:
        devices = devices[:n]
    return Mesh(np.array(devices), (axis,))


def flat_union_mesh(a: Mesh, b: Mesh, axis: str) -> Mesh:
    """1-D mesh over the UNION of two meshes' device lists (order: a's
    devices first, then b's not already present) — the transfer surface a
    live reshard (parallel.reshard) runs its collective program on.  For a
    shrink the union is just the source mesh flattened; for a grow it adds
    the new devices after the survivors, so every source shard stays on
    its original device when the program starts."""
    devs = list(a.devices.reshape(-1))
    seen = {d.id for d in devs}
    for d in b.devices.reshape(-1):
        if d.id not in seen:
            devs.append(d)
            seen.add(d.id)
    return Mesh(np.array(devs), (axis,))
