"""Multi-axis sharded trainer: dp x tp x sp with ZeRO-1 over dp.

Generalizes `parallel.train.DPTrainer` (the reference's shape: pure DP,
SURVEY.md §2) to the full mesh the BASELINE configs demand:

- tp: params arrive tp-sharded per the model's ``param_specs``; the model
  itself closes its row-parallel sums with ``psum(tp)``.
- sp: batch sequence axis sharded; gradients are partial per sequence shard
  and are summed over sp before the weight update.
- dp: batch axis sharded; the fused ZeRO-1 collective (reduce-scatter ->
  optimizer on owned f32 master shard -> all-gather of updated weights)
  runs over dp, per tp shard.

Master/optimizer state layout: one flat f32 vector per tp shard, sharded
over dp — a global 1-D array of length tp * padded_len with spec
P(("tp", "dp")).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import accum
from . import mesh as mesh_lib
from .. import compat
from .. import optim
from ..ops import fused_update
from ..utils.config import TrainConfig


class ShardedState(NamedTuple):
    params: Any            # tp-sharded working weights (model dtype)
    w_own: jax.Array       # [tp * padded_len] f32, spec P(("tp","dp"))
    opt_state: Any
    step: jax.Array


def _axis_factor(spec_entry, mesh: Mesh) -> int:
    if spec_entry is None:
        return 1
    names = spec_entry if isinstance(spec_entry, tuple) else (spec_entry,)
    f = 1
    for nm in names:
        f *= mesh.shape[nm]
    return f


def local_shape_tree(tree, specs, mesh: Mesh):
    """ShapeDtypeStructs of the per-device shards given PartitionSpecs."""
    def one(leaf, spec):
        shape = list(leaf.shape)
        for d, entry in enumerate(spec):
            shape[d] //= _axis_factor(entry, mesh)
        return jax.ShapeDtypeStruct(tuple(shape), leaf.dtype)

    return jax.tree_util.tree_map(one, tree, specs,
                                  is_leaf=lambda x: isinstance(x, P))


class ShardedTrainer:
    """loss_fn(params_local, batch_local) -> scalar, already closed over the
    model's tp/sp axis names.  batch leaves are [global_batch, global_seq]
    and shard as P(dp, sp)."""

    def __init__(self, loss_fn: Callable, mesh: Mesh, cfg: TrainConfig,
                 param_specs, *, dp_axis: str = "dp", tp_axis: str = "tp",
                 sp_axis: str = "sp", pp_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None,
                 loss_and_grads_fn: Optional[Callable] = None):
        """loss_and_grads_fn(params_local, batch_local) -> (loss, grads):
        an explicit-gradient alternative to jax.grad(loss_fn) — the hook
        for schedules that produce gradients themselves, e.g. the 1F1B
        pipeline (llama.loss_and_grads_pp_1f1b).  The contract matches
        what vma autodiff would produce: dp-varying per-shard grads (the
        trainer's manual dp reduction follows), tp/pp-replicated leaves
        already psum'd.  Mutually exclusive with accum_steps > 1 (1F1B
        already microbatches inside the schedule)."""
        self.loss_fn = loss_fn
        self.loss_and_grads_fn = loss_and_grads_fn
        if loss_and_grads_fn is not None and cfg.accum_steps > 1:
            raise ValueError(
                "loss_and_grads_fn (explicit-gradient schedule) does not "
                "compose with accum_steps > 1 — fold accumulation into "
                "the schedule's num_microbatches instead")
        if cfg.collective.integrity_check:
            raise ValueError(
                "integrity_check is implemented on DPTrainer only (both "
                "value and exact wire tiers ride its step diag); "
                "ShardedTrainer's dp reduce/gather do not thread the "
                "verdicts yet, and a silently ignored flag would be "
                "claimed-but-absent coverage — construct with "
                "integrity_check=False (docs/CHAOS.md 'Exact wire "
                "integrity')")
        self.mesh = mesh
        self.cfg = cfg
        self.param_specs = param_specs
        self.dp, self.tp, self.sp = dp_axis, tp_axis, sp_axis
        self.pp, self.ep = pp_axis, ep_axis
        # flat-master sharding: one distinct f32 shard per (tp[, pp, ep])
        # model shard, split over dp for ZeRO-1
        self._waxes = ((tp_axis,) + ((pp_axis,) if pp_axis else ())
                       + ((ep_axis,) if ep_axis else ()) + (dp_axis,))
        # token/batch sharding: ep splits the batch alongside dp (experts
        # exchange tokens within the ep group via all_to_all)
        self._bspec = P((dp_axis, ep_axis) if ep_axis else dp_axis, sp_axis)
        self.n_dp = mesh.shape[dp_axis]
        self._meta = None

    @property
    def batch_spec(self):
        """PartitionSpec batch leaves are sharded with — the public handle
        for data loaders (`data.ShardedLoader(..., tr.batch_spec)`)."""
        return self._bspec

    # -- init ---------------------------------------------------------------

    def shard_params(self, params):
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            params, self.param_specs,
            is_leaf=lambda x: isinstance(x, P))

    def _ensure_meta(self, params_like) -> None:
        """Derive the flat-master layout from a params tree OR a tree of
        ShapeDtypeStructs (e.g. ``jax.eval_shape(model.init, ...)``) — no
        device work, so a restoring process never materializes throwaway
        params."""
        local = local_shape_tree(params_like, self.param_specs, self.mesh)
        self._meta = fused_update.flat_meta(local, self.cfg.collective,
                                            self.n_dp)
        self.__dict__.pop("step_fn", None)
        self.__dict__.pop("_gather_fn", None)

    def _norm_weight_tables(self):
        """Segment tables for per-element global-norm weights over the
        LOCAL flat master layout: leaves replicated across the non-dp
        master axes get weight 1/replication so the cross-axis psum counts
        each parameter once; sharded leaves (disjoint across ranks) get 1;
        padding gets 0.  Returned as (bounds [n+1], values [n]) so each
        device materializes only ITS dp-chunk of weights (a searchsorted
        over ~n_leaves boundaries), never the full flat vector."""
        meta = self._meta
        assert meta is not None, "call init_state/_ensure_meta first"
        spec_leaves = jax.tree_util.tree_leaves(
            self.param_specs, is_leaf=lambda x: isinstance(x, P))
        assert len(spec_leaves) == len(meta.sizes), (
            len(spec_leaves), len(meta.sizes))
        non_dp = [a for a in self._waxes if a != self.dp]
        bounds, values = [0], []
        for spec, size in zip(spec_leaves, meta.sizes):
            used = set()
            for entry in tuple(spec):
                if entry is None:
                    continue
                used.update(entry if isinstance(entry, tuple) else (entry,))
            rep = 1
            for a in non_dp:
                if a not in used:
                    rep *= self.mesh.shape[a]
            bounds.append(bounds[-1] + size)
            values.append(1.0 / rep)
        if bounds[-1] < meta.padded_len:       # padding segment
            bounds.append(meta.padded_len)
            values.append(0.0)
        return (np.asarray(bounds, np.int32),
                np.asarray(values, np.float32))

    def init_state(self, params) -> ShardedState:
        coll, opt_cfg = self.cfg.collective, self.cfg.optimizer
        params = self.shard_params(params)
        self._ensure_meta(params)
        meta, dp = self._meta, self.dp

        def _init(p):
            w_own, opt_state, _ = fused_update.init_master_shard(
                p, dp, coll, opt_cfg)
            return w_own, opt_state

        w_own, opt_state = jax.jit(jax.shard_map(
            _init, mesh=self.mesh, in_specs=(self.param_specs,),
            out_specs=P(self._waxes), check_vma=False))(params)
        return ShardedState(params=params, w_own=w_own, opt_state=opt_state,
                            step=jnp.zeros((), jnp.int32))

    # -- step ---------------------------------------------------------------

    @functools.cached_property
    def step_fn(self):
        coll, opt_cfg = self.cfg.collective, self.cfg.optimizer
        meta = self._meta
        assert meta is not None, "call init_state first"
        dp, tp, sp, pp, ep = self.dp, self.tp, self.sp, self.pp, self.ep
        n_sp = self.mesh.shape[sp]
        w_spec = P(self._waxes)
        b_spec = self._bspec
        clip_tables = (self._norm_weight_tables()
                       if opt_cfg.clip_norm is not None else None)

        # Phase 1 runs with check_vma=True: differentiating THROUGH
        # collectives (tp psum, sp loss reduction, ring-attention ppermute)
        # is only sound with variance tracking on — with it, the transposes
        # of auto-inserted pvary ops ARE the tp/sp gradient reductions.
        # (check_vma=False silently corrupts those gradients.)
        def shard_update(params, w_own, opt_state, step, batch):
            # dp goes varying BEFORE grad so the dp reduction stays manual
            # (reduce-scatter, fusible, compressible); sp and tp stay as-is
            # so vma-typed autodiff inserts exactly the right psums for
            # sequence shards and tp-replicated params.
            params_v = jax.tree_util.tree_map(
                lambda x: lax.pcast(x, dp, to="varying"), params)
            if self.loss_and_grads_fn is not None:
                loss, grads = self.loss_and_grads_fn(params_v, batch)
            else:
                loss, grads = accum.accumulated_value_and_grad(
                    self.loss_fn, self.cfg.accum_steps)(params_v, batch)
            if not compat.HAS_VMA and pp is not None \
                    and self.mesh.shape[pp] > 1 \
                    and self.loss_and_grads_fn is None:
                # Manual stand-in for the vma pvary transposes this
                # polyfill jaxlib lacks: a pp-REPLICATED leaf (spec omits
                # pp — embeddings on stage 0, the head on stage pp-1) gets
                # per-stage PARTIAL gradients from autodiff (the pipeline
                # loss keeps collectives off the gradient path —
                # from_last_stage), so the stages' master copies would
                # silently diverge without this psum.  pp-SHARDED leaves
                # keep their per-stage gradients.  (The 1F1B
                # loss_and_grads_fn contract already delivers psum'd
                # replicated leaves — _unwiden_grads.)
                def _pp_rep_sum(g, spec):
                    used = set()
                    for entry in tuple(spec):
                        if entry is not None:
                            used.update(entry if isinstance(entry, tuple)
                                        else (entry,))
                    return g if pp in used else lax.psum(g, pp)
                grads = jax.tree_util.tree_map(
                    _pp_rep_sum, grads, self.param_specs,
                    is_leaf=lambda x: isinstance(x, P))
            flat_g, _ = fused_update.flatten_tree(grads, coll, self.n_dp)
            g_own = fused_update.reduce_scatter(flat_g, dp, coll) / self.n_dp
            if opt_cfg.clip_norm is not None:
                # per-element weights de-duplicate tp/pp/ep-REPLICATED
                # leaves in the cross-axis psum (sharded leaves are
                # disjoint, weight 1); built per-device from the tiny
                # segment tables so no full-length constant is embedded
                bounds, values = clip_tables
                c = g_own.shape[0]
                pos = (lax.axis_index(dp) * c
                       + lax.broadcasted_iota(jnp.int32, (c, 1), 0)[:, 0])
                seg = jnp.searchsorted(jnp.asarray(bounds), pos,
                                       side="right") - 1
                w_chunk = jnp.asarray(values)[seg]
                g_own = optim.clip_by_global_norm(
                    opt_cfg, g_own, self._waxes, weights=w_chunk)
            w_new, opt_state2 = optim.apply(opt_cfg, w_own, g_own,
                                            opt_state, step)
            loss = lax.pmean(loss, dp)
            loss = lax.pmean(loss, tp)     # numerically identity; clears vma
            if n_sp == 1:
                loss = lax.pmean(loss, sp)  # loss_fn psums sp when n_sp > 1
            if pp is not None:
                loss = lax.pmean(loss, pp)  # identity: loss_fn psums pp
            if ep is not None:
                loss = lax.pmean(loss, ep)  # identity: loss_fn psums ep
            return w_new, opt_state2, loss

        gather = self._gather_fn       # phase 2: weights back to working copy

        def _step(state: ShardedState, batch):
            w_own, opt_state, loss = jax.shard_map(
                shard_update, mesh=self.mesh,
                in_specs=(self.param_specs, w_spec, w_spec, P(),
                          b_spec),
                out_specs=(w_spec, w_spec, P()),
            )(state.params, state.w_own, state.opt_state, state.step, batch)
            return ShardedState(gather(w_own), w_own, opt_state,
                                state.step + 1), loss

        return jax.jit(_step, donate_argnums=(0,))

    @functools.cached_property
    def _gather_fn(self):
        """Jitted gather of the flat masters into the working params tree —
        phase 2 of the fused step AND the checkpoint-restore
        rematerialization (one definition so they cannot drift; cached so
        repeated params_from_master calls hit jit's cache, invalidated by
        _ensure_meta)."""
        meta, coll, dp = self._meta, self.cfg.collective, self.dp
        assert meta is not None, "call init_state/_ensure_meta first"

        def shard_gather(w_new):
            flat_w = fused_update.all_gather_flat(w_new, dp, coll)
            return fused_update.unflatten_tree(flat_w, meta)

        return jax.jit(jax.shard_map(shard_gather, mesh=self.mesh,
                                     in_specs=P(self._waxes),
                                     out_specs=self.param_specs,
                                     check_vma=False))

    def step(self, state: ShardedState, batch) -> Tuple[ShardedState, jax.Array]:
        return self.step_fn(state, batch)

    # -- restore ------------------------------------------------------------

    def params_from_master(self, w_own: jax.Array):
        """Rematerialize the working params tree from the flat master shards
        (the fused step's gather phase, run standalone — checkpoint-restore
        needs it because checkpoints persist only the masters)."""
        return self._gather_fn(w_own)

    def restore_state(self, restored: dict,
                      params_like=None) -> ShardedState:
        """ShardedState from a Checkpointer.restore() payload.

        The flat layout must be known: either call init_state first, or
        pass ``params_like`` — a params tree or ShapeDtypeStructs (e.g.
        ``jax.eval_shape(functools.partial(model.init, key), cfg)``), which
        sets it with zero device work."""
        if params_like is not None:
            self._ensure_meta(params_like)
        assert self._meta is not None, (
            "flat layout unknown: call init_state first or pass params_like")
        sh = NamedSharding(self.mesh, P(self._waxes))
        w_own = jax.device_put(jnp.asarray(restored["w_own"]), sh)
        opt_state = {k: jax.device_put(jnp.asarray(v), sh)
                     for k, v in restored["opt_state"].items()}
        return ShardedState(
            params=self.params_from_master(w_own), w_own=w_own,
            opt_state=opt_state, step=jnp.asarray(restored["step"]))

    def shard_batch(self, batch):
        return mesh_lib.shard_host_batch(batch, self.mesh, self._bspec)
