"""Multi-host control plane — the MPI layer of the reference, TPU-native.

The reference's distributed backend has two planes (SURVEY.md §2): MPI for
control (`MPI_Init_thread`/`Scatter`/`Bcast`/`Barrier`,
sw/mlp_mpi_example_f32.cpp:195,452-470,688, launched by mpirun with a
`hostlist` side file, sw/README:1-3) and the FPGA ring for data.  On TPU
both collapse into JAX: `jax.distributed.initialize` is the control plane
(coordinator + process ids from flags or the environment — TPU pod
environments autoconfigure), and ICI/DCN collectives are the data plane.

What this module adds over raw jax.distributed:
- `initialize()` — idempotent, env-var-driven init (the mpirun/hostlist
  ritual as one call), no-op on single process.
- `local_batch_to_global()` — each process feeds its PROCESS-LOCAL batch
  shard and gets the global sharded array (the per-rank MPI_Scatter that
  the loaders sit on top of).
- `barrier()` — MPI_Barrier.

Every trainer in `parallel/` already takes an explicit Mesh, and
`make_mesh` builds over `jax.devices()` — which is the GLOBAL device list
after initialize() — so multi-host scaling is: initialize(); make_mesh
(global sizes); feed with local_batch_to_global.  The 8-device virtual CPU
mesh exercises the same code paths single-process (num_processes=1).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[list] = None) -> None:
    """Idempotent `jax.distributed.initialize` with env fallbacks.

    Resolution order per field: explicit arg -> JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID env -> platform autodetection when
    a multi-host TPU environment is detected (no-arg
    jax.distributed.initialize; libtpu publishes worker topology via
    TPU_WORKER_HOSTNAMES / MEGASCALE_COORDINATOR_ADDRESS on pods).
    Plain single-process runs (no args, no env, no pod markers) are a
    no-op, so the same training script runs unmodified on a laptop, one
    host, or a pod — unlike the reference, which hard-requires mpirun +
    hostlist even for one node.
    """
    global _initialized
    if _initialized:
        return
    coord = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "0") or 0) or None
    pid = process_id if process_id is not None else (
        int(os.environ["JAX_PROCESS_ID"])
        if "JAX_PROCESS_ID" in os.environ else None)
    if coord is None and nproc in (None, 1):
        if _on_multihost_tpu():
            # pod slice: let jax autodetect coordinator + process ids
            jax.distributed.initialize()
            _initialized = True
        return                       # single-process: nothing to coordinate
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid,
                               local_device_ids=local_device_ids)
    _initialized = True


def _on_multihost_tpu() -> bool:
    """Detect a multi-worker TPU environment from env alone (never probes
    jax — backend queries can hang on a wedged transport; same rule as
    tests/conftest.py)."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) > 1:
        return True
    return bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def process_info() -> dict:
    """(rank, size) readback — the reference prints these from MPI
    (sw/mlp_mpi_example_f32.cpp:300-302)."""
    return {"process_id": jax.process_index(),
            "num_processes": jax.process_count(),
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices())}


def local_batch_to_global(batch: Any, mesh: Mesh, spec) -> Any:
    """Assemble global sharded arrays from PROCESS-LOCAL host data.

    Each process passes only the rows it loaded (global_batch /
    num_processes of them); the result behaves like one global array laid
    out per `spec` — the MPI_Scatter analogue
    (sw/mlp_mpi_example_f32.cpp:452-460), except no root process ever
    materializes the full batch.  Single-process this degrades to a plain
    device_put, so loaders can use it unconditionally.
    """
    ns = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(lambda x: jax.device_put(x, ns), batch)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(ns, np.asarray(x)),
        batch)


def barrier(name: str = "barrier") -> None:
    """Block until every process arrives (MPI_Barrier,
    sw/mlp_mpi_example_f32.cpp:688)."""
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)
