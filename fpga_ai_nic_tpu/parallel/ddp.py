"""DDP trainer: bucketed gradient all-reduce + replicated optimizer.

BASELINE.json config 4 ("BERT-base DP bucketed ring all-reduce") is this
shape: plain data parallelism where *gradients* are all-reduced (bucketed,
in backward order — `ops.bucketed`) and every device runs the full optimizer
on a replicated f32 master copy.  It is the un-fused counterpart of
`parallel.train.DPTrainer` (which reduce-scatters and gathers updated
weights, ZeRO-1); the reference's own dataflow is the fused one, but its
host API — one all-reduce per layer's gradient buffer, optimizer elsewhere
(sw/mlp_mpi_example_f32.cpp:753-756 with the host optimizer calls intact
rather than commented out) — is exactly this trainer.

Master weights / optimizer state: one flat replicated f32 vector, updated
from the bucketed gradient means; working params are re-materialized in the
model dtype each step (same cast discipline as the fused path).

Memory: every device holds the FULL f32 master + optimizer state + flat
gradient — simple and right for models that fit comfortably (BERT-base on
any modern chip).  When master+state pressure matters, prefer
`parallel.train.DPTrainer` (ZeRO-1: masters sharded over dp, ~1/n the
state) or `parallel.fsdp.FSDPTrainer` (ZeRO-3: params sharded too).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import accum
from . import mesh as mesh_lib
from .. import optim
from ..ops import bucketed, fused_update
from ..utils.config import CollectiveConfig, TrainConfig


class DDPState(NamedTuple):
    params: Any            # replicated working weights (model dtype)
    w_master: jax.Array    # replicated flat f32 master vector
    opt_state: Any         # replicated flat optimizer state
    step: jax.Array


def _unbucketed_meta(coll: CollectiveConfig):
    """Flat-vector layout for the master copy: no per-device chunking, so
    pad multiple is 1 (a CollectiveConfig with compression=None, n=1)."""
    return CollectiveConfig(impl="xla", bucket_elems=coll.bucket_elems)


class DDPTrainer:
    """loss_fn(params, batch) -> scalar; batch leaves shard over dp."""

    def __init__(self, loss_fn: Callable, mesh: Mesh, cfg: TrainConfig,
                 axis_name: str = "dp"):
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.cfg = cfg
        self.ax = axis_name
        self.n = mesh.shape[axis_name]
        if cfg.collective.integrity_check:
            raise ValueError(
                "integrity_check is implemented on DPTrainer only (both "
                "value and exact wire tiers ride its step diag); the "
                "bucketed/queued DDP reduces do not thread the verdicts "
                "yet, and a silently ignored flag would be claimed-but-"
                "absent coverage — construct with integrity_check=False "
                "(docs/CHAOS.md 'Exact wire integrity')")
        self._meta = None
        self._plan = None
        # codec="auto": the tuner owns codec / bucket_elems / depth /
        # topology, resolved once at the first _ensure_meta (same
        # contract as DPTrainer) — in THIS trainer bucket_elems is the
        # knob that actually bites (it sizes the bucketed collective
        # plan, non-uniform last bucket included)
        self._tuned_plan = None

    # -- init ---------------------------------------------------------------

    def _resolve_auto(self, params_like) -> None:
        from .. import tune as tune_lib
        cfg, plan, _calib = tune_lib.resolve_train_config(
            self.cfg, self.n, params_like)
        if plan is None:
            return
        self.cfg = cfg
        self._tuned_plan = plan

    def _ensure_meta(self, params_like) -> None:
        """Flat layout + bucket plan from a params tree or ShapeDtypeStructs
        (no device work — restore paths use jax.eval_shape output)."""
        self._resolve_auto(params_like)
        coll = self.cfg.collective
        self._meta = fused_update.flat_meta(params_like,
                                            _unbucketed_meta(coll), 1)
        self._plan = bucketed.plan_buckets(params_like, coll, self.n)
        self.__dict__.pop("step_fn", None)

    def obs_static_metrics(self) -> dict:
        """Telemetry statics for the bucketed trainer: per-plan wire
        accounting (the flit-counter arithmetic summed over buckets) plus
        the banked tuning decision when codec='auto' resolved one."""
        plan, coll = self._plan, self.cfg.collective
        assert plan is not None, "call init_state first"
        codec = fused_update.resolve_codec(coll)
        d = {"n_devices": self.n, "impl": coll.impl,
             "topology": coll.topology,
             "n_buckets": len(plan.buckets),
             "bucket_elems": coll.bucket_elems,
             "wire_bytes_per_allreduce":
                 bucketed.bucket_wire_bytes(plan, self.n, coll),
             "raw_bytes_per_allreduce": sum(
                 fused_update.wire_bytes_for(coll, b.padded_len, self.n,
                                             codec=None)
                 for b in plan.buckets)}
        if codec is not None:
            d["codec"] = codec.name
        if self._tuned_plan is not None:
            d["tune"] = self._tuned_plan.describe()
        return d

    def init_state(self, params) -> DDPState:
        self._ensure_meta(params)    # resolves codec='auto' first
        coll, opt_cfg = self.cfg.collective, self.cfg.optimizer

        def _init(p):
            flat, _ = fused_update.flatten_tree(p, _unbucketed_meta(coll), 1)
            return flat, optim.init_state(opt_cfg, flat.shape[0])

        w_master, opt_state = jax.jit(_init)(params)
        return DDPState(params=params, w_master=w_master,
                        opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    def restore_state(self, restored: dict, params_like=None) -> DDPState:
        """DDPState from a Checkpointer.restore() payload (masters only —
        working params are rematerialized).  Layout must be known: call
        init_state first or pass params_like (tree or ShapeDtypeStructs)."""
        if params_like is not None:
            self._ensure_meta(params_like)
        assert self._meta is not None, (
            "flat layout unknown: call init_state first or pass params_like")
        meta = self._meta
        sh = NamedSharding(self.mesh, P())
        w_master = jax.device_put(jnp.asarray(restored["w_master"]), sh)
        opt_state = {k: jax.device_put(jnp.asarray(v), sh)
                     for k, v in restored["opt_state"].items()}
        params = jax.jit(
            lambda w: fused_update.unflatten_tree(w, meta))(w_master)
        return DDPState(params=params, w_master=w_master,
                        opt_state=opt_state,
                        step=jnp.asarray(restored["step"]))

    # -- step ---------------------------------------------------------------

    @functools.cached_property
    def step_fn(self):
        coll, opt_cfg = self.cfg.collective, self.cfg.optimizer
        meta, plan = self._meta, self._plan
        assert meta is not None, "call init_state first"
        ax = self.ax

        # Phase 1 (check_vma=True): grads + bucketed all-reduce.  The ring
        # collective's result is replicated in value but vma-typed varying
        # (there is no varying->invariant cast), so the mean gradient is
        # handed to phase 2 through a P(ax) output — physically each
        # device's own copy, no extra bytes moved.
        def shard_grads(params, batch):
            # dp-varying before grad: keeps the dp reduction manual (the
            # bucketed collective below), not an autodiff-inserted psum.
            params_v = jax.tree_util.tree_map(
                lambda x: lax.pcast(x, ax, to="varying"), params)
            loss, grads = accum.accumulated_value_and_grad(
                self.loss_fn, self.cfg.accum_steps)(params_v, batch)
            # flat f32 end to end: the dp-mean gradient must NOT round
            # through the model dtype on its way to the f32 master update
            flat_g = bucketed.all_reduce_bucketed_flat(grads, ax, coll, plan)
            if coll.impl == "xla":        # psum output is invariant-typed
                flat_g = lax.pcast(flat_g, ax, to="varying")
            return flat_g, lax.pmean(loss, ax)

        # Phase 2 (no autodiff): replicated optimizer on the flat master.
        def shard_update(flat_g, w_master, opt_state, step):
            flat_g = optim.clip_by_global_norm(opt_cfg, flat_g)
            w_new, opt_state2 = optim.apply(opt_cfg, w_master, flat_g,
                                            opt_state, step)
            params2 = fused_update.unflatten_tree(w_new, meta)
            return params2, w_new, opt_state2

        def _step(state: DDPState, batch):
            flat_g, loss = jax.shard_map(
                shard_grads, mesh=self.mesh, in_specs=(P(), P(ax)),
                out_specs=(P(ax), P()),
            )(state.params, batch)
            params, w_master, opt_state = jax.shard_map(
                shard_update, mesh=self.mesh,
                in_specs=(P(ax), P(), P(), P()),
                out_specs=(P(), P(), P()), check_vma=False,
            )(flat_g, state.w_master, state.opt_state, state.step)
            return DDPState(params, w_master, opt_state,
                            state.step + 1), loss

        return jax.jit(_step, donate_argnums=(0,))

    def step(self, state: DDPState, batch) -> Tuple[DDPState, jax.Array]:
        return self.step_fn(state, batch)

    # -- data ---------------------------------------------------------------

    @property
    def batch_spec(self):
        """PartitionSpec for batch leaves (same public handle as the other
        trainers)."""
        return P(self.ax)

    def shard_batch(self, batch):
        return mesh_lib.shard_host_batch(batch, self.mesh, self.batch_spec)
