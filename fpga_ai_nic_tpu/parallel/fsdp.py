"""Fully-sharded data parallelism (ZeRO-3) over the ``fsdp`` mesh axis.

The reference's fused engine is ZeRO-1: optimizer state + master weights
sharded, working weights replicated by the all-gather of updated weights
(hw/all_reduce.sv FORWARD_OUTPUT; `parallel.train.DPTrainer`).  ZeRO-3
drops the replicated working copy too: each device persistently holds ONLY
its flat f32 master shard [L/n] and optimizer shard — full parameters exist
transiently inside the step, materialized by an all-gather-on-use.

TPU-first shape of the step (one jitted ``shard_map`` over fsdp):

    flat    = all_gather(w_own)            # transient full vector
    params  = unflatten(flat)              # model dtype views
    loss    = loss_fn(params, batch_shard)
    g_own   = grad wrt w_own               # == psum_scatter(dL/dflat):
                                           #    the TRANSPOSE of all_gather
                                           #    IS the reduce-scatter, so
                                           #    ZeRO-3's gradient collective
                                           #    falls out of autodiff
    w_own'  = opt(w_own, g_own / n)        # f32 master update, same as ZeRO-1

No gather of updated weights happens: the next step's all-gather reads the
new shards.  Peak memory = master shard + one transient full copy during
fwd/bwd (XLA donates/reuses the gather buffer), vs ZeRO-1's persistent
replicated params + transient copies.

The gather runs in f32 (master precision): gathering in model dtype would
round the master before the forward AND make the transposed reduce-scatter
accumulate in bf16; the 2x wire cost vs a bf16 gather is the price of
exactness, and per-layer/bf16 gathering composes later via param_specs.

Parity contract (tests/test_fsdp.py): identical losses to DPTrainer on the
same model/batch/optimizer, since both compute mean-reduced gradients into
an f32 master — only the collective schedule differs.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import accum
from . import mesh as mesh_lib
from .. import optim
from ..obs import metrics as obs_metrics
from ..ops import fused_update
from ..utils.config import OptimizerSpec, TrainConfig


class FSDPState(NamedTuple):
    w_own: jax.Array       # this device's f32 master shard [L/n]
    opt_state: Any         # sharded optimizer state
    step: jax.Array
    # error-feedback residual of the compression codec (per-device full
    # [L_pad] dropped-gradient carry; None without an EF codec) — same
    # contract as parallel.train.TrainState.codec_state
    codec_state: Any = None


class FSDPTrainer:
    """loss_fn(params, batch) -> scalar over a 1-D ``fsdp`` mesh axis.

    Batch leaves shard over fsdp (ZeRO-3 is still data parallelism); params
    never exist replicated outside the step.
    """

    def __init__(self, loss_fn: Callable, mesh: Mesh, cfg: TrainConfig,
                 axis_name: str = "fsdp"):
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.cfg = cfg
        self.ax = axis_name
        self.n = mesh.shape[axis_name]
        self._meta = None
        # codec="auto" resolves at the first _ensure_meta — same
        # autotune contract as DPTrainer (_resolve_auto below)
        self._tuned_plan = None
        self._tune_calib = None
        self._set_codec_flags()
        if cfg.collective.fused_optimizer \
                and cfg.optimizer.clip_norm is not None:
            raise ValueError(
                "fused_optimizer cannot honor clip_norm (same contract "
                "as DPTrainer: no barrier between reduce and update)")
        if cfg.collective.integrity_check:
            raise ValueError(
                "integrity_check is implemented on DPTrainer only (both "
                "value and exact wire tiers ride its step diag); "
                "FSDPTrainer does not thread the verdicts yet, and a "
                "silently ignored flag would be claimed-but-absent "
                "coverage — construct with integrity_check=False "
                "(docs/CHAOS.md 'Exact wire integrity')")

    def _set_codec_flags(self) -> None:
        coll = self.cfg.collective
        from .. import tune as tune_lib
        if tune_lib.needs_autotune(coll):
            self._codec, self._ef = None, False
            return
        codec = fused_update.resolve_codec(coll)
        self._codec = codec
        self._ef = (coll.impl == "ring" and codec is not None
                    and codec.error_feedback)

    def _resolve_auto(self, params_like) -> None:
        """One-shot autotune resolution (no-op for concrete configs) —
        deterministic in the banked artifacts; the plan is banked into
        obs_static_metrics().  Shared implementation:
        tune.resolve_train_config."""
        from .. import tune as tune_lib
        cfg, plan, calib = tune_lib.resolve_train_config(
            self.cfg, self.n, params_like)
        if plan is None:
            return
        self.cfg = cfg
        self._tuned_plan, self._tune_calib = plan, calib
        self._set_codec_flags()

    # -- init ---------------------------------------------------------------

    def _ensure_meta(self, params_like) -> None:
        """Flat layout from a params tree or ShapeDtypeStructs (no device
        work — same restore contract as the other trainers)."""
        self._resolve_auto(params_like)
        self._meta = fused_update.flat_meta(params_like,
                                            self.cfg.collective, self.n)
        if self._tuned_plan is not None \
                and self._tuned_plan.payload_elems != self._meta.padded_len:
            # exact wire declaration needs the padded length, priced
            # under the SAME calibration/slice plan as the argmin (see
            # DPTrainer._ensure_meta)
            from .. import tune as tune_lib
            self._tuned_plan = tune_lib.rescore(
                self._tuned_plan, self._meta.padded_len,
                calibration=self._tune_calib,
                slice_elems=self.cfg.collective.slice_elems)
        self.__dict__.pop("step_fn", None)

    @property
    def batch_spec(self):
        """PartitionSpec for batch leaves (same public handle as the other
        trainers)."""
        return P(self.ax)

    def init_state(self, params) -> FSDPState:
        """Shard replicated init params into the persistent master shards
        (the only copy that survives the call — the ZeRO-3 memory claim)."""
        self._ensure_meta(params)    # resolves codec='auto' first
        coll, opt_cfg = self.cfg.collective, self.cfg.optimizer

        def _init(p):
            w_own, opt_state, _ = fused_update.init_master_shard(
                p, self.ax, coll, opt_cfg)
            return w_own, opt_state

        w_own, opt_state = jax.jit(jax.shard_map(
            _init, mesh=self.mesh, in_specs=P(),
            out_specs=P(self.ax), check_vma=False))(params)
        return FSDPState(w_own=w_own, opt_state=opt_state,
                         step=jnp.zeros((), jnp.int32),
                         codec_state=self._init_codec_state())

    def _init_codec_state(self):
        """Zeroed error-feedback residuals, [n * L_pad] sharded over the
        axis (each device's own full-gradient residual)."""
        if not self._ef:
            return None
        return jax.device_put(
            jnp.zeros((self.n * self._meta.padded_len,), jnp.float32),
            NamedSharding(self.mesh, P(self.ax)))

    # -- step ---------------------------------------------------------------

    @functools.cached_property
    def step_fn(self):
        coll, opt_cfg = self.cfg.collective, self.cfg.optimizer
        meta = self._meta
        assert meta is not None, "call init_state first"
        ax, n = self.ax, self.n
        codec, ef = self._codec, self._ef
        # trace-time metrics gate (obs.metrics compiled-out contract)
        obs_on = self.cfg.obs_metrics

        def shard_step_ef(w_own, opt_state, step, batch, resid):
            # Error-feedback variant: the gradient collective is explicit
            # (not the gather's autodiff transpose) so the full local
            # cotangent can be compensated and re-quantized BEFORE the
            # per-hop-compressed reduce-scatter.  The forward gather is
            # unchanged (quantized masters under a compressed ring —
            # straight-through semantics); memory-wise this materializes
            # the full flat cotangent, which the transposed path also
            # produced transiently before its reduce-scatter.
            flat = fused_update.all_gather_flat(w_own, ax, coll)

            def flat_loss(f):
                params = fused_update.unflatten_tree(f, meta)
                return accum.accumulated_loss(
                    self.loss_fn, self.cfg.accum_steps)(params, batch)

            loss, g_flat = jax.value_and_grad(flat_loss)(flat)
            g_wire, new_resid = fused_update.error_feedback_encode(
                codec, g_flat, resid)
            m = {}
            if obs_on:
                # g_wire IS roundtrip(g_flat + resid): declared-vs-
                # observed error comes free of an extra roundtrip
                m["codec_obs_rel_err"] = lax.pmax(
                    obs_metrics.codec_observed_error(
                        codec, g_flat + resid, quantized=g_wire), ax)
                m["ef_resid_norm"] = obs_metrics.l2_norm(new_resid, ax)
            if coll.fused_optimizer:
                # decode+accumulate+update in one pass (see DPTrainer)
                g_sum, w_new, opt_state2 = \
                    fused_update.reduce_scatter_update(
                        g_wire, w_own, opt_state, step, ax, coll, opt_cfg)
                if obs_on:
                    m["grad_norm"] = obs_metrics.l2_norm(g_sum / n, ax)
            else:
                g_own = fused_update.reduce_scatter(g_wire, ax, coll)
                g_own = g_own / n
                if obs_on:
                    m["grad_norm"] = obs_metrics.l2_norm(g_own, ax)
                g_own = optim.clip_by_global_norm(opt_cfg, g_own, (ax,))
                w_new, opt_state2 = optim.apply(opt_cfg, w_own, g_own,
                                                opt_state, step)
            loss_m = lax.pmean(loss, ax)
            if obs_on:
                m["loss"] = loss_m
            return (w_new, opt_state2, loss_m, new_resid) + (
                (m,) if obs_on else ())

        def shard_step(w_own, opt_state, step, batch):
            def shard_loss(w_own):
                # all-gather-on-use; its transpose is the reduce-scatter
                # that lands gradients on the owning shard.  impl="xla"
                # relies on jax's automatic all_gather transpose; the
                # explicit ring (and the BFP wire format with it) needs the
                # declared VJP — forward gathers (possibly quantized)
                # masters, backward is the per-hop-compressed ring
                # reduce-scatter (ops.fused_update.all_gather_flat_vjp).
                gather = (fused_update.all_gather_flat if coll.impl == "xla"
                          else fused_update.all_gather_flat_vjp)
                flat = gather(w_own, ax, coll)
                params = fused_update.unflatten_tree(flat, meta)
                return accum.accumulated_loss(
                    self.loss_fn, self.cfg.accum_steps)(params, batch)

            loss, g_sum = jax.value_and_grad(shard_loss)(w_own)
            g_own = g_sum / n
            m = {}
            if obs_on:
                # the codec path here is the gather's declared VJP — no
                # explicit encode to compare against, so this variant
                # carries the norm/loss metrics only
                m["grad_norm"] = obs_metrics.l2_norm(g_own, ax)
            if coll.fused_optimizer:
                # the gather transpose already landed the summed shard;
                # the update is the shared fused formula (same hyper
                # vector / golden twin as the in-kernel path)
                w_new, opt_state2 = optim.fused_apply_flat(
                    OptimizerSpec.from_optimizer(opt_cfg), w_own, g_sum,
                    opt_state, optim.fused_hyperparams(opt_cfg, step), n)
            else:
                g_own = optim.clip_by_global_norm(opt_cfg, g_own, (ax,))
                w_new, opt_state2 = optim.apply(opt_cfg, w_own, g_own,
                                                opt_state, step)
            loss_m = lax.pmean(loss, ax)
            if obs_on:
                m["loss"] = loss_m
            return (w_new, opt_state2, loss_m) + ((m,) if obs_on else ())

        def _step(state: FSDPState, batch):
            m_specs = (P(),) if obs_on else ()
            if ef:
                res = jax.shard_map(
                    shard_step_ef, mesh=self.mesh,
                    in_specs=(P(ax), P(ax), P(), P(ax), P(ax)),
                    out_specs=(P(ax), P(ax), P(), P(ax)) + m_specs,
                )(state.w_own, state.opt_state, state.step, batch,
                  state.codec_state)
                w_own, opt_state, loss, codec_state = res[:4]
            else:
                res = jax.shard_map(
                    shard_step, mesh=self.mesh,
                    in_specs=(P(ax), P(ax), P(), P(ax)),
                    out_specs=(P(ax), P(ax), P()) + m_specs,
                )(state.w_own, state.opt_state, state.step, batch)
                w_own, opt_state, loss = res[:3]
                codec_state = state.codec_state
            if obs_on:
                loss = obs_metrics.tap(loss, res[-1])
            return FSDPState(w_own, opt_state, state.step + 1,
                             codec_state), loss

        return jax.jit(_step, donate_argnums=(0,))

    def step(self, state: FSDPState, batch) -> Tuple[FSDPState, jax.Array]:
        return self.step_fn(state, batch)

    def obs_static_metrics(self) -> dict:
        """Same telemetry statics contract (and keys) as DPTrainer.
        ZeRO-3's per-step wire volume is one forward all-gather plus one
        backward reduce-scatter — byte-identical to the single all-reduce
        the 2*(n-1)/n formula accounts, so the same arithmetic applies."""
        meta = self._meta
        assert meta is not None, "call init_state first"
        coll = self.cfg.collective
        d = {"padded_len": meta.padded_len, "n_devices": self.n,
             "impl": coll.impl, "topology": coll.topology}
        d.update(obs_metrics.codec_static_metrics(self._codec,
                                                  meta.padded_len))
        d["wire_bytes_per_allreduce"] = fused_update.wire_bytes_for(
            coll, meta.padded_len, self.n)
        d["raw_bytes_per_allreduce"] = fused_update.wire_bytes_for(
            coll, meta.padded_len, self.n, codec=None)
        if self._tuned_plan is not None:
            d["tune"] = self._tuned_plan.describe()
        return d

    # -- materialization (eval / checkpoint restore) ------------------------

    def gathered_params(self, state: FSDPState):
        """Replicated params pytree from the master shards — for eval or
        export only; training never materializes this persistently."""
        meta, coll, ax = self._meta, self.cfg.collective, self.ax
        assert meta is not None, "call init_state first"

        def _gather(w):
            return fused_update.unflatten_tree(
                fused_update.all_gather_flat(w, ax, coll), meta)

        return jax.jit(jax.shard_map(
            _gather, mesh=self.mesh, in_specs=P(ax), out_specs=P(),
            check_vma=False))(state.w_own)

    def restore_state(self, restored: dict,
                      params_like=None) -> FSDPState:
        """FSDPState from a Checkpointer.restore() payload (same layout the
        ZeRO-1 trainers persist: flat master + opt shards).  Layout must be
        known: call init_state first or pass params_like (a params tree or
        jax.eval_shape output — zero device work), same contract as every
        other trainer."""
        if params_like is not None:
            self._ensure_meta(params_like)
        assert self._meta is not None, (
            "flat layout unknown: call init_state first or pass params_like")
        # mesh-shape-portable: re-pad the live elements onto THIS mesh's
        # flat layout (see fused_update.repad_flat / DPTrainer)
        sh = NamedSharding(self.mesh, P(self.ax))
        return FSDPState(
            w_own=jax.device_put(
                fused_update.repad_flat(restored["w_own"], self._meta), sh),
            opt_state={
                k: jax.device_put(
                    fused_update.repad_flat(v, self._meta), sh)
                for k, v in restored["opt_state"].items()},
            step=jnp.asarray(restored["step"]),
            codec_state=self._init_codec_state())

    # -- live resharding (parallel.reshard) ---------------------------------

    def reshard_leaves(self, state: FSDPState) -> dict:
        """Flat-vector leaves for a live mesh move — the shared transfer
        naming (reshard.pack_state_leaves); ZeRO-3 has no replicated
        params to rebuild, the shards ARE the state."""
        from . import reshard as reshard_lib
        return reshard_lib.pack_state_leaves(state.w_own, state.opt_state)

    def state_from_reshard(self, leaves: dict, step,
                           codec_state) -> FSDPState:
        from . import reshard as reshard_lib
        w_own, opt_state = reshard_lib.split_state_leaves(leaves)
        return FSDPState(w_own=w_own, opt_state=opt_state,
                         step=jnp.asarray(step), codec_state=codec_state)

    # -- data ---------------------------------------------------------------

    def shard_batch(self, batch):
        return mesh_lib.shard_host_batch(batch, self.mesh, self.batch_spec)
